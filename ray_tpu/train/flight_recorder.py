"""Flight recorder: always-cheap, ring-buffered per-step telemetry for
the training hot loop.

The runtime can time tasks, spans, and object transfers — but none of
that decomposes a slow TRAINING STEP. This module supplies the missing
layer: a `StepProfiler` the loop wraps around each step that records a
per-step wall-time breakdown (data-wait, compute, collective, checkpoint,
other), compile/retrace counts, throughput, and an MFU estimate, into a
fixed-size ring buffer. Recording is a handful of `perf_counter` reads
and dict writes per step — cheap enough to leave on in production
(bench_obs.py pins the overhead; BENCH_OBS.json).

Per-rank records ride the existing report/poll stream back to the
trainer, which computes CROSS-RANK SKEW and names the slowest rank
(straggler attribution in `Result.metrics_history` and the
`train_step_skew_seconds` metric) — the rank-level visibility The Big
Send-off (arXiv:2409.05208-adjacent, PAPERS.md) identifies as the root
of most large-scale collective slowdowns, and the per-phase overlap
ledger T3 (arXiv:2401.16677) shows is the prerequisite for optimizing
compute/collective overlap. Aggregates also flush through the GCS
metrics stream (rank-tagged), powering `rt top` and the Grafana panels.

Usage (inside a train_loop_per_worker):

    from ray_tpu import train

    prof = train.StepProfiler(flops_per_step=model_flops)
    prof.watch_jit(train_step)              # compile/retrace counting
    prof.attach_feed(it)                    # data-wait from FeedStats
    for batch in it:
        with prof.step(tokens=batch_tokens) as s:
            with prof.phase("compute"):
                state, loss = train_step(state, batch)
            s.fence(loss)                   # block_until_ready boundary
        train.report({"loss": float(loss)}) # step records ride along

Collective time needs no annotation: the eager collective wrappers
(util/collective) report op wall time into the active step through an
observer hook. Phases not covered by an explicit `phase(...)`/`fence`
land in "other_s", so the breakdown always sums to the step wall time.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ray_tpu._private import chaos
from ray_tpu.util import journal

#: Phase keys every record carries (plus "other_s" for the remainder).
PHASES = ("data", "compute", "collective", "checkpoint")

# Dense peak-flops table (bf16, per chip) for the MFU estimate; matched
# by substring against jax's device_kind. Overridable (and extendable to
# unlisted hardware) via RT_PEAK_FLOPS_PER_S.
_PEAK_FLOPS_BY_KIND = (
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5litepod", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

_tls = threading.local()  # .step = the thread's in-flight _StepHandle

_metrics_lock = threading.Lock()
_metrics: Optional[Dict] = None
_collective_hooked = False


def peak_flops_per_s() -> Optional[float]:
    """Per-device peak flops/s for MFU: RT_PEAK_FLOPS_PER_S env override,
    else the device-kind table; None when unknown (CPU test meshes)."""
    env = os.environ.get("RT_PEAK_FLOPS_PER_S")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # rtlint: disable=RT007 — no backend: no MFU
        return None
    for sub, flops in _PEAK_FLOPS_BY_KIND:
        if sub in kind:
            return flops
    return None


def _recorder_metrics() -> Dict:
    """Process-wide recorder metrics (created on first StepProfiler, not
    import, so importing train/ never starts the metrics flusher)."""
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util import metrics as m

            _metrics = {
                "wall": m.get_or_create(
                    m.Histogram, "train_step_wall_seconds",
                    "Training step wall time per rank.",
                    boundaries=m.LATENCY_BOUNDARIES, tag_keys=("rank",),
                ),
                "phase": m.get_or_create(
                    m.Counter, "train_step_phase_seconds_total",
                    "Cumulative step wall time by phase "
                    "(data/compute/collective/checkpoint/other) and rank.",
                    tag_keys=("rank", "phase"),
                ),
                "compiles": m.get_or_create(
                    m.Counter, "train_step_compiles_total",
                    "Jit compilations observed during training steps "
                    "(steady-state steps should never compile).",
                    tag_keys=("rank",),
                ),
                "throughput": m.get_or_create(
                    m.Gauge, "train_tokens_per_s",
                    "Tokens (or samples) per second of the latest step.",
                    tag_keys=("rank",),
                ),
                "mfu": m.get_or_create(
                    m.Gauge, "train_step_mfu",
                    "Model-flops utilization estimate of the latest step.",
                    tag_keys=("rank",),
                ),
            }
        return _metrics


def note_phase(name: str, seconds: float) -> None:
    """Attribute `seconds` to phase `name` of this thread's in-flight
    step. No-op (two attribute reads) when no step is open — safe to
    call from library code unconditionally."""
    step = getattr(_tls, "step", None)
    if step is not None:
        step._phases[name] = step._phases.get(name, 0.0) + seconds


def _collective_observer(op_name: str, seconds: float,
                         info: Optional[dict] = None) -> None:
    # `info` carries {tier, algo, bytes, ...} from the collective layer;
    # step attribution only needs the wall time, but accepting it keeps
    # this on the three-arg observer protocol (collective.py calls with
    # info when the group records one).
    note_phase("collective", seconds)


def _ensure_collective_hook() -> None:
    global _collective_hooked
    if _collective_hooked:
        return
    _collective_hooked = True
    from ray_tpu.util.collective import collective as col

    col.add_op_observer(_collective_observer)


class _StepHandle:
    """The object `with prof.step() as s:` yields — the in-flight step's
    accumulator AND context manager (class-based, not @contextmanager:
    this runs once per training step). `fence(tree)` closes the
    async-dispatch gap: it blocks until the device work the step issued
    is done and attributes the block time to "compute" (without a fence,
    device time still inside the XLA queue at step exit lands in the
    NEXT step's wall)."""

    __slots__ = ("_prof", "_phases", "tokens", "samples", "_t0", "_prev")

    def __init__(self, prof, tokens=None, samples=None):
        self._prof = prof
        self._phases: Dict[str, float] = {}
        self.tokens = tokens
        self.samples = samples
        self._t0 = 0.0
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_tls, "step", None)
        _tls.step = self
        self._t0 = time.perf_counter()
        # Chaos straggler injection sleeps INSIDE the timed window — the
        # recorder must see the slowness it models (as other_s: a real
        # straggler's lost time is exactly the un-attributed kind).
        delay = chaos.take_step_delay()
        if delay:
            time.sleep(delay)
        return self

    def __exit__(self, *exc):
        wall = time.perf_counter() - self._t0
        _tls.step = self._prev
        self._prof._finish(self, wall)
        return False

    def fence(self, tree: Any) -> None:
        t0 = time.perf_counter()
        block = getattr(tree, "block_until_ready", None)
        if block is not None:  # single array: skip the tree walk
            block()
        else:
            import jax

            jax.block_until_ready(tree)
        self._phases["compute"] = (
            self._phases.get("compute", 0.0) + time.perf_counter() - t0
        )


class _PhaseTimer:
    """`with prof.phase(name):` — times the block into the active step."""

    __slots__ = ("_name", "_t0")

    def __init__(self, name: str):
        self._name = name
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        note_phase(self._name, time.perf_counter() - self._t0)
        return False


class StepProfiler:
    """Ring-buffered per-step recorder (one per rank, one per loop).

    ring: records kept in memory (old steps fall off — flight-recorder
      discipline: always on, bounded, overwrite-oldest).
    flops_per_step / peak_flops: MFU estimate inputs; peak defaults to
      the device table (RT_PEAK_FLOPS_PER_S override). No flops → no MFU.
    rank: tag for the exported metrics; defaults to the active train
      session's world rank (standalone use: pass explicitly).
    emit_metrics: also observe per-step aggregates into rank-tagged
      util.metrics series (what `rt top`/Grafana read). Ring recording
      itself never touches the metrics path.

    Thread discipline: step()/phase() run on the loop thread; summary()
    and drain_records() may be called from another thread (the actor's
    poll) — shared aggregates are lock-guarded.
    """

    def __init__(self, ring: int = 512,
                 flops_per_step: Optional[float] = None,
                 peak_flops: Optional[float] = None,
                 rank: Optional[int] = None,
                 emit_metrics: bool = True):
        if ring < 1:
            raise ValueError(f"ring must be >= 1, got {ring}")
        self._ring: "collections.deque" = collections.deque(maxlen=ring)
        self._pending: "collections.deque" = collections.deque(maxlen=ring)
        self._lock = threading.Lock()
        self._flops_per_step = flops_per_step
        self._peak_flops = peak_flops or peak_flops_per_s()
        self._emit = emit_metrics
        self._watched: List[Any] = []
        self._last_compiles = 0
        self._feed = None
        self._feed_last: Dict[str, float] = {}
        self._steps = 0
        self._totals: Dict[str, float] = {}
        self._total_wall = 0.0
        self._total_tokens = 0.0
        self._last_wall = 0.0
        if rank is None:
            try:
                from ray_tpu.train.session import get_session

                rank = get_session().world_rank
            except Exception:  # rtlint: disable=RT007 — standalone profiler, no session
                rank = None
        self.rank = rank
        self._rank_tag = {"rank": str(rank if rank is not None else "-")}
        _ensure_collective_hook()
        # Metric series keys resolved ONCE — _finish runs per step and
        # must not merge/sort tag dicts or take the registry lock there.
        self._m = _recorder_metrics() if self._emit else None
        if self._m is not None:
            m = self._m
            self._wall_key = m["wall"]._key(self._rank_tag)
            self._compiles_key = m["compiles"]._key(self._rank_tag)
            self._throughput_key = m["throughput"]._key(self._rank_tag)
            self._mfu_key = m["mfu"]._key(self._rank_tag)
            self._phase_keys = {
                k: m["phase"]._key({**self._rank_tag, "phase": k})
                for k in PHASES + ("other",)
            }
        # Auto-attach to the active session so step records ride
        # session.report / worker poll without extra user wiring.
        try:
            from ray_tpu.train.session import get_session

            get_session().attach_profiler(self)
        except Exception:  # rtlint: disable=RT007 — no session (driver/bench use)
            pass

    # -- loop-side API ---------------------------------------------------
    def watch_jit(self, *fns: Any) -> "StepProfiler":
        """Track compiled-program cache growth of these jitted callables:
        any growth during a step is recorded as that step's `compiles`
        (a steady-state loop should record 0 — growth means a retrace)."""
        self._watched.extend(fns)
        self._last_compiles = self._compile_count()
        return self

    def attach_feed(self, source: Any) -> "StepProfiler":
        """Wire data-wait accounting to an input pipeline: `source` is a
        FeedStats, or anything with feed_stats()/stats (DataIterator,
        _DevicePrefetcher). Each step records the delta of the feed's
        consumer wait; steps with no explicit "data" phase attribute the
        delta to data_s automatically."""
        self._feed = source
        self._feed_last = self._feed_snapshot() or {}
        return self

    def step(self, tokens: Optional[float] = None,
             samples: Optional[float] = None) -> _StepHandle:
        """Record one training step. Yields the step handle (set .tokens
        /.samples late, call .fence(tree) before exit)."""
        return _StepHandle(self, tokens=tokens, samples=samples)

    def phase(self, name: str) -> "_PhaseTimer":
        """Attribute the enclosed wall time to `name` within the current
        step ("data", "compute", "collective", "checkpoint", or any
        custom key). Outside a step: a plain no-op timer. Class-based
        (not @contextmanager) — this runs inside the hot loop."""
        return _PhaseTimer(name)

    # -- record assembly -------------------------------------------------
    def _compile_count(self) -> int:
        n = 0
        for f in self._watched:
            try:
                n += f._cache_size()
            except (AttributeError, TypeError):
                # A callable without jit cache introspection just
                # disables retrace counting for itself.
                pass
        return n

    def _feed_snapshot(self) -> Optional[Dict[str, float]]:
        src = self._feed
        if src is None:
            return None
        for attr in ("snapshot", "feed_stats"):
            fn = getattr(src, attr, None)
            if callable(fn):
                try:
                    snap = fn()
                except Exception:  # rtlint: disable=RT007 — feed gone mid-run
                    return None
                return snap if isinstance(snap, dict) else None
        stats = getattr(src, "stats", None)
        if stats is not None and hasattr(stats, "snapshot"):
            return stats.snapshot()
        return None

    def _finish(self, handle: _StepHandle, wall: float) -> None:
        phases = handle._phases
        rec: Dict[str, Any] = {
            "step": self._steps,
            "ts": time.time(),
            "wall_s": wall,
        }
        # Feed delta: consumer wait the pipeline measured this step.
        snap = self._feed_snapshot()
        if snap is not None:
            wait = snap.get("wait_s", 0.0) - self._feed_last.get("wait_s", 0.0)
            stalls = (snap.get("stall_count", 0)
                      - self._feed_last.get("stall_count", 0))
            self._feed_last = snap
            rec["feed_wait_s"] = max(wait, 0.0)
            rec["feed_stalls"] = max(stalls, 0)
            if "data" not in phases:
                # No explicit data phase: the measured feed wait IS the
                # step's data time.
                phases["data"] = rec["feed_wait_s"]
        named = 0.0
        for k in PHASES:
            v = min(phases.get(k, 0.0), wall)
            rec[f"{k}_s"] = v
            named += v
        for k, v in phases.items():
            if k not in PHASES:
                rec[f"{k}_s"] = v
                named += v
        rec["other_s"] = max(wall - named, 0.0)
        compiles = 0
        if self._watched:
            n = self._compile_count()
            compiles = max(n - self._last_compiles, 0)
            self._last_compiles = n
        rec["compiles"] = compiles
        tokens = handle.tokens if handle.tokens is not None else handle.samples
        if tokens is not None and wall > 0:
            rec["tokens"] = tokens
            rec["tokens_per_s"] = tokens / wall
        if self._flops_per_step and self._peak_flops and wall > 0:
            rec["mfu"] = self._flops_per_step / wall / self._peak_flops
        with self._lock:
            self._steps += 1
            rec["step"] = self._steps - 1
            self._ring.append(rec)
            self._pending.append(rec)
            self._total_wall += wall
            self._last_wall = wall
            if tokens is not None:
                self._total_tokens += tokens
            for k in list(rec):
                # Phase-time keys only ("tokens_per_s" is a rate).
                if k.endswith("_s") and k not in ("tokens_per_s", "wall_s"):
                    self._totals[k] = self._totals.get(k, 0.0) + rec[k]
        m = self._m
        if m is not None:
            m["wall"].observe_keyed(self._wall_key, wall)
            phase_keys = self._phase_keys
            phase_counter = m["phase"]
            for k in PHASES + ("other",):
                v = rec.get(f"{k}_s", 0.0)
                if v > 0:
                    phase_counter.inc_keyed(phase_keys[k], v)
            if compiles:
                m["compiles"].inc_keyed(self._compiles_key, compiles)
            if "tokens_per_s" in rec:
                m["throughput"].set_keyed(
                    self._throughput_key, rec["tokens_per_s"]
                )
            if "mfu" in rec:
                m["mfu"].set_keyed(self._mfu_key, rec["mfu"])
        journal.emit("train.step", step=rec["step"],
                     wall_s=round(wall, 6), compiles=compiles,
                     **({"tokens": tokens} if tokens is not None else {}))

    # -- observer-side API -----------------------------------------------
    def records(self) -> List[Dict]:
        """The ring buffer's current contents (oldest first)."""
        with self._lock:
            return list(self._ring)

    def drain_records(self) -> List[Dict]:
        """Pop records not yet shipped (the session.report path calls
        this so each report carries the steps since the last one)."""
        with self._lock:
            out = list(self._pending)
            self._pending.clear()
            return out

    def summary(self) -> Dict:
        """Cumulative per-rank stats — the compact record each poll ships
        to the trainer for cross-rank skew computation."""
        with self._lock:
            steps = self._steps
            out = {
                "rank": self.rank,
                "steps": steps,
                "wall_s": self._total_wall,
                "mean_step_s": self._total_wall / steps if steps else 0.0,
                "last_step_s": self._last_wall,
                "tokens": self._total_tokens,
            }
            for k, v in self._totals.items():
                out[k] = v
            return out


def compute_skew(rank_summaries: Sequence[Optional[Dict]]) -> Optional[Dict]:
    """Cross-rank straggler attribution from per-rank summary() dicts
    (driver-side; entries may be None for ranks not yet reporting).

    Returns {"skew_s", "straggler_rank", "mean_step_s_by_rank",
    "straggler_breakdown"} — skew is (slowest - fastest) mean step wall;
    the straggler is the argmax rank; its per-phase means show WHERE the
    lost time goes. None until >= 2 ranks have completed steps.
    """
    ranked = [
        (i, s) for i, s in enumerate(rank_summaries)
        if s and s.get("steps", 0) > 0
    ]
    if len(ranked) < 2:
        return None
    means = {i: s["wall_s"] / s["steps"] for i, s in ranked}
    straggler = max(means, key=means.get)
    skew = means[straggler] - min(means.values())
    s = dict(ranked)[straggler]
    steps = s["steps"]
    breakdown = {
        k: round(v / steps, 6)
        for k, v in s.items()
        if isinstance(v, (int, float)) and k.endswith("_s")
        and k not in ("wall_s", "mean_step_s", "last_step_s", "tokens_per_s")
    }
    return {
        "skew_s": skew,
        "straggler_rank": straggler,
        "mean_step_s_by_rank": {i: round(m, 6) for i, m in means.items()},
        "straggler_breakdown": breakdown,
    }
