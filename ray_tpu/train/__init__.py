"""ray_tpu.train: distributed training on TPU gangs.

Public surface mirrors the reference's ray.train/ray.air:
ScalingConfig/RunConfig/FailureConfig/CheckpointConfig/Result,
Checkpoint, JaxTrainer (the TorchTrainer replacement), DataParallelTrainer,
and the session API (report / get_checkpoint / get_dataset_shard /
get_world_rank ...).
"""

from ray_tpu.train.backend import Backend, BackendConfig, JaxConfig, allreduce_gradients
from ray_tpu.train.checkpoint import (
    AsyncCheckpointer,
    Checkpoint,
    CheckpointManager,
    ShardRemapPlan,
    ShardedState,
)
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    ResizePolicy,
    Result,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.session import (
    ResizeEvent,
    get_checkpoint,
    get_dataset_shard,
    get_local_rank,
    get_session,
    get_trial_dir,
    get_context,
    get_world_rank,
    get_world_size,
    report,
    shard_state,
    should_stop,
    sync_resize,
)
from ray_tpu.train.backend_executor import ResizeError, TrainingFailedError
from ray_tpu.train.flight_recorder import StepProfiler, compute_skew
from ray_tpu.train.trainer import BaseTrainer, DataParallelTrainer, JaxTrainer
from ray_tpu.train.data_config import DataConfig
from ray_tpu.train import torch  # noqa: F401 — train.torch.TorchTrainer
from ray_tpu.train.sklearn import SklearnTrainer

__all__ = [
    "Backend",
    "BackendConfig",
    "JaxConfig",
    "allreduce_gradients",
    "AsyncCheckpointer",
    "Checkpoint",
    "CheckpointManager",
    "CheckpointConfig",
    "FailureConfig",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "BaseTrainer",
    "DataParallelTrainer",
    "JaxTrainer",
    "SklearnTrainer",
    "DataConfig",
    "torch",
    "report",
    "get_checkpoint",
    "get_dataset_shard",
    "get_context",
    "get_world_rank",
    "get_world_size",
    "get_local_rank",
    "get_trial_dir",
    "get_session",
    "should_stop",
    "StepProfiler",
    "compute_skew",
    "TrainingFailedError",
    "ResizeError",
    "ResizeEvent",
    "ResizePolicy",
    "ShardRemapPlan",
    "ShardedState",
    "shard_state",
    "sync_resize",
]
