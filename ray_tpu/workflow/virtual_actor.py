"""Virtual actors: durable actor state addressed by a string id.

Analog of the reference's workflow virtual actors (ray.workflow
virtual_actor decorator): unlike a regular actor — whose state lives in
one process and dies with it — a virtual actor's state lives in workflow
storage. Any process can `get_or_create` the same id, each method call
atomically advances the persisted state, and a crash between calls loses
nothing.

Durability contract: one method call = one atomic state transition.
State is persisted with write-then-rename AFTER the method returns, so a
crash mid-call leaves the previous state intact (the call simply never
happened). Methods marked @readonly skip persistence entirely.

    @workflow.virtual_actor
    class Counter:
        def __init__(self, start=0):
            self.value = start

        def add(self, n):
            self.value += n
            return self.value

        @workflow.readonly
        def get(self):
            return self.value

    c = Counter.get_or_create("my-counter", start=10)
    c.add(5)                                   # -> 15, persisted
    c2 = Counter.get_or_create("my-counter")   # any process, later
    c2.get()                                   # -> 15
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Dict, Optional

from ray_tpu.workflow import _checkpoint, _root


def readonly(fn):
    """Mark a virtual-actor method as non-mutating: it runs against the
    loaded state and skips the persistence step."""
    fn.__rt_readonly__ = True
    return fn


def virtual_actor(cls) -> "VirtualActorClass":
    """Class decorator turning a plain class into a virtual-actor class."""
    return VirtualActorClass(cls)


def _actor_dir(actor_id: str, storage: Optional[str]) -> str:
    return os.path.join(_root(storage), "virtual_actors", actor_id)


class VirtualActorClass:
    def __init__(self, cls):
        self._cls = cls
        self.__name__ = getattr(cls, "__name__", "VirtualActor")

    def get_or_create(self, actor_id: str, *args,
                      storage: Optional[str] = None,
                      **kwargs) -> "VirtualActorHandle":
        d = _actor_dir(actor_id, storage)
        state_path = os.path.join(d, "state.pkl")
        handle = VirtualActorHandle(self._cls, actor_id, d)
        if not os.path.exists(state_path):
            os.makedirs(d, exist_ok=True)
            # Initialization holds the same per-actor lock as _call:
            # without it, two creators can both see state.pkl missing and
            # the loser's late initial write (rename = last-writer-wins)
            # would clobber transitions the winner already committed.
            lock, token = handle._acquire()
            try:
                if not os.path.exists(state_path):
                    instance = self._cls(*args, **kwargs)
                    _checkpoint(state_path, {
                        "seq": 0,
                        "state": dict(instance.__dict__),
                        "created_at": time.time(),
                    })
            finally:
                handle._release(lock, token)
        return handle

    def exists(self, actor_id: str, storage: Optional[str] = None) -> bool:
        return os.path.exists(
            os.path.join(_actor_dir(actor_id, storage), "state.pkl")
        )


class _LockHeld(Exception):
    pass


class VirtualActorHandle:
    """Proxy whose attribute access returns callable method stubs."""

    def __init__(self, cls, actor_id: str, d: str):
        self._cls = cls
        self._actor_id = actor_id
        self._dir = d

    # -- state IO ---------------------------------------------------------
    def _load(self) -> Dict[str, Any]:
        with open(os.path.join(self._dir, "state.pkl"), "rb") as f:
            return pickle.load(f)

    def _persist(self, record: Dict[str, Any]):
        _checkpoint(os.path.join(self._dir, "state.pkl"), record)

    # -- locking (cross-process mutual exclusion per actor id) ------------
    def _acquire(self, timeout_s: float = 30.0):
        """Returns (lock_path, token). Release with _release — a blind
        unlink could delete a *different* holder's lock if ours was
        reaped as stale while we ran (slow user code past timeout_s)."""
        lock = os.path.join(self._dir, ".lock")
        token = f"{os.getpid()}:{time.monotonic_ns()}"
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.write(fd, token.encode())
                os.close(fd)
                return lock, token
            except FileExistsError:
                # Reap locks from dead holders (crash mid-call).
                try:
                    age = time.time() - os.path.getmtime(lock)
                    if age > timeout_s:
                        os.unlink(lock)
                        continue
                except OSError:
                    continue
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"virtual actor {self._actor_id!r} is locked"
                    ) from None
                time.sleep(0.02)

    @staticmethod
    def _release(lock: str, token: str):
        """Unlink the lock only if we still own it (our token inside)."""
        try:
            with open(lock, "rb") as f:
                if f.read().decode(errors="replace") != token:
                    return  # reaped as stale; someone else holds it now
            os.unlink(lock)
        except OSError:
            pass

    def _call(self, method_name: str, args, kwargs):
        fn = getattr(self._cls, method_name)
        is_readonly = getattr(fn, "__rt_readonly__", False)
        if is_readonly:
            record = self._load()
            instance = self._materialize(record)
            return fn(instance, *args, **kwargs)
        lock, token = self._acquire()
        try:
            record = self._load()
            instance = self._materialize(record)
            result = fn(instance, *args, **kwargs)
            # The atomic transition: a crash before this rename = the
            # call never happened; after = fully durable.
            self._persist({
                **record,
                "seq": record["seq"] + 1,
                "state": dict(instance.__dict__),
                "updated_at": time.time(),
            })
            return result
        finally:
            self._release(lock, token)

    def _materialize(self, record: Dict[str, Any]):
        instance = self._cls.__new__(self._cls)
        instance.__dict__.update(record["state"])
        return instance

    @property
    def seq(self) -> int:
        """Number of durable state transitions so far."""
        return self._load()["seq"]

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if not callable(getattr(self._cls, name, None)):
            raise AttributeError(
                f"{self._cls.__name__} has no method {name!r}"
            )

        def stub(*args, **kwargs):
            return self._call(name, args, kwargs)

        stub.__name__ = name
        return stub
