"""Durable workflows: DAG execution with storage-backed step checkpoints.

Analog of the reference's ``ray.workflow`` (workflow/api.py:123 run,
workflow_executor.py, workflow_state_from_dag.py): a task DAG executes
step by step, each step's result is checkpointed to durable storage, and
``resume()`` re-runs a failed/interrupted workflow skipping every step
whose checkpoint exists.

Storage layout (one directory per workflow under the storage root):
    <root>/<workflow_id>/status.json
    <root>/<workflow_id>/input.pkl
    <root>/<workflow_id>/steps/<step_key>.pkl

Step keys are stable across runs: the function's qualname plus its
position in the deterministic topological order.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import time
from typing import Any, Dict, List, Optional

from ray_tpu.dag.dag_node import DAGNode, FunctionNode, InputNode

_DEFAULT_ROOT = os.path.join(
    os.path.expanduser("~"), ".ray_tpu", "workflows"
)


def _root(storage: Optional[str]) -> str:
    return storage or os.environ.get("RT_WORKFLOW_STORAGE") or _DEFAULT_ROOT


def _wf_dir(workflow_id: str, storage: Optional[str]) -> str:
    return os.path.join(_root(storage), workflow_id)


def _step_key(node: DAGNode, index: int) -> str:
    if isinstance(node, FunctionNode):
        name = getattr(node._remote_fn, "__qualname__", "fn")
    elif isinstance(node, EventNode):
        name = f"event-{node.event_name}"
    else:
        name = type(node).__name__
    return f"{index:04d}-{name.replace('/', '_').replace('<', '').replace('>', '')}"


def _checkpoint(path: str, value: Any):
    """Durably persist a step/event result: write-then-rename, so the
    checkpoint is either complete or absent."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(value, f, protocol=5)
    os.replace(tmp, path)


def _write_status(d: str, **fields):
    path = os.path.join(d, "status.json")
    status = {}
    if os.path.exists(path):
        with open(path) as f:
            status = json.load(f)
    status.update(fields)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(status, f)
    os.replace(tmp, path)


def _read_status(d: str) -> Optional[dict]:
    try:
        with open(os.path.join(d, "status.json")) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


class EventNode(DAGNode):
    """A workflow step that resolves when an external signal arrives.

    Reference analog: workflow events (workflow.wait_for_event): the node
    blocks the workflow until `workflow.signal(workflow_id, name, ...)`
    delivers a payload; the payload checkpoints like any step, so a
    resumed workflow does not wait again for an event it already received.
    """

    def __init__(self, name: str, timeout_s: Optional[float] = None,
                 poll_s: float = 0.2):
        super().__init__((), {})
        self.event_name = name
        self.timeout_s = timeout_s
        self.poll_s = poll_s

    def _execute_node(self, resolved):
        raise WorkflowError(
            f"workflow.event({self.event_name!r}) only resolves under "
            "workflow.run(...), which provides the durable signal store"
        )


def event(name: str, timeout_s: Optional[float] = None) -> EventNode:
    """Declare an event dependency in a workflow DAG."""
    return EventNode(name, timeout_s=timeout_s)


def signal(workflow_id: str, name: str, payload: Any = None,
           storage: Optional[str] = None):
    """Deliver an event payload to a (possibly waiting) workflow. Durable:
    signaling before the workflow reaches the event is fine."""
    import tempfile

    d = os.path.join(_wf_dir(workflow_id, storage), "events")
    os.makedirs(d, exist_ok=True)
    # Unique tmp per signaler: concurrent signals must never interleave
    # writes into one tmp inode before the atomic rename.
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    with os.fdopen(fd, "wb") as f:
        pickle.dump(payload, f, protocol=5)
    os.replace(tmp, os.path.join(d, name + ".pkl"))


class WorkflowError(Exception):
    pass


def _execute(dag: DAGNode, wf_dir: str, input_value, max_step_retries: int):
    import ray_tpu as rt

    steps_dir = os.path.join(wf_dir, "steps")
    os.makedirs(steps_dir, exist_ok=True)
    topo = dag._topo()
    resolved: Dict[int, Any] = {}
    for index, node in enumerate(topo):
        if isinstance(node, InputNode):
            resolved[node._id] = input_value
            continue
        if isinstance(node, EventNode):
            key = _step_key(node, index)
            ckpt = os.path.join(steps_dir, key + ".pkl")
            if os.path.exists(ckpt):
                with open(ckpt, "rb") as f:
                    resolved[node._id] = pickle.load(f)
                continue
            ev_path = os.path.join(wf_dir, "events", node.event_name + ".pkl")
            _write_status(wf_dir, state="WAITING", waiting_on=node.event_name)
            deadline = (
                None if node.timeout_s is None
                else time.monotonic() + node.timeout_s
            )
            while not os.path.exists(ev_path):
                if deadline is not None and time.monotonic() > deadline:
                    raise WorkflowError(
                        f"timed out waiting for event {node.event_name!r}"
                    )
                time.sleep(node.poll_s)
            with open(ev_path, "rb") as f:
                payload = pickle.load(f)
            _checkpoint(ckpt, payload)
            _write_status(wf_dir, state="RUNNING", last_step=key,
                          waiting_on=None, updated_at=time.time())
            resolved[node._id] = payload
            continue
        if not isinstance(node, FunctionNode):
            raise WorkflowError(
                "workflows support task (function) DAGs; actor nodes hold "
                "process state that cannot be checkpoint-resumed"
            )
        key = _step_key(node, index)
        ckpt = os.path.join(steps_dir, key + ".pkl")
        if os.path.exists(ckpt):
            with open(ckpt, "rb") as f:
                resolved[node._id] = pickle.load(f)
            continue
        args, kwargs = node._resolve_args(resolved)
        last_exc = None
        for _ in range(max_step_retries + 1):
            try:
                value = rt.get(node._remote_fn.remote(*args, **kwargs))
                break
            except Exception as e:  # noqa: BLE001
                last_exc = e
        else:
            raise WorkflowError(f"step {key} failed: {last_exc}") from last_exc
        _checkpoint(ckpt, value)  # atomic: a step is durable or absent
        _write_status(wf_dir, last_step=key, updated_at=time.time())
        resolved[node._id] = value
    return resolved[dag._id]


def run(
    dag: DAGNode,
    *args,
    workflow_id: Optional[str] = None,
    storage: Optional[str] = None,
    max_step_retries: int = 3,
):
    """Execute a DAG durably; returns the final result.

    Reference: workflow.run (workflow/api.py:123).
    """
    workflow_id = workflow_id or f"wf-{int(time.time() * 1000):x}"
    wf_dir = _wf_dir(workflow_id, storage)
    os.makedirs(wf_dir, exist_ok=True)
    input_value = args[0] if args else None
    _write_status(wf_dir, workflow_id=workflow_id, state="RUNNING",
                  created_at=time.time())
    # Everything after the RUNNING mark reports failures durably —
    # run_async callers would otherwise time out with no recorded error
    # when e.g. the DAG is not serializable.
    try:
        _checkpoint(os.path.join(wf_dir, "input.pkl"), input_value)
        with open(os.path.join(wf_dir, "dag.pkl"), "wb") as f:
            import cloudpickle

            cloudpickle.dump(dag, f)
        result = _execute(dag, wf_dir, input_value, max_step_retries)
    except BaseException as e:
        _write_status(wf_dir, state="FAILED", error=str(e))
        raise
    return _commit_output(wf_dir, result)


def resume(workflow_id: str, storage: Optional[str] = None,
           max_step_retries: int = 3):
    """Re-run a workflow, skipping checkpointed steps
    (workflow.resume in the reference)."""
    wf_dir = _wf_dir(workflow_id, storage)
    status = _read_status(wf_dir)
    if status is None:
        raise WorkflowError(f"no such workflow: {workflow_id}")
    if status.get("state") == "SUCCEEDED":
        with open(os.path.join(wf_dir, "output.pkl"), "rb") as f:
            return pickle.load(f)
    import cloudpickle

    with open(os.path.join(wf_dir, "dag.pkl"), "rb") as f:
        dag = cloudpickle.load(f)
    with open(os.path.join(wf_dir, "input.pkl"), "rb") as f:
        input_value = pickle.load(f)
    _write_status(wf_dir, state="RUNNING")
    try:
        result = _execute(dag, wf_dir, input_value, max_step_retries)
    except BaseException as e:
        _write_status(wf_dir, state="FAILED", error=str(e))
        raise
    return _commit_output(wf_dir, result)


def _commit_output(wf_dir: str, result):
    """Durably commit a finished workflow: atomic output write, THEN the
    SUCCEEDED status — readers key off the status, so they can never see
    a partial output or a success without one."""
    _checkpoint(os.path.join(wf_dir, "output.pkl"), result)
    _write_status(wf_dir, state="SUCCEEDED", finished_at=time.time())
    return result


def run_async(
    dag: DAGNode,
    *args,
    workflow_id: Optional[str] = None,
    storage: Optional[str] = None,
    max_step_retries: int = 3,
) -> str:
    """Start a workflow without blocking; returns its id immediately
    (reference: workflow.run_async, workflow/api.py). Follow with
    get_output(workflow_id, wait=...) or signal()/get_status()."""
    import threading

    workflow_id = workflow_id or f"wf-{int(time.time() * 1000):x}"
    t = threading.Thread(
        target=run,
        args=(dag, *args),
        kwargs={
            "workflow_id": workflow_id,
            "storage": storage,
            "max_step_retries": max_step_retries,
        },
        daemon=True,
    )
    t.start()
    return workflow_id


def get_status(workflow_id: str, storage: Optional[str] = None) -> Optional[str]:
    status = _read_status(_wf_dir(workflow_id, storage))
    return status.get("state") if status else None


def get_output(workflow_id: str, storage: Optional[str] = None,
               wait: float = 0.0):
    """The workflow's result. With wait > 0, blocks up to that many
    seconds for an in-flight run (run_async) to finish; FAILED surfaces
    as WorkflowError with the recorded error.

    Keys off the status, not the output file: SUCCEEDED is written after
    the atomic output commit, so a SUCCEEDED status guarantees a complete
    output.pkl."""
    wf_dir = _wf_dir(workflow_id, storage)
    deadline = time.monotonic() + wait
    while True:
        status = _read_status(wf_dir) or {}
        state = status.get("state")
        if state == "SUCCEEDED":
            with open(os.path.join(wf_dir, "output.pkl"), "rb") as f:
                return pickle.load(f)
        if state == "FAILED":
            raise WorkflowError(
                f"workflow {workflow_id} failed: {status.get('error')}"
            )
        if time.monotonic() >= deadline:
            raise WorkflowError(f"workflow {workflow_id} has no output yet")
        time.sleep(0.05)


def list_all(storage: Optional[str] = None) -> List[dict]:
    root = _root(storage)
    out = []
    if not os.path.isdir(root):
        return out
    for wid in sorted(os.listdir(root)):
        status = _read_status(os.path.join(root, wid))
        if status:
            out.append(status)
    return out


def delete(workflow_id: str, storage: Optional[str] = None):
    shutil.rmtree(_wf_dir(workflow_id, storage), ignore_errors=True)


from ray_tpu.workflow.virtual_actor import (  # noqa: E402 — needs _root
    VirtualActorClass,
    VirtualActorHandle,
    readonly,
    virtual_actor,
)
