"""Export / import of remote functions and actor classes through the GCS KV.

Analog of the reference's FunctionActorManager
(python/ray/_private/function_manager.py:62): the driver pickles the
function/class once, exports it under a content-addressed key, and workers
fetch + cache by key.
"""

from __future__ import annotations

import hashlib
import threading
import weakref
from typing import Any, Dict

import cloudpickle

_NS = "fn"


def function_key(pickled: bytes) -> bytes:
    return hashlib.blake2b(pickled, digest_size=16).digest()


class FunctionManager:
    def __init__(self, client):
        # `client` provides kv_put / kv_get (sync wrappers over GCS).
        self._client = client
        self._exported: set = set()
        self._cache: Dict[bytes, Any] = {}
        self._lock = threading.Lock()
        # Same-object fast path: pickling the function on every submit just
        # to compute its key was ~90us/task on the hot path. Weak keys so a
        # collected function can't alias a recycled id.
        self._id_cache: "weakref.WeakKeyDictionary[Any, bytes]" = (
            weakref.WeakKeyDictionary()
        )

    def export(self, obj: Any) -> bytes:
        try:
            key = self._id_cache.get(obj)
        except TypeError:  # unhashable / not weakref-able
            key = None
        if key is not None:
            return key
        pickled = cloudpickle.dumps(obj)
        key = function_key(pickled)
        with self._lock:
            if key in self._exported:
                try:
                    self._id_cache[obj] = key
                except TypeError:
                    pass
                return key
        self._client.kv_put(key, pickled, ns=_NS, overwrite=False)
        with self._lock:
            self._exported.add(key)
            self._cache[key] = obj
        try:
            self._id_cache[obj] = key
        except TypeError:
            pass
        return key

    def fetch(self, key: bytes) -> Any:
        with self._lock:
            if key in self._cache:
                return self._cache[key]
        pickled = self._client.kv_get(key, ns=_NS)
        if pickled is None:
            raise KeyError(f"function {key.hex()} not found in GCS")
        obj = cloudpickle.loads(pickled)
        with self._lock:
            self._cache[key] = obj
        return obj
