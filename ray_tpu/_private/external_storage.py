"""External storage for spilled objects.

Analog of the reference's python/ray/_private/external_storage.py
(ExternalStorage ABC :72, FileSystemStorage :246, smart_open/S3 impl :445).
The raylet spills pinned primary copies here when the shared-memory store
passes its high-water mark, and restores them on demand.
"""

from __future__ import annotations

import os
import uuid
from abc import ABC, abstractmethod
from typing import List, Optional


class ExternalStorage(ABC):
    @abstractmethod
    def spill(self, object_id: bytes, data: memoryview) -> str:
        """Write one object; returns a restore URI."""

    @abstractmethod
    def restore(self, uri: str) -> bytes:
        """Read a spilled object back."""

    @abstractmethod
    def delete(self, uris: List[str]) -> None:
        """Best-effort cleanup of spilled objects."""


class FileSystemStorage(ExternalStorage):
    """Spill to a node-local (or network-mounted) directory."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def spill(self, object_id: bytes, data: memoryview) -> str:
        fname = f"{object_id.hex()}-{uuid.uuid4().hex[:8]}.bin"
        path = os.path.join(self.directory, fname)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.rename(tmp, path)
        return "file://" + path

    def restore(self, uri: str) -> bytes:
        path = uri.removeprefix("file://")
        with open(path, "rb") as f:
            return f.read()

    def delete(self, uris: List[str]) -> None:
        for uri in uris:
            try:
                os.unlink(uri.removeprefix("file://"))
            except OSError:
                pass


class UriStorage(ExternalStorage):
    """Spill to any pyarrow.fs-resolvable URI — s3://, gs://, hdfs://,
    mock:// (the same resolution layer train/storage.py drives for
    checkpoints; reference: external_storage.py:445 ExternalStorageSmartOpenImpl).

    An explicit `filesystem` overrides URI resolution (tests inject a
    local fake filesystem for s3://-shaped URIs)."""

    def __init__(self, base_uri: str, filesystem=None, base_path: Optional[str] = None):
        import pyarrow.fs as pafs

        self.base_uri = base_uri.rstrip("/")
        if filesystem is not None:
            self.fs = filesystem
            self.path = (base_path if base_path is not None
                         else self._strip_scheme(self.base_uri))
        else:
            self.fs, self.path = pafs.FileSystem.from_uri(self.base_uri)
        self.fs.create_dir(self.path, recursive=True)

    @staticmethod
    def _strip_scheme(uri: str) -> str:
        rest = uri.split("://", 1)[-1]
        return rest

    def spill(self, object_id: bytes, data: memoryview) -> str:
        fname = f"{object_id.hex()}-{uuid.uuid4().hex[:8]}.bin"
        path = f"{self.path}/{fname}"
        with self.fs.open_output_stream(path) as f:
            f.write(data)
        return f"{self.base_uri}/{fname}"

    def _fs_path(self, uri: str) -> str:
        assert uri.startswith(self.base_uri + "/"), uri
        return f"{self.path}/{uri[len(self.base_uri) + 1:]}"

    def restore(self, uri: str) -> bytes:
        with self.fs.open_input_stream(self._fs_path(uri)) as f:
            return f.read()

    def delete(self, uris: List[str]) -> None:
        for uri in uris:
            try:
                self.fs.delete_file(self._fs_path(uri))
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass


def create_storage(node_id_hex: str, spill_dir: Optional[str] = None,
                   filesystem=None) -> ExternalStorage:
    base = spill_dir or os.environ.get("RT_SPILL_DIR") or os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "ray_tpu", "spill"
    )
    if "://" in base:
        # s3:// gs:// hdfs:// file:// ... — anything pyarrow.fs resolves
        # (file:// rides UriStorage too, which is the e2e test path for
        # the URI backend without cloud credentials).
        return UriStorage(f"{base.rstrip('/')}/{node_id_hex[:12]}",
                          filesystem=filesystem)
    return FileSystemStorage(os.path.join(base, node_id_hex[:12]))
