"""External storage for spilled objects.

Analog of the reference's python/ray/_private/external_storage.py
(ExternalStorage ABC :72, FileSystemStorage :246, smart_open/S3 impl :445).
The raylet spills pinned primary copies here when the shared-memory store
passes its high-water mark, and restores them on demand.
"""

from __future__ import annotations

import os
import uuid
from abc import ABC, abstractmethod
from typing import List, Optional


class ExternalStorage(ABC):
    @abstractmethod
    def spill(self, object_id: bytes, data: memoryview) -> str:
        """Write one object; returns a restore URI."""

    @abstractmethod
    def restore(self, uri: str) -> bytes:
        """Read a spilled object back."""

    @abstractmethod
    def delete(self, uris: List[str]) -> None:
        """Best-effort cleanup of spilled objects."""


class FileSystemStorage(ExternalStorage):
    """Spill to a node-local (or network-mounted) directory."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def spill(self, object_id: bytes, data: memoryview) -> str:
        fname = f"{object_id.hex()}-{uuid.uuid4().hex[:8]}.bin"
        path = os.path.join(self.directory, fname)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.rename(tmp, path)
        return "file://" + path

    def restore(self, uri: str) -> bytes:
        path = uri.removeprefix("file://")
        with open(path, "rb") as f:
            return f.read()

    def delete(self, uris: List[str]) -> None:
        for uri in uris:
            try:
                os.unlink(uri.removeprefix("file://"))
            except OSError:
                pass


def create_storage(node_id_hex: str, spill_dir: Optional[str] = None) -> ExternalStorage:
    base = spill_dir or os.environ.get("RT_SPILL_DIR") or os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "ray_tpu", "spill"
    )
    if base.startswith(("s3://", "gs://")):
        raise NotImplementedError(
            "cloud spill storage requires a smart_open-style dependency not "
            "baked into this image; mount the bucket or use a shared filesystem"
        )
    return FileSystemStorage(os.path.join(base, node_id_hex[:12]))
