"""Local (in-process) execution mode.

Analog of the reference's local_mode in ray.init: tasks run synchronously
in the driver process, actors are plain in-process instances. Useful for
debugging user code and for fast unit tests of library layers.
"""

from __future__ import annotations

import concurrent.futures
from typing import Dict, List, Optional

from ray_tpu._private.ids import ActorID, ObjectID
from ray_tpu._private.worker import ActorHandle, ObjectRef, make_task_error, _rebuild_task_error
from ray_tpu.exceptions import ActorDiedError


class _LocalRefGenerator:
    """Local-mode stand-in for ObjectRefGenerator: the task already ran
    eagerly, so iteration walks the stored item refs. A generator body
    that raised mid-way surfaces its error FROM ITERATION after the
    produced items — matching the real path, where the task future's
    error re-raises out of ObjectRefGenerator.__next__."""

    def __init__(self, refs: List[ObjectRef], error=None):
        self._refs = refs
        self._error = error
        self._i = 0

    def __iter__(self) -> "_LocalRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        if self._i >= len(self._refs):
            if self._error is not None:
                raise self._error
            raise StopIteration
        self._i += 1
        return self._refs[self._i - 1]

    def __len__(self) -> int:
        return len(self._refs)


class LocalClient:
    """Implements the CoreClient surface with synchronous local execution."""

    def __init__(self, resources: Optional[Dict[str, float]] = None):
        self.objects: Dict[bytes, object] = {}
        self.actors: Dict[bytes, object] = {}
        self.named: Dict[tuple, ActorID] = {}
        self.kv: Dict[tuple, bytes] = {}
        self.resources = dict(resources or {"CPU": 8.0})
        self.mode = "local"
        self.known_refs: Dict[bytes, ObjectRef] = {}

    # -- objects ---------------------------------------------------------
    def _store(self, value) -> ObjectRef:
        oid = ObjectID.from_random()
        self.objects[oid.binary()] = value
        fut = concurrent.futures.Future()
        fut.set_result(value)
        return ObjectRef(oid, fut)

    def put(self, value) -> ObjectRef:
        return self._store(value)

    def get(self, refs: List[ObjectRef], timeout=None):
        out = []
        for r in refs:
            if r._future is not None:
                r._future.result(timeout)
            if r.id.binary() not in self.objects:
                raise KeyError(f"object {r.hex()} not found (local mode)")
            out.append(self.objects[r.id.binary()])
        return out

    def wait(self, refs, num_returns, timeout, fetch_local=True):
        return refs[:num_returns], refs[num_returns:]

    def prefetch(self, refs) -> int:
        return 0  # everything is already local in local mode

    def _error_refs(self, err, num_returns):
        if num_returns == "dynamic":
            return [_LocalRefGenerator([], error=err)]
        refs = []
        for _ in range(num_returns):
            fut = concurrent.futures.Future()
            fut.set_exception(err)
            refs.append(ObjectRef(ObjectID.from_random(), fut))
        return refs

    def _result_refs(self, value, num_returns):
        if num_returns == "dynamic":
            import inspect as _inspect

            # Consume incrementally: a generator body that raises midway
            # keeps its produced items; the error re-raises from
            # iteration after them (real-path semantics).
            refs = []
            err = None
            try:
                if _inspect.isgenerator(value):
                    for v in value:
                        refs.append(self._store(v))
                else:
                    refs.append(self._store(value))
            except BaseException as e:  # noqa: BLE001
                err = _rebuild_task_error(make_task_error(e))
            return [_LocalRefGenerator(refs, error=err)]
        values = [value] if num_returns == 1 else list(value)
        return [self._store(v) for v in values]

    # -- tasks -----------------------------------------------------------
    def submit_task(self, fn, args, kwargs, name="", num_returns=1,
                    resources=None, scheduling=None, max_retries=None,
                    runtime_env=None, max_calls=None, priority=0):
        # max_calls and priority are no-ops in local mode: there is no
        # worker process to retire and no queue to reorder (everything
        # runs inline in the driver).
        try:
            value = fn(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001
            return self._error_refs(
                _rebuild_task_error(make_task_error(e)), num_returns
            )
        return self._result_refs(value, num_returns)

    # -- actors ----------------------------------------------------------
    def create_actor(self, cls, args, kwargs, name=None, namespace="",
                     resources=None, max_restarts=0, max_task_retries=0,
                     max_concurrency=1, scheduling=None, detached=False,
                     runtime_env=None, priority=0):
        instance = cls(*args, **kwargs)
        actor_id = ActorID.from_random()
        self.actors[actor_id.binary()] = instance
        if name:
            self.named[(namespace, name)] = actor_id
        methods = [m for m in dir(instance)
                   if callable(getattr(instance, m, None)) and not m.startswith("__")]
        return ActorHandle(actor_id, cls.__name__, methods, max_task_retries)

    def submit_actor_call(self, actor_id, method, args, kwargs,
                          num_returns=1, max_task_retries=0):
        instance = self.actors.get(actor_id.binary())
        if instance is None:
            raise ActorDiedError(f"actor {actor_id.hex()} not found (local mode)")
        import inspect, asyncio

        m = getattr(instance, method)
        try:
            if inspect.iscoroutinefunction(m):
                value = asyncio.run(m(*args, **kwargs))
            else:
                value = m(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001
            return self._error_refs(
                _rebuild_task_error(make_task_error(e)), num_returns
            )
        return self._result_refs(value, num_returns)

    def kill_actor(self, actor_id, no_restart=True):
        self.actors.pop(actor_id.binary(), None)

    def get_actor_by_name(self, name, namespace=""):
        aid = self.named.get((namespace, name))
        if aid is None or aid.binary() not in self.actors:
            raise ValueError(f"no live actor named {name!r}")
        instance = self.actors[aid.binary()]
        methods = [m for m in dir(instance)
                   if callable(getattr(instance, m, None)) and not m.startswith("__")]
        return ActorHandle(aid, type(instance).__name__, methods)

    # -- kv / cluster ----------------------------------------------------
    def kv_put(self, key, value, ns="", overwrite=True):
        if not overwrite and (ns, key) in self.kv:
            return False
        self.kv[(ns, key)] = value
        return True

    def kv_get(self, key, ns=""):
        return self.kv.get((ns, key))

    def kv_del(self, key, ns=""):
        return self.kv.pop((ns, key), None) is not None

    def kv_keys(self, prefix=b"", ns=""):
        return [k for (n, k) in self.kv if n == ns and k.startswith(prefix)]

    def nodes(self):
        return [{
            "node_id": b"local" * 3 + b"x",
            "state": "ALIVE",
            "address": "127.0.0.1",
            "resources_total": self.resources,
            "resources_available": self.resources,
            "is_head": True,
        }]

    def cluster_resources(self):
        return dict(self.resources)

    def available_resources(self):
        return dict(self.resources)

    def disconnect(self):
        self.objects.clear()
        self.actors.clear()
