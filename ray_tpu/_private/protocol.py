"""Wire protocol for control-plane RPC.

The reference uses gRPC with 24 .proto services (src/ray/protobuf/,
src/ray/rpc/grpc_server.h). This runtime uses a leaner scheme suited to the
one-process-per-TPU-host world: length-prefixed msgpack frames over asyncio
TCP streams, with request/response correlation ids and server-push frames
for pubsub. Binary payloads (pickled functions, inlined objects) ride as
msgpack bin values.

Frame layout: u32 length | msgpack map {
    "k": kind ("req" | "resp" | "push"),
    "i": correlation id (int, for req/resp),
    "m": method name (req) or channel (push),
    "d": payload (any msgpack value),
    "e": error string or null (resp),
    "h": optional HLC stamp [physical_us, logical] (util/journal.py) —
         senders tick, receivers merge, so cross-process happens-before
         is recoverable from journal dumps despite host clock skew,
}
"""

from __future__ import annotations

import asyncio
import itertools
import struct
import time
from typing import Any, Awaitable, Callable, Dict, Optional

import msgpack

from ray_tpu._private.config import get_config
from ray_tpu.util import journal

_LEN = struct.Struct("<I")

# asyncio holds only weak references to tasks: a fire-and-forget
# ensure_future() whose result is dropped can be garbage-collected
# mid-flight, silently killing the coroutine (observed as RPC handlers
# vanishing while awaiting a forwarded call). Every background task must
# be anchored here until done.
_background_tasks: set = set()


def spawn(coro) -> asyncio.Task:
    """ensure_future with a strong reference for the task's lifetime."""
    task = asyncio.ensure_future(coro)
    _background_tasks.add(task)
    task.add_done_callback(_background_tasks.discard)
    return task


def pack_frame(obj: Any) -> bytes:
    body = msgpack.packb(obj, use_bin_type=True)
    return _LEN.pack(len(body)) + body


class BinResponse:
    """Handler return type for raw-payload responses: the header map rides
    msgpack, the payload follows the frame as raw bytes (no msgpack copy
    on either side — the bulk-transfer path for object chunks)."""

    __slots__ = ("data", "payload")

    def __init__(self, data: Any, payload):
        self.data = data
        self.payload = payload  # bytes / memoryview


class FrameSender:
    """Coalesces small frames into one transport write per loop tick.

    The naive write-then-drain per frame costs one socket syscall (and an
    asyncio.Lock round trip) per message — ~7 syscalls per task on the
    submit path. Queued frames from the same event-loop iteration are
    joined and written once; large frames flush the queue and await
    drain for backpressure (the gRPC write-buffer role,
    src/ray/rpc/grpc_client.h)."""

    __slots__ = ("_writer", "_buf", "_size", "_scheduled", "_lock",
                 "_direct", "_drain")

    def __init__(self, writer: asyncio.StreamWriter):
        cfg = get_config()
        self._direct = cfg.rpc_direct_write_threshold
        self._drain = cfg.rpc_write_buffer_drain
        self._writer = writer
        self._buf: list = []
        self._size = 0
        self._scheduled = False
        self._lock = asyncio.Lock()  # serializes large direct writes only

    def flush(self) -> None:
        self._scheduled = False
        if not self._buf:
            return
        data = b"".join(self._buf)
        self._buf.clear()
        self._size = 0
        self._writer.write(data)

    async def send(self, frame: bytes) -> None:
        if len(frame) >= self._direct:
            async with self._lock:
                self.flush()
                self._writer.write(frame)
                await self._writer.drain()
            return
        if not self._scheduled:
            # First frame this tick: write immediately (ping-pong traffic
            # keeps its latency); laters coalesce until the tick ends.
            self._scheduled = True
            asyncio.get_event_loop().call_soon(self._safe_flush)
            self._writer.write(frame)
        else:
            self._buf.append(frame)
            self._size += len(frame)
        # Real backpressure: when the transport's unsent backlog (a stuck
        # or slow peer) passes the watermark, park this sender in drain()
        # until the kernel accepts it — small frames must not be allowed
        # to grow the buffer without bound.
        transport = self._writer.transport
        if (
            self._size >= self._drain
            or (
                transport is not None
                and transport.get_write_buffer_size() >= self._drain
            )
        ):
            async with self._lock:
                self.flush()
                await self._writer.drain()

    async def send_pair(self, frame: bytes, payload) -> None:
        """Write a header frame + raw payload back-to-back with nothing
        interleaved: both writes happen without a yield point under the
        large-write lock (small sends cannot slip between either — they
        have no await between our two write() calls)."""
        async with self._lock:
            self.flush()
            self._writer.write(frame)
            self._writer.write(payload)
            await self._writer.drain()

    def _safe_flush(self) -> None:
        try:
            self.flush()
        except Exception:  # noqa: BLE001 — peer gone; read side reports it
            pass


async def read_frame(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(4)
    (length,) = _LEN.unpack(header)
    body = await reader.readexactly(length)
    return msgpack.unpackb(body, raw=False, strict_map_key=False)


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class Connection:
    """A bidirectional RPC connection: concurrent requests + push handling."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        push_handler: Optional[Callable[[str, Any], None]] = None,
    ):
        self.reader = reader
        self.writer = writer
        self.push_handler = push_handler
        self._ids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._closed = False
        self._reader_task = spawn(self._read_loop())
        self._sender = FrameSender(writer)

    async def _read_loop(self):
        try:
            while True:
                frame = await read_frame(self.reader)
                kind = frame.get("k")
                if "h" in frame:
                    journal.observe_wire(frame["h"])
                if kind == "resp":
                    payload = None
                    if frame.get("nb"):
                        # Raw binary payload follows the header frame.
                        payload = await self.reader.readexactly(frame["nb"])
                    fut = self._pending.pop(frame["i"], None)
                    if fut is not None and not fut.done():
                        if frame.get("e"):
                            fut.set_exception(RpcError(frame["e"]))
                        elif payload is not None:
                            fut.set_result((frame.get("d"), payload))
                        else:
                            fut.set_result(frame.get("d"))
                elif kind == "push":
                    if self.push_handler is not None:
                        self.push_handler(frame["m"], frame.get("d"))
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self._closed = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionLost("connection closed"))
            self._pending.clear()

    async def call(self, method: str, payload: Any = None, timeout: float = None) -> Any:
        if self._closed:
            raise ConnectionLost("connection closed")
        cid = next(self._ids)
        fut = asyncio.get_event_loop().create_future()
        self._pending[cid] = fut
        obj = {"k": "req", "i": cid, "m": method, "d": payload}
        h = journal.wire_stamp()
        if h is not None:
            obj["h"] = h
        frame = pack_frame(obj)
        await self._sender.send(frame)
        if timeout is not None:
            return await asyncio.wait_for(fut, timeout)
        return await fut

    async def notify(self, method: str, payload: Any = None):
        """Fire-and-forget request (no response expected)."""
        obj = {"k": "req", "i": 0, "m": method, "d": payload}
        h = journal.wire_stamp()
        if h is not None:
            obj["h"] = h
        frame = pack_frame(obj)
        await self._sender.send(frame)

    async def close(self):
        self._closed = True
        self._reader_task.cancel()
        try:
            self._sender._safe_flush()  # same-tick buffered frames
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass


Handler = Callable[[Any, "ServerConnection"], Awaitable[Any]]


class ServerConnection:
    """Server side of one accepted connection; supports pushes to the peer."""

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer
        self._sender = FrameSender(writer)
        self.meta: Dict[str, Any] = {}  # e.g. node_id / worker_id after register
        self.closed = False

    async def push(self, channel: str, payload: Any):
        if self.closed:
            return
        obj = {"k": "push", "m": channel, "d": payload}
        h = journal.wire_stamp()
        if h is not None:
            obj["h"] = h
        frame = pack_frame(obj)
        try:
            await self._sender.send(frame)
        except (ConnectionError, RuntimeError):
            self.closed = True

    async def respond(self, cid: int, data: Any = None, error: str = None):
        obj = {"k": "resp", "i": cid, "d": data, "e": error}
        h = journal.wire_stamp()
        if h is not None:
            obj["h"] = h
        frame = pack_frame(obj)
        try:
            await self._sender.send(frame)
        except (ConnectionError, RuntimeError):
            self.closed = True

    async def respond_bin(self, cid: int, data: Any, payload):
        """Header frame + raw payload bytes: the payload goes straight to
        the transport — no msgpack pass over the bulk bytes."""
        obj = {"k": "resp", "i": cid, "d": data, "nb": len(payload)}
        h = journal.wire_stamp()
        if h is not None:
            obj["h"] = h
        frame = pack_frame(obj)
        try:
            await self._sender.send_pair(frame, payload)
        except (ConnectionError, RuntimeError):
            self.closed = True


class RpcServer:
    """Dispatches method calls to registered async handlers.

    Analog of the reference's GrpcServer (src/ray/rpc/grpc_server.h) +
    ServerCall dispatch (src/ray/rpc/server_call.h).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self.handlers: Dict[str, Handler] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: set[ServerConnection] = set()
        self.on_disconnect: Optional[Callable[[ServerConnection], Awaitable[None]]] = None
        # Per-method request counter hook (the stats/metric_defs.h role:
        # per-component rpc volume metrics). Called synchronously with
        # the method name before dispatch.
        self.on_request: Optional[Callable[[str], None]] = None
        # Per-method handler-latency hook: called synchronously with
        # (method, duration_s) after the handler returns or raises. Feeds
        # the GCS's gcs_rpc_* latency histograms; None keeps dispatch
        # timer-free.
        self.on_complete: Optional[Callable[[str, float], None]] = None

    def register(self, method: str, handler: Handler):
        self.handlers[method] = handler

    async def start(self):
        self._server = await asyncio.start_server(
            self._on_client, self.host, self.port,
            limit=get_config().rpc_stream_buffer_limit,
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def _on_client(self, reader, writer):
        conn = ServerConnection(reader, writer)
        self.connections.add(conn)
        try:
            while True:
                frame = await read_frame(reader)
                if frame.get("k") != "req":
                    continue
                spawn(self._dispatch(conn, frame))
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            conn.closed = True
            self.connections.discard(conn)
            if self.on_disconnect is not None:
                try:
                    await self.on_disconnect(conn)
                except Exception:
                    pass
            try:
                conn._sender._safe_flush()  # same-tick buffered frames
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, conn: ServerConnection, frame):
        cid = frame.get("i", 0)
        method = frame.get("m")
        if "h" in frame:
            journal.observe_wire(frame["h"])
        # Count only known methods: a malformed/unknown frame must not
        # plant unbounded (or None) keys in the metrics table.
        if self.on_request is not None and method in self.handlers:
            self.on_request(method)
        handler = self.handlers.get(method)
        if handler is None:
            if cid:
                await conn.respond(cid, error=f"no such method: {method}")
            return
        on_complete = self.on_complete
        t0 = time.monotonic() if on_complete is not None else 0.0
        try:
            try:
                result = await handler(frame.get("d"), conn)
            finally:
                if on_complete is not None:
                    on_complete(method, time.monotonic() - t0)
            if cid:
                if isinstance(result, BinResponse):
                    await conn.respond_bin(cid, result.data, result.payload)
                else:
                    await conn.respond(cid, data=result)
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            import traceback

            if cid:
                await conn.respond(cid, error=f"{type(e).__name__}: {e}\n{traceback.format_exc()}")

    async def stop(self):
        if self._server is not None:
            self._server.close()
        # Close accepted connections as well: peers must observe the death
        # (ConnectionLost) to enter their reconnect paths — a closed
        # listener alone leaves established sockets half-alive. Must happen
        # BEFORE wait_closed(): since 3.12 it waits for handler coroutines,
        # which only exit when their sockets close.
        for conn in list(self.connections):
            conn.closed = True
            try:
                conn.writer.close()
            except Exception:  # noqa: BLE001
                pass
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2.0)
            except asyncio.TimeoutError:
                pass


async def connect(host: str, port: int, push_handler=None, timeout: float = 10.0) -> Connection:
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(
            host, port, limit=get_config().rpc_stream_buffer_limit
        ),
        timeout,
    )
    return Connection(reader, writer, push_handler)
