"""Raylet-side client for the worker fork server (zygote.py).

Exposes `ZygoteManager.spawn(env) -> ZygoteProc | None`, a synchronous,
non-blocking fork request the dispatch loop can issue in place of a
subprocess.Popen. ZygoteProc mirrors the Popen surface the raylet uses
(pid / poll / kill / terminate / wait / returncode) so WorkerHandle and
the reap loop are agnostic to how the worker was started.

The manager is deliberately loop-agnostic (plain threading, one daemon
reader thread per zygote generation, a mutex around shared state): one
PROCESS-LEVEL zygote serves every raylet/session in the process
(`get_shared_manager`). Children receive their complete environment per
spawn request, so the zygote has no per-cluster state — sharing it
across rt.init cycles saves the warm-interpreter cost on every session
(a large win for test suites and notebooks that init/shutdown
repeatedly).

Generational rotation: Linux reverse-map (anon_vma) chains grow with
the number of COW-faulted siblings forked from one parent, so page
faults in the Nth child slow superlinearly (measured on this kernel:
fork+touch-20MB goes ~24ms -> ~500ms+ with 250+ touched siblings; in
the runtime, worker boots went ~5ms -> ~27ms sys each by ~900 live
workers). The manager therefore retires a zygote after
`zygote_respawn_after` forks and re-execs a fresh one — fresh parent,
fresh chains. A retired generation stays alive (stdin open) purely to
reap and report its remaining children, and is closed once the last of
them exits. The next generation pre-warms in the background so rotation
never stalls a spawn.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Dict, Optional


class ZygoteProc:
    """Popen-compatible handle for a zygote-forked worker.

    The pid arrives asynchronously (the fork reply is read off the
    zygote's stdout by the manager's reader thread); kill/terminate
    before the pid is known are remembered and delivered on assignment.
    """

    def __init__(self, mgr: "ZygoteManager"):
        self._mgr = mgr
        self.pid: Optional[int] = None
        self.returncode: Optional[int] = None
        self._pending_signal: Optional[int] = None

    def _assign_locked(self, pid: int) -> None:
        # _locked suffix: only ever called with the manager lock held
        # (the reader thread's fork-reply dispatch).
        self.pid = pid
        if self._pending_signal is not None:
            sig, self._pending_signal = self._pending_signal, None
            self._kill_locked(sig)

    def _fail_locked(self, rc: int) -> None:
        # _locked suffix: caller (reader-thread EOF path) holds the
        # manager lock; racing poll() writes the same field under it.
        if self.returncode is None:
            self.returncode = rc

    @staticmethod
    def _deliver(pid: int, sig: int) -> None:
        try:
            os.kill(pid, sig)
        except (ProcessLookupError, PermissionError):
            pass

    def _kill_locked(self, sig: int) -> None:
        # _locked suffix: only called from _assign_locked, with the
        # manager lock held.
        if self.returncode is None and self.pid is not None:
            self._deliver(self.pid, sig)

    def _signal(self, sig: int) -> None:
        with self._mgr._lock:
            if self.returncode is not None:
                return
            if self.pid is None:
                self._pending_signal = sig
                return
            pid = self.pid
        self._deliver(pid, sig)

    def poll(self) -> Optional[int]:
        with self._mgr._lock:
            if self.returncode is None and self.pid is not None:
                rc = self._mgr._dead.pop(self.pid, None)
                if rc is not None:
                    self.returncode = rc
            return self.returncode

    def kill(self) -> None:
        self._signal(signal.SIGKILL)

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            rc = self.poll()
            if rc is not None:
                return rc
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("zygote-worker", timeout or 0)
            time.sleep(0.01)


class _Generation:
    """One zygote process plus its in-flight and live children."""

    __slots__ = ("proc", "pending", "spawned", "live", "retiring")

    def __init__(self, proc: subprocess.Popen):
        self.proc = proc
        self.pending: deque[ZygoteProc] = deque()
        self.spawned = 0  # forks requested of this zygote
        self.live = 0  # children forked and not yet reported dead
        self.retiring = False  # no new spawns; close when live hits 0

    def alive(self) -> bool:
        return self.proc.poll() is None

    def close(self) -> None:
        try:
            self.proc.stdin.close()
        except Exception:  # noqa: BLE001
            pass
        try:
            self.proc.terminate()
        except Exception:  # noqa: BLE001
            pass


class ZygoteManager:
    def __init__(self, base_env: Optional[dict] = None):
        # The zygote itself must not import jax: strip the TPU tunnel
        # trigger from its environment (children get their own env per
        # spawn request and attach the backend lazily).
        env = dict(base_env if base_env is not None else os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        self._base_env = env
        self._gen: Optional[_Generation] = None
        self._next: Optional[_Generation] = None  # pre-warming successor
        self._old: list[_Generation] = []  # retired, still reaping
        self._dead: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._deaths = 0  # unexpected zygote deaths; disable after 3

    # Kept for tests / introspection.
    @property
    def proc(self) -> Optional[subprocess.Popen]:
        return self._gen.proc if self._gen is not None else None  # rtlint: disable=RT010 — introspection-only racy read (tests)

    def alive(self) -> bool:
        return self._gen is not None and self._gen.alive()

    def _start_generation(self) -> Optional[_Generation]:
        """Exec a fresh zygote (sync, cheap — the import cost is paid
        inside the zygote, not here) and attach its reader thread."""
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.zygote"],
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=None,
                env=self._base_env,
                text=True,
                bufsize=1,
            )
        except Exception:  # noqa: BLE001 — caller falls back to Popen spawns
            return None
        gen = _Generation(proc)
        # A dedicated DAEMON thread, not run_in_executor: a blocked
        # readline in a loop's default executor is a non-daemon thread
        # that keeps the interpreter alive at exit.
        threading.Thread(
            target=self._read_loop, args=(gen,),
            name="zygote-reader", daemon=True,
        ).start()
        return gen

    def start(self) -> bool:
        if self.alive():
            return True
        self._gen = self._start_generation()
        return self._gen is not None

    def _read_loop(self, gen: _Generation) -> None:
        """Daemon thread: reads one zygote's replies, applies them under
        the manager lock."""
        proc = gen.proc
        while True:
            try:
                line = proc.stdout.readline()
            except Exception:  # noqa: BLE001
                line = ""
            if not line:
                with self._lock:
                    if not gen.retiring:
                        self._deaths += 1
                    # Pending forks never happened (retiring or not):
                    # their handles must resolve or callers poll forever.
                    while gen.pending:
                        gen.pending.popleft()._fail_locked(-1)
                    if self._gen is gen:
                        self._gen = None
                    if gen in self._old:
                        self._old.remove(gen)
                return
            try:
                msg = json.loads(line)
            except json.JSONDecodeError:
                continue
            with self._lock:
                op = msg.get("op")
                if op == "spawned" and gen.pending:
                    gen.pending.popleft()._assign_locked(msg["pid"])
                    gen.live += 1
                elif op == "dead":
                    if len(self._dead) > 4096:  # unconsumed-notice backstop
                        self._dead.clear()
                    self._dead[msg["pid"]] = msg["rc"]
                    gen.live -= 1
                    if gen.retiring and gen.live <= 0 and not gen.pending:
                        # Last child reaped and no fork reply in flight:
                        # the retired zygote's only remaining job is done.
                        gen.close()
                        if gen in self._old:
                            self._old.remove(gen)

    def _rotate_locked(self) -> None:
        """Retire the current generation and promote the pre-warmed
        successor (or start one). Called under the lock."""
        gen = self._gen
        if gen is not None:
            gen.retiring = True
            if gen.live <= 0 and not gen.pending:
                gen.close()
            else:
                self._old.append(gen)
        nxt, self._next = self._next, None
        if nxt is not None and nxt.alive():
            self._gen = nxt
        else:
            self._gen = self._start_generation()

    def spawn(self, env: dict) -> Optional[ZygoteProc]:
        """Queue a fork request; returns None when the zygote can't serve
        (caller uses a normal Popen spawn).

        The whole liveness-check + enqueue + stdin write happens under
        the manager lock: with the manager process-shared, two sessions'
        threads spawning concurrently must observe the same FIFO order in
        _pending as on the pipe (else the reader assigns pids to the
        wrong handles), and must not double-start the zygote."""
        from ray_tpu._private.config import get_config

        limit = max(1, get_config().zygote_respawn_after)
        with self._lock:
            if self._deaths >= 3:
                return None  # repeatedly crashing: stick to Popen spawns
            if self._gen is not None and self._gen.spawned >= limit:
                self._rotate_locked()
            if (self._gen is None or not self._gen.alive()) and not self.start():
                return None
            gen = self._gen
            # Pre-warm the successor while the current zygote still has
            # headroom: by rotation time its interpreter boot is done.
            if gen.spawned >= int(limit * 0.7) and self._next is None:
                self._next = self._start_generation()
            zp = ZygoteProc(self)
            gen.pending.append(zp)
            try:
                gen.proc.stdin.write(
                    json.dumps({"op": "spawn", "env": env}) + "\n"
                )
                gen.proc.stdin.flush()
            except Exception:  # noqa: BLE001 — zygote just died
                try:
                    gen.pending.remove(zp)
                except ValueError:
                    pass
                return None
            gen.spawned += 1
            return zp

    def stop(self) -> None:
        with self._lock:
            gens = [g for g in (self._gen, self._next, *self._old) if g]
            self._gen = None
            self._next = None
            self._old = []
            # Intentional shutdown: the reader threads will see EOF when
            # close() lands — mark every generation retiring FIRST so
            # those EOFs don't count toward _deaths (3 cumulative
            # stop/start cycles would otherwise permanently disable the
            # manager and push every spawn onto the slow Popen path).
            for g in gens:
                g.retiring = True
        for g in gens:
            g.close()


_shared: Optional[ZygoteManager] = None
_shared_lock = threading.Lock()


def get_shared_manager() -> ZygoteManager:
    """The process-level zygote: shared across raylets/sessions (children
    are fully parameterized by their per-spawn environment)."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = ZygoteManager()
            atexit.register(_shared.stop)
        return _shared
