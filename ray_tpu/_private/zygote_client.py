"""Raylet-side client for the worker fork server (zygote.py).

Exposes `ZygoteManager.spawn(env) -> ZygoteProc | None`, a synchronous,
non-blocking fork request the dispatch loop can issue in place of a
subprocess.Popen. ZygoteProc mirrors the Popen surface the raylet uses
(pid / poll / kill / terminate / wait / returncode) so WorkerHandle and
the reap loop are agnostic to how the worker was started.

The manager is deliberately loop-agnostic (plain threading, one daemon
reader thread, a mutex around shared state): one PROCESS-LEVEL zygote
serves every raylet/session in the process (`get_shared_manager`).
Children receive their complete environment per spawn request, so the
zygote has no per-cluster state — sharing it across rt.init cycles saves
the warm-interpreter cost on every session (a large win for test suites
and notebooks that init/shutdown repeatedly).
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Dict, Optional


class ZygoteProc:
    """Popen-compatible handle for a zygote-forked worker.

    The pid arrives asynchronously (the fork reply is read off the
    zygote's stdout by the manager's reader thread); kill/terminate
    before the pid is known are remembered and delivered on assignment.
    """

    def __init__(self, mgr: "ZygoteManager"):
        self._mgr = mgr
        self.pid: Optional[int] = None
        self.returncode: Optional[int] = None
        self._pending_signal: Optional[int] = None

    def _assign(self, pid: int) -> None:
        # Called under the manager lock.
        self.pid = pid
        if self._pending_signal is not None:
            sig, self._pending_signal = self._pending_signal, None
            self._kill(sig)

    def _fail(self, rc: int) -> None:
        if self.returncode is None:
            self.returncode = rc

    @staticmethod
    def _deliver(pid: int, sig: int) -> None:
        try:
            os.kill(pid, sig)
        except (ProcessLookupError, PermissionError):
            pass

    def _kill(self, sig: int) -> None:
        if self.returncode is None and self.pid is not None:
            self._deliver(self.pid, sig)

    def _signal(self, sig: int) -> None:
        with self._mgr._lock:
            if self.returncode is not None:
                return
            if self.pid is None:
                self._pending_signal = sig
                return
        self._deliver(self.pid, sig)

    def poll(self) -> Optional[int]:
        with self._mgr._lock:
            if self.returncode is None and self.pid is not None:
                rc = self._mgr._dead.pop(self.pid, None)
                if rc is not None:
                    self.returncode = rc
            return self.returncode

    def kill(self) -> None:
        self._signal(signal.SIGKILL)

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("zygote-worker", timeout or 0)
            time.sleep(0.01)
        return self.returncode  # type: ignore[return-value]


class ZygoteManager:
    def __init__(self, base_env: Optional[dict] = None):
        # The zygote itself must not import jax: strip the TPU tunnel
        # trigger from its environment (children get their own env per
        # spawn request and attach the backend lazily).
        env = dict(base_env if base_env is not None else os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        self._base_env = env
        self.proc: Optional[subprocess.Popen] = None
        self._pending: deque[ZygoteProc] = deque()
        self._dead: Dict[int, int] = {}
        self._reader: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._deaths = 0  # zygote process deaths; disable after 3

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def start(self) -> bool:
        """Start the zygote process (sync, cheap — the import cost is paid
        inside the zygote, not here)."""
        if self.alive():
            return True
        try:
            self.proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.zygote"],
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=None,
                env=self._base_env,
                text=True,
                bufsize=1,
            )
        except Exception:  # noqa: BLE001 — caller falls back to Popen spawns
            self.proc = None
            return False
        # A dedicated DAEMON thread, not run_in_executor: a blocked
        # readline in a loop's default executor is a non-daemon thread
        # that keeps the interpreter alive at exit.
        self._reader = threading.Thread(
            target=self._read_loop, args=(self.proc,),
            name="zygote-reader", daemon=True,
        )
        self._reader.start()
        return True

    def _read_loop(self, proc: subprocess.Popen) -> None:
        """Daemon thread: reads zygote replies, applies them under lock."""
        while True:
            try:
                line = proc.stdout.readline()
            except Exception:  # noqa: BLE001
                line = ""
            if not line:
                with self._lock:
                    # Pending forks never happened.
                    self._deaths += 1
                    while self._pending:
                        self._pending.popleft()._fail(-1)
                return
            try:
                msg = json.loads(line)
            except json.JSONDecodeError:
                continue
            with self._lock:
                op = msg.get("op")
                if op == "spawned" and self._pending:
                    self._pending.popleft()._assign(msg["pid"])
                elif op == "dead":
                    if len(self._dead) > 4096:  # unconsumed-notice backstop
                        self._dead.clear()
                    self._dead[msg["pid"]] = msg["rc"]

    def spawn(self, env: dict) -> Optional[ZygoteProc]:
        """Queue a fork request; returns None when the zygote can't serve
        (caller uses a normal Popen spawn).

        The whole liveness-check + enqueue + stdin write happens under
        the manager lock: with the manager process-shared, two sessions'
        threads spawning concurrently must observe the same FIFO order in
        _pending as on the pipe (else the reader assigns pids to the
        wrong handles), and must not double-start the zygote."""
        with self._lock:
            if self._deaths >= 3:
                return None  # repeatedly crashing: stick to Popen spawns
            if not self.alive() and not self.start():
                return None
            zp = ZygoteProc(self)
            self._pending.append(zp)
            try:
                self.proc.stdin.write(
                    json.dumps({"op": "spawn", "env": env}) + "\n"
                )
                self.proc.stdin.flush()
            except Exception:  # noqa: BLE001 — zygote just died
                try:
                    self._pending.remove(zp)
                except ValueError:
                    pass
                return None
            return zp

    def stop(self) -> None:
        if self.proc is not None:
            try:
                self.proc.stdin.close()
            except Exception:  # noqa: BLE001
                pass
            try:
                self.proc.terminate()
            except Exception:  # noqa: BLE001
                pass
            self.proc = None
        self._reader = None  # daemon thread exits on pipe EOF


_shared: Optional[ZygoteManager] = None
_shared_lock = threading.Lock()


def get_shared_manager() -> ZygoteManager:
    """The process-level zygote: shared across raylets/sessions (children
    are fully parameterized by their per-spawn environment)."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = ZygoteManager()
            atexit.register(_shared.stop)
        return _shared
