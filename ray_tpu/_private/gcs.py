"""Global Control Service: the cluster metadata authority.

TPU-native analog of the reference GCS server
(src/ray/gcs/gcs_server/gcs_server.h:78, entry gcs_server_main.cc:40) with
its managers collapsed into one asyncio process:

  * node table + health checks   (GcsNodeManager, GcsHealthCheckManager,
                                  gcs_health_check_manager.h:39)
  * resource views               (GcsResourceManager + ray_syncer — here the
                                  raylets push deltas over their persistent
                                  RPC connection instead of a separate
                                  bidi-stream service, ray_syncer.h:88)
  * actor table + scheduling     (GcsActorManager, gcs_actor_manager.cc:255,
                                  GcsActorScheduler::ScheduleByGcs,
                                  gcs_actor_scheduler.cc:60)
  * placement groups             (GcsPlacementGroupManager two-phase
                                  prepare/commit, gcs_placement_group_scheduler.h)
  * KV store                     (GcsKvManager / StoreClientInternalKV,
                                  store_client_kv.h; in-memory store client,
                                  in_memory_store_client.h:31)
  * object directory             (ownership_based_object_directory.h — here a
                                  GCS table since owners and the directory
                                  share a process boundary anyway on TPU pods)
  * pubsub                       (src/ray/pubsub/publisher.h:307 — long-poll
                                  replaced by server-push frames)
  * job table + function exports (GcsJobManager, GcsFunctionManager)
  * task events                  (GcsTaskManager task-event sink, powers the
                                  state API)
"""

from __future__ import annotations

import asyncio
import os
import struct
import time
from bisect import bisect_left
from collections import defaultdict
from typing import Any, Dict, List, Optional, Set

from ray_tpu._private.config import get_config
from ray_tpu._private.protocol import RpcServer, ServerConnection
from ray_tpu.util import journal

#: Bucket boundaries (seconds) for the per-method server-side RPC latency
#: histograms — matches util.metrics.LATENCY_BOUNDARIES so gcs_rpc_*
#: series quantile the same way client-side metrics do. Kept as a local
#: copy: the GCS process must not import the client metrics registry.
_RPC_LATENCY_BOUNDS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

#: Bucket boundaries (seconds) for preempt_grace_seconds: how long a victim
#: gang actually took from eviction notice to releasing its bundles. Spans
#: sub-second cooperative drains up to multi-minute stragglers that hit the
#: hard-kill deadline.
_PREEMPT_GRACE_BOUNDS = (
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

#: Task-event ring capacity (GcsTaskManager's task_events_max_num_task_
#: in_gcs analog). Evictions are counted so consumers can detect
#: truncation instead of silently missing history.
_TASK_EVENTS_CAP = 100_000


#: Handlers that mutate durable tables; each marks the snapshot dirty.
_WRITE_METHODS = {
    "kv_put", "kv_del",
    "register_actor", "actor_ready", "kill_actor", "worker_dead",
    "register_job", "submit_job", "job_update", "job_log_append", "stop_job",
    "create_placement_group", "remove_placement_group",
    "release_pg_bundles", "reserve_pg_bundles",
    "object_location_add", "object_location_remove", "object_spilled",
    "objects_freed",
}


class GcsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 persist_path: Optional[str] = None):
        self.rpc = RpcServer(host, port)
        self.host = host
        # Fault tolerance: durable tables snapshot to persist_path (debounced
        # + atomic rename) and restore on restart; live state (nodes,
        # connections, waiters) is rebuilt as raylets reconnect within a
        # heartbeat. The role of the reference's Redis store client
        # (gcs/store_client/redis_store_client.h:33), file-backed.
        self.persist_path = persist_path
        self._persist_dirty = False
        self._persist_task: Optional[asyncio.Task] = None
        # Write-ahead log (gcs_table_storage.h / redis_store_client.h:33
        # role): every durable mutation appends a seq-numbered record
        # BEFORE its reply, so an abrupt GCS kill loses nothing that was
        # acknowledged — the debounced snapshot is only WAL compaction.
        self._wal_path = persist_path + ".wal" if persist_path else None
        self._wal_old_path = persist_path + ".wal.old" if persist_path else None
        self._wal_fh = None
        self._wal_seq = 0
        self._wal_bytes = 0
        self._wal_compact_bytes = get_config().gcs_wal_compact_bytes
        self._wal_fsync = get_config().gcs_wal_fsync
        self._base_handlers: Dict[str, Any] = {}
        # tables
        self.kv: Dict[str, Dict[bytes, bytes]] = defaultdict(dict)  # namespace -> k -> v
        self.nodes: Dict[bytes, dict] = {}  # node_id -> info
        self.node_conns: Dict[bytes, ServerConnection] = {}
        self.actors: Dict[bytes, dict] = {}  # actor_id -> info
        self.named_actors: Dict[tuple, bytes] = {}  # (namespace, name) -> actor_id
        self.jobs: Dict[bytes, dict] = {}
        self.placement_groups: Dict[bytes, dict] = {}
        self.object_dir: Dict[bytes, dict] = {}  # object_id -> {nodes: set, size}
        self._partial_seq = 0  # chain-seniority counter for partial pulls
        self.object_waiters: Dict[bytes, List[asyncio.Event]] = defaultdict(list)
        self.task_events: List[dict] = []  # ring buffer of task state events
        # Aggregated user metrics: name -> {type, description, boundaries?,
        #   series: {tags_tuple -> value | histogram-state}}
        self.metrics: Dict[str, dict] = {}
        self._metrics_seq: Dict[bytes, int] = {}  # reporter -> last seq
        self.subscribers: Dict[str, Set[ServerConnection]] = defaultdict(set)
        self.pending_actors: Set[bytes] = set()
        self.pending_pgs: Set[bytes] = set()
        self.pg_counter = 0
        # -- preemption (priority chip reclamation) ----------------------
        # victim_pg_id -> record. A record is born "draining" when the
        # reclamation pass marks the victim's nodes, flips to "released"
        # when the victim gives its placement group back (cooperatively or
        # via the hard-kill deadline), and is pruned from the tail of the
        # history once preempt_history_limit is exceeded. Live-only state:
        # fences and drains are re-derived after a GCS restart by the next
        # reclamation pass.
        self.preemptions: Dict[bytes, dict] = {}
        # Resize obligations: victim_pg_id -> record. Born "armed" when a
        # partially-reclaimed gang releases exactly the claimed bundles
        # (elastic shrink instead of eviction); flips to "lifted" when the
        # claimant releases — the fence-lift signal the trainer's
        # grow-back path polls via get_resize_state. Dropped once the
        # victim re-reserves the bundles (or is itself removed).
        self.resize_obligations: Dict[bytes, dict] = {}
        # Sentinel claimant ids minted by chaos.reclaim_chips: they hold
        # their reclamation fences (the fence sweep treats them as
        # forever-waiting) until chaos.lift_fence clears them.
        self.chaos_claims: Set[bytes] = set()
        # preempt_total{tenant,reason} counter state, exported as a
        # synthetic series from h_metrics_snapshot like gcs_rpc_*.
        self.preempt_counts: Dict[tuple, float] = {}
        # preempt_grace_seconds histogram state (notice -> release).
        self.preempt_grace = {
            "buckets": [0] * (len(_PREEMPT_GRACE_BOUNDS) + 1),
            "sum": 0.0, "count": 0,
        }
        self._started = asyncio.Event()
        self._stopping = False
        self._health_task: Optional[asyncio.Task] = None
        # Per-component runtime metrics (stats/metric_defs.h role): RPC
        # volume by method, exported through gcs_stats -> /metrics.
        self.rpc_counts: Dict[str, int] = defaultdict(int)
        self.rpc.on_request = (
            lambda method: self.rpc_counts.__setitem__(
                method, self.rpc_counts[method] + 1
            )
        )
        # Per-method handler-latency accounting (count/sum/max + fixed
        # buckets), feeding `rt rpc` and the gcs_rpc_server_seconds
        # series in metrics_snapshot. Makes "N GCS round-trips per actor
        # birth, at M µs each" a reported number.
        self.rpc_latency: Dict[str, dict] = {}
        self.rpc.on_complete = self._rpc_complete
        # Evicted-task-event count: lets list_task_events consumers warn
        # on truncated history instead of silently under-reporting.
        self._task_events_dropped = 0
        # Cluster-wide runtime profiling config (`rt profile --on`):
        # stored here, broadcast to every connected client over the
        # profile_config pubsub channel (server-originated; clients may
        # not publish to it).
        self.profile_config: Dict[str, Any] = {}
        # Postmortem bundles minted by journal_trigger (cluster black
        # box): the GCS is the single trigger authority so a cluster-wide
        # failure storm collapses into one bundle per cooldown window.
        self.postmortems: List[dict] = []
        self._pm_seq = 0
        self._pm_last_mono = 0.0
        self._pm_last_payload: Optional[dict] = None
        journal.set_process_label("gcs", weak=True)

        r = self.rpc.register
        # kv
        r("kv_put", self.h_kv_put)
        r("kv_get", self.h_kv_get)
        r("kv_del", self.h_kv_del)
        r("kv_keys", self.h_kv_keys)
        # nodes
        r("register_node", self.h_register_node)
        r("get_nodes", self.h_get_nodes)
        r("resource_update", self.h_resource_update)
        r("drain_node", self.h_drain_node)
        r("cordon_node", self.h_cordon_node)
        r("node_drain_status", self.h_node_drain_status)
        # actors
        r("register_actor", self.h_register_actor)
        r("actor_ready", self.h_actor_ready)
        r("get_actor", self.h_get_actor)
        r("get_named_actor", self.h_get_named_actor)
        r("list_actors", self.h_list_actors)
        r("kill_actor", self.h_kill_actor)
        r("worker_dead", self.h_worker_dead)
        # jobs
        r("register_job", self.h_register_job)
        r("list_jobs", self.h_list_jobs)
        # job submission (dashboard/modules/job analog)
        r("submit_job", self.h_submit_job)
        r("get_job", self.h_get_job)
        r("job_update", self.h_job_update)
        r("job_log_append", self.h_job_log_append)
        r("job_logs", self.h_job_logs)
        r("stop_job", self.h_stop_job)
        # objects
        r("object_location_add", self.h_object_location_add)
        r("object_locations_add", self.h_object_locations_add)
        r("object_location_get", self.h_object_location_get)
        r("object_location_wait", self.h_object_location_wait)
        r("object_location_remove", self.h_object_location_remove)
        r("object_spilled", self.h_object_spilled)
        r("objects_freed", self.h_objects_freed)
        r("list_objects", self.h_list_objects)
        # placement groups
        r("create_placement_group", self.h_create_pg)
        r("remove_placement_group", self.h_remove_pg)
        r("get_placement_group", self.h_get_pg)
        r("list_placement_groups", self.h_list_pgs)
        # elastic resize (partial bundle release / grow-back)
        r("release_pg_bundles", self.h_release_pg_bundles)
        r("reserve_pg_bundles", self.h_reserve_pg_bundles)
        r("get_resize_state", self.h_get_resize_state)
        # preemption
        r("get_preemptions", self.h_get_preemptions)
        r("preempt_node", self.h_preempt_node)
        r("chaos_reclaim_chips", self.h_chaos_reclaim_chips)
        r("chaos_lift_fence", self.h_chaos_lift_fence)
        # pubsub
        r("subscribe", self.h_subscribe)
        r("publish", self.h_publish)
        # task events / state API
        r("add_task_events", self.h_add_task_events)
        r("list_task_events", self.h_list_task_events)
        # metrics (stats agent + prometheus_exporter analog)
        r("metrics_report", self.h_metrics_report)
        r("metrics_snapshot", self.h_metrics_snapshot)
        r("gcs_stats", self.h_gcs_stats)
        # control-plane profiler (runtime sampling toggle)
        r("set_profile_config", self.h_set_profile_config)
        r("get_profile_config", self.h_get_profile_config)
        # cluster black box (util/journal.py): failure-triggered capture
        r("journal_trigger", self.h_journal_trigger)
        r("get_postmortems", self.h_get_postmortems)
        # misc
        r("ping", self.h_ping)

        self.rpc.on_disconnect = self._on_disconnect

        if self.persist_path:
            if os.path.exists(self.persist_path):
                self._restore(self.persist_path)
            for name in _WRITE_METHODS:
                self._base_handlers[name] = self.rpc.handlers[name]
                self.rpc.handlers[name] = self._wrap_durable(
                    name, self.rpc.handlers[name]
                )

    # -- persistence ----------------------------------------------------
    def _wrap_durable(self, name, handler):
        async def wrapped(d, conn):
            # True write-AHEAD, at handler entry: handlers that await
            # mid-mutation (e.g. placement-group creation pushing bundle
            # reservations) would otherwise log in completion order, and
            # replay could resurrect state a concurrent delete removed.
            # Entry order == mutation-start order on this single loop.
            # (A handler that then fails leaves a record whose replay
            # deterministically fails the same way — harmless.)
            self._wal_append(name, d)
            out = await handler(d, conn)
            self._mark_dirty()
            return out

        return wrapped

    def _mark_dirty(self):
        if not self.persist_path:
            return
        self._persist_dirty = True
        if self._persist_task is None or self._persist_task.done():
            self._persist_task = asyncio.ensure_future(self._persist_soon())

    # -- write-ahead log -------------------------------------------------
    def _wal_append(self, method: str, payload: Any):
        if not self._wal_path:
            return
        import msgpack

        if self._wal_fh is None:
            self._wal_fh = open(self._wal_path, "ab")
        self._wal_seq += 1
        body = msgpack.packb(
            {"s": self._wal_seq, "m": method, "d": payload}, use_bin_type=True
        )
        rec = struct.pack("<I", len(body)) + body
        self._wal_fh.write(rec)
        self._wal_fh.flush()
        if self._wal_fsync:
            os.fsync(self._wal_fh.fileno())
        self._wal_bytes += len(rec)
        if self._wal_bytes >= self._wal_compact_bytes:
            self._mark_dirty()  # snapshot write doubles as compaction

    def _rotate_wal(self) -> bool:
        """Move the live WAL aside before a snapshot lands; returns True
        if there is a .old file to delete once the snapshot succeeds. A
        previously-failed compaction's .old is folded together with the
        current file so at most two WAL files ever exist."""
        if not self._wal_path or not os.path.exists(self._wal_path):
            return os.path.exists(self._wal_old_path or "")
        if self._wal_fh is not None:
            self._wal_fh.close()
            self._wal_fh = None
        if os.path.exists(self._wal_old_path):
            with open(self._wal_old_path, "ab") as dst, \
                    open(self._wal_path, "rb") as src:
                dst.write(src.read())
            os.remove(self._wal_path)
        else:
            os.rename(self._wal_path, self._wal_old_path)
        self._wal_bytes = 0
        return True

    @staticmethod
    def _read_wal_records(path: str):
        """Yield (seq, method, payload); a torn tail record (crash mid-
        append) terminates the stream cleanly."""
        import msgpack

        with open(path, "rb") as f:
            data = f.read()
        pos = 0
        while pos + 4 <= len(data):
            (length,) = struct.unpack_from("<I", data, pos)
            if pos + 4 + length > len(data):
                break  # torn tail
            try:
                rec = msgpack.unpackb(
                    data[pos + 4:pos + 4 + length],
                    raw=False, strict_map_key=False,
                )
            except Exception:  # noqa: BLE001 — corrupt tail
                break
            yield rec["s"], rec["m"], rec["d"]
            pos += 4 + length

    async def _replay_wal(self):
        """Redo acknowledged mutations newer than the snapshot."""
        covered = self._wal_seq

        class _ReplayConn:
            closed = True
            meta: Dict[str, Any] = {}

            async def push(self, *_a, **_k):
                pass

            async def respond(self, *_a, **_k):
                pass

        conn = _ReplayConn()
        replayed = 0
        for path in (self._wal_old_path, self._wal_path):
            if not path or not os.path.exists(path):
                continue
            for seq, method, payload in self._read_wal_records(path):
                if seq <= covered:
                    continue
                handler = self._base_handlers.get(method)
                if handler is None:
                    continue
                try:
                    await handler(payload, conn)
                    replayed += 1
                except Exception:  # noqa: BLE001 — redo is best-effort per record
                    pass
                self._wal_seq = max(self._wal_seq, seq)
        if replayed:
            from ray_tpu.util.event import record_event

            record_event("gcs", "recovered from write-ahead log",
                         severity="INFO", replayed_records=replayed)
            self._mark_dirty()

    def _snapshot_bytes(self) -> bytes:
        import pickle

        return pickle.dumps(
            {
                "kv": {ns: dict(kvs) for ns, kvs in self.kv.items()},
                "jobs": self.jobs,
                "actors": self.actors,
                "named_actors": self.named_actors,
                "placement_groups": self.placement_groups,
                "object_dir": self.object_dir,
                "pg_counter": self.pg_counter,
                "wal_seq": self._wal_seq,
            }
        )

    @staticmethod
    def _write_snapshot(path: str, data: bytes):
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    async def _persist_soon(self):
        while self._persist_dirty:
            self._persist_dirty = False
            await asyncio.sleep(get_config().gcs_persist_debounce_s)
            # Pickle on the loop (tables are mutated by handlers on this
            # loop, so a thread would race them) but write in an executor —
            # the disk I/O is the slow part and must not head-of-line-block
            # heartbeats and scheduling.
            data = self._snapshot_bytes()
            had_old = self._rotate_wal()
            try:
                await asyncio.get_event_loop().run_in_executor(
                    None, self._write_snapshot, self.persist_path, data
                )
            except Exception:  # noqa: BLE001 — .old stays; replay covers it
                continue
            # Snapshot covers every rotated record: compaction complete.
            if had_old:
                try:
                    os.remove(self._wal_old_path)
                except OSError:
                    pass

    def _restore(self, path: str):
        import pickle

        with open(path, "rb") as f:
            snap = pickle.load(f)
        for ns, kvs in snap.get("kv", {}).items():
            self.kv[ns].update(kvs)
        self.jobs.update(snap.get("jobs", {}))
        self.actors.update(snap.get("actors", {}))
        self.named_actors.update(snap.get("named_actors", {}))
        self.placement_groups.update(snap.get("placement_groups", {}))
        self.object_dir.update(snap.get("object_dir", {}))
        self.pg_counter = snap.get("pg_counter", self.pg_counter)
        self._wal_seq = snap.get("wal_seq", 0)

    # ------------------------------------------------------------------
    async def start(self) -> int:
        if self.persist_path:
            # Redo acknowledged-but-unsnapshotted mutations before the
            # listener opens — clients must never observe pre-replay state.
            await self._replay_wal()
        port = await self.rpc.start()
        self._health_task = asyncio.ensure_future(self._health_loop())
        self._started.set()
        return port

    async def stop(self):
        # Stop flag first: the connection teardown below triggers
        # _on_disconnect for every peer, which would otherwise mark nodes
        # (and their actors) dead and persist that into the snapshot a
        # restarted GCS restores from.
        self._stopping = True
        if self._health_task:
            self._health_task.cancel()
        persist_pending = (
            self._persist_task is not None and not self._persist_task.done()
        )
        if persist_pending:
            self._persist_task.cancel()
        if self.persist_path and (self._persist_dirty or persist_pending):
            # Flush acknowledged-but-debounced mutations synchronously: a
            # clean shutdown must not lose the last 50ms of writes (the
            # loop clears the dirty flag before its debounce sleep, so a
            # cancelled-in-flight task also means unflushed writes).
            self._persist_dirty = False
            data = self._snapshot_bytes()
            had_old = self._rotate_wal()
            self._write_snapshot(self.persist_path, data)
            # The final snapshot covers everything: drop compacted WALs.
            if had_old:
                try:
                    os.remove(self._wal_old_path)
                except OSError:
                    pass
        if self._wal_fh is not None:
            try:
                self._wal_fh.close()
            except OSError:
                pass
            self._wal_fh = None
        await self.rpc.stop()

    async def kill(self):
        """Abrupt death for fault injection: no final snapshot — only the
        per-write WAL flushes survive, which is the point: chaos tests
        validate WAL replay from exactly this state (the in-process
        equivalent of `kill -9` on the GCS)."""
        self._stopping = True
        if self._health_task:
            self._health_task.cancel()
        if self._persist_task is not None and not self._persist_task.done():
            self._persist_task.cancel()
        if self._wal_fh is not None:
            try:
                self._wal_fh.close()
            except OSError:
                pass
            self._wal_fh = None
        await self.rpc.stop()

    async def publish(self, channel: str, payload: Any):
        dead = []
        for conn in list(self.subscribers.get(channel, ())):
            if conn.closed:
                dead.append(conn)
            else:
                await conn.push(channel, payload)
        for c in dead:
            self.subscribers[channel].discard(c)

    async def _on_disconnect(self, conn: ServerConnection):
        if self._stopping:
            return  # our own teardown, not a peer death
        for subs in self.subscribers.values():
            subs.discard(conn)
        node_id = conn.meta.get("node_id")
        if node_id and node_id in self.nodes:
            await self._mark_node_dead(node_id, "connection lost")

    async def _health_loop(self):
        cfg = get_config()
        tick = 0
        sleep_s = min(0.25, cfg.health_check_period_s)
        last_wake = time.monotonic()
        while True:
            await asyncio.sleep(sleep_s)
            tick += 1
            # Suspend detection (the standard failure-detector guard, cf.
            # phi-accrual): if this loop itself just missed its deadline —
            # event-loop stall, GC pause, machine suspend — the monitor
            # was deaf for that window and cannot distinguish "node
            # silent" from "I wasn't listening". Forgive the pause
            # instead of charging it against every node's heartbeat.
            now = time.monotonic()
            pause = now - last_wake - sleep_s
            last_wake = now
            if pause > cfg.health_check_period_s:
                for info in self.nodes.values():
                    info["last_heartbeat"] = min(
                        now, info["last_heartbeat"] + pause
                    )
            # Retry pending actors as the resource view changes — highest
            # priority first, so a spike's demand is considered before the
            # best-effort tier it may be about to evict.
            for actor_id in sorted(
                self.pending_actors,
                key=lambda aid: -int(
                    (self.actors.get(aid) or {}).get("priority") or 0
                ),
            ):
                a = self.actors.get(actor_id)
                if a is None or a["state"] not in ("PENDING", "RESTARTING"):
                    self.pending_actors.discard(actor_id)
                    continue
                if await self._schedule_actor(actor_id):
                    self.pending_actors.discard(actor_id)
                else:
                    self._maybe_preempt(
                        actor_id,
                        a.get("name") or a.get("class_name") or "actor",
                        int(a.get("priority") or 0),
                        [a.get("resources") or {}],
                        "PACK",
                    )
            # Retry pending placement groups, priority first.
            for pg_id in sorted(
                self.pending_pgs,
                key=lambda pid: -int(
                    (self.placement_groups.get(pid) or {}).get("priority")
                    or 0
                ),
            ):
                pg = self.placement_groups.get(pg_id)
                if pg is None or pg["state"] != "PENDING":
                    self.pending_pgs.discard(pg_id)
                    continue
                result = await self._try_reserve_pg(pg)
                if result.get("ok"):
                    self.pending_pgs.discard(pg_id)
                else:
                    self._maybe_preempt(
                        pg_id,
                        self._pg_tenant(pg),
                        int(pg.get("priority") or 0),
                        pg["bundles"],
                        pg["strategy"],
                    )
            await self._preemption_tick()
            if tick * 0.25 < cfg.health_check_period_s:
                continue
            tick = 0
            now = time.monotonic()
            timeout = cfg.health_check_period_s * cfg.health_check_failure_threshold
            for node_id, info in list(self.nodes.items()):
                if info["state"] == "ALIVE" and now - info["last_heartbeat"] > timeout:
                    await self._mark_node_dead(node_id, "health check timeout")

    async def _mark_node_dead(self, node_id: bytes, reason: str):
        info = self.nodes.get(node_id)
        if not info or info["state"] == "DEAD":
            return
        info["state"] = "DEAD"
        info["death_reason"] = reason
        self.node_conns.pop(node_id, None)
        from ray_tpu.util.event import record_event

        record_event("gcs", f"node marked DEAD: {reason}",
                     severity="ERROR", node_id=node_id.hex())
        journal.emit("gcs.node_dead", node_id=node_id.hex(), reason=reason)
        # Fail actors living on that node; restart if budget remains.
        for actor_id, a in list(self.actors.items()):
            if a.get("node_id") == node_id and a["state"] in ("ALIVE", "PENDING", "RESTARTING"):
                await self._on_actor_failure(actor_id, f"node died: {reason}")
        # Drop object locations on that node; spill copies on its local
        # disk died with it.
        for oid, entry in self.object_dir.items():
            entry["nodes"].discard(node_id)
            if entry.get("spilled", {}).get("node_id") == node_id:
                entry.pop("spilled", None)
        # Fail submitted jobs supervised by that node — their drivers died
        # with it, and no further state updates will ever arrive.
        for j in self.jobs.values():
            if (
                j.get("node_id") == node_id
                and j.get("state") in ("PENDING", "RUNNING")
            ):
                j["state"] = "FAILED"
                j["end_time"] = time.time()
                j["message"] = f"supervising node died: {reason}"
        await self.publish("node_dead", {"node_id": node_id, "reason": reason})
        self._mark_dirty()

    # -- kv -------------------------------------------------------------
    async def h_kv_put(self, d, conn):
        ns = d.get("ns", "")
        overwrite = d.get("overwrite", True)
        table = self.kv[ns]
        if not overwrite and d["key"] in table:
            return {"added": False}
        table[d["key"]] = d["value"]
        return {"added": True}

    async def h_kv_get(self, d, conn):
        return {"value": self.kv[d.get("ns", "")].get(d["key"])}

    async def h_kv_del(self, d, conn):
        return {"deleted": self.kv[d.get("ns", "")].pop(d["key"], None) is not None}

    async def h_kv_keys(self, d, conn):
        prefix = d.get("prefix", b"")
        return {"keys": [k for k in self.kv[d.get("ns", "")] if k.startswith(prefix)]}

    # -- nodes ----------------------------------------------------------
    async def h_register_node(self, d, conn):
        node_id = d["node_id"]
        self.nodes[node_id] = {
            "node_id": node_id,
            "address": d["address"],
            "port": d["port"],
            "object_store_name": d.get("object_store_name"),
            "machine_id": d.get("machine_id"),
            "resources_total": d["resources"],
            "resources_available": dict(d["resources"]),
            "labels": d.get("labels", {}),
            "state": "ALIVE",
            "last_heartbeat": time.monotonic(),
            "is_head": d.get("is_head", False),
        }
        conn.meta["node_id"] = node_id
        self.node_conns[node_id] = conn
        await self.publish("node_added", {"node_id": node_id})
        return {"ok": True}

    async def h_get_nodes(self, d, conn):
        out = []
        for info in self.nodes.values():
            out.append({k: v for k, v in info.items() if k != "last_heartbeat"})
        return {"nodes": out}

    def _rpc_complete(self, method: str, dur_s: float) -> None:
        """RpcServer on_complete hook: fold one served RPC's handler
        latency into the per-method accounting."""
        st = self.rpc_latency.get(method)
        if st is None:
            st = self.rpc_latency[method] = {
                "count": 0, "sum_s": 0.0, "max_s": 0.0,
                "buckets": [0] * (len(_RPC_LATENCY_BOUNDS) + 1),
            }
        st["count"] += 1
        st["sum_s"] += dur_s
        if dur_s > st["max_s"]:
            st["max_s"] = dur_s
        st["buckets"][bisect_left(_RPC_LATENCY_BOUNDS, dur_s)] += 1

    async def h_gcs_stats(self, d, conn):
        """GCS-internal runtime metrics (per-component stats, the
        stats/metric_defs.h role): rpc volume + per-method handler
        latency (count/sum/max/buckets over rpc_latency_boundaries) +
        table sizes. `rt rpc` renders the latency table."""
        return {
            "rpc_counts": dict(self.rpc_counts),
            "rpc_latency": {
                m: dict(st, buckets=list(st["buckets"]))
                for m, st in self.rpc_latency.items()
            },
            "rpc_latency_boundaries": list(_RPC_LATENCY_BOUNDS),
            "nodes_alive": sum(
                1 for n in self.nodes.values() if n["state"] == "ALIVE"
            ),
            "kv_entries": sum(len(t) for t in self.kv.values()),
            "task_events": len(self.task_events),
            "task_events_dropped": self._task_events_dropped,
            "subscriber_conns": sum(
                len(s) for s in self.subscribers.values()
            ),
            "object_dir_entries": len(self.object_dir),
            "placement_groups": len(self.placement_groups),
        }

    async def h_set_profile_config(self, d, conn):
        """Flip control-plane profiling at runtime (`rt profile --on`):
        persist the sampling rate in the GCS and broadcast it so every
        connected client (drivers AND workers) adjusts without restarts.
        Server-originated publish — profile_config is not a client-
        publishable channel."""
        updates = {
            k: d[k] for k in ("task_trace_sample",) if d.get(k) is not None
        }
        self.profile_config.update(updates)
        await self.publish("profile_config", dict(self.profile_config))
        return {"ok": True, "profile_config": dict(self.profile_config)}

    async def h_get_profile_config(self, d, conn):
        return {"profile_config": dict(self.profile_config)}

    async def h_resource_update(self, d, conn):
        """Raylet pushes its resource view (ray_syncer analog:
        versioned deltas with gap detection; full maps as fallback).

        A version gap — anything other than last+1 on a delta — means
        this GCS missed state (restart, dropped ack): reply need_full so
        the raylet rebases with its whole view. Version 1 with a full map
        establishes (or re-establishes) the baseline.
        """
        info = self.nodes.get(d["node_id"])
        if not info:
            # Unknown node (GCS restarted before re-registration): the
            # raylet must re-register; meanwhile ask for a full view.
            return {"ok": False, "need_full": True}
        if "proc_stats" in d:
            info["proc_stats"] = d["proc_stats"]
        ver = d.get("version")
        full = "available" in d
        # need_full replies still carry the draining flag — a version gap
        # must not silently un-cordon the raylet for a beat.
        drain_flag = (
            {"draining": True} if info.get("draining") else {}
        )
        if ver is not None and not full:
            expected = info.get("sync_version")
            if expected is None or ver != expected + 1:
                return {"ok": False, "need_full": True, **drain_flag}
        if full:
            info["resources_available"] = dict(d["available"])
        else:
            avail = info["resources_available"]
            avail.update(d.get("delta", {}))
            for k in d.get("removed", ()):
                avail.pop(k, None)
        if ver is not None:
            info["sync_version"] = ver
        if "total" in d:
            info["resources_total"] = d["total"]
        if "demand_bundles" in d:
            info["demand_bundles"] = d["demand_bundles"]
        info["last_heartbeat"] = time.monotonic()
        return {"ok": True, **drain_flag}

    async def h_drain_node(self, d, conn):
        # Actors still pending on a hard affinity to this node can never
        # place once it is gone: fail them with a clear cause instead of
        # leaving their creators waiting forever.
        for actor_id in list(self.pending_actors):
            a = self.actors.get(actor_id)
            sched = (a or {}).get("scheduling") or {}
            if (
                sched.get("type") == "node_affinity"
                and sched.get("node_id") == d["node_id"]
                and not sched.get("soft", False)
            ):
                self.pending_actors.discard(actor_id)
                a["state"] = "DEAD"
                a["death_cause"] = "hard-affinity node was drained"
                await self.publish(
                    "actor_update:" + actor_id.hex(), self._actor_view(a)
                )
        await self._mark_node_dead(d["node_id"], "drained")
        return {"ok": True}

    async def h_cordon_node(self, d, conn):
        """Graceful drain step 1 (reference: `ray drain-node`,
        autoscaler.proto DrainNode): mark the node draining — every
        placement path skips it, its raylet stops keeping new work local
        (heartbeat replies carry the flag) — while running work finishes.
        Step 2 is polling drain_status until idle, then drain_node."""
        info = self.nodes.get(d["node_id"])
        if not info or info["state"] != "ALIVE":
            return {"ok": False, "error": "node not alive"}
        if info.get("is_head") and not d.get("undo"):
            # Draining the head would fail every supervised job and
            # leave the cluster headless; the reference's DrainNode is
            # a worker-node operation too.
            return {"ok": False, "error": "refusing to drain the head node"}
        info["draining"] = not d.get("undo", False)
        return {"ok": True}

    async def h_node_drain_status(self, d, conn):
        """idle = every resource fully available again (tasks done,
        actors gone, PG bundles returned) and no queued demand."""
        info = self.nodes.get(d["node_id"])
        if not info:
            return {"ok": False, "error": "unknown node"}
        avail, total = info["resources_available"], info["resources_total"]
        # GCS-pending actors hard-affined here block the drain: once the
        # node is removed they could never place (the operator must undo
        # the cordon, or the removal path fails them explicitly).
        blocked_actors = 0
        for actor_id in self.pending_actors:
            a = self.actors.get(actor_id)
            sched = (a or {}).get("scheduling") or {}
            if (
                sched.get("type") == "node_affinity"
                and sched.get("node_id") == d["node_id"]
                and not sched.get("soft", False)
            ):
                blocked_actors += 1
        idle = (
            all(avail.get(k, 0.0) + 1e-6 >= v for k, v in total.items())
            and not info.get("demand_bundles")
            and blocked_actors == 0
        )
        return {
            "ok": True,
            "draining": bool(info.get("draining")),
            "idle": idle,
            "state": info["state"],
            "pending_affinity_actors": blocked_actors,
        }

    # -- jobs -----------------------------------------------------------
    async def h_register_job(self, d, conn):
        self.jobs[d["job_id"]] = {
            "job_id": d["job_id"],
            "driver_pid": d.get("pid"),
            "start_time": time.time(),
            "state": "RUNNING",
            "entrypoint": d.get("entrypoint", ""),
        }
        return {"ok": True}

    async def h_list_jobs(self, d, conn):
        return {"jobs": [self._job_view(j) for j in self.jobs.values()]}

    # -- job submission ---------------------------------------------------
    # The head raylet plays JobSupervisor (dashboard/modules/job/
    # job_manager.py:525 + the per-job JobSupervisor actor :140): the GCS
    # pushes run_job to it, it spawns the detached driver subprocess and
    # streams state/logs back.
    @staticmethod
    def _job_view(j: dict) -> dict:
        return {k: v for k, v in j.items() if k != "logs"}

    def _find_supervisor_node(self) -> Optional[bytes]:
        for nid, info in self.nodes.items():
            if info["state"] == "ALIVE" and info.get("is_head"):
                return nid
        for nid, info in self.nodes.items():  # headless test clusters
            if info["state"] == "ALIVE":
                return nid
        return None

    async def h_submit_job(self, d, conn):
        submission_id = d.get("submission_id") or f"rtjob_{len(self.jobs):05d}_{int(time.time())}"
        job_key = submission_id.encode()
        if job_key in self.jobs:
            return {"ok": False, "error": f"job {submission_id} already exists"}
        node_id = self._find_supervisor_node()
        if node_id is None:
            return {"ok": False, "error": "no alive node to run the job"}
        self.jobs[job_key] = {
            "job_id": job_key,
            "submission_id": submission_id,
            "entrypoint": d["entrypoint"],
            "state": "PENDING",
            "start_time": time.time(),
            "end_time": None,
            "node_id": node_id,
            "runtime_env": d.get("runtime_env") or {},
            "metadata": d.get("metadata") or {},
            "logs": [],
        }
        try:
            await self.node_conns[node_id].push(
                "run_job",
                {
                    "submission_id": submission_id,
                    "entrypoint": d["entrypoint"],
                    "runtime_env": d.get("runtime_env") or {},
                },
            )
        except Exception as e:  # noqa: BLE001 — roll back the record
            self.jobs.pop(job_key, None)
            return {"ok": False, "error": f"failed to dispatch job: {e}"}
        return {"ok": True, "submission_id": submission_id}

    def _find_job(self, submission_id: str) -> Optional[dict]:
        return self.jobs.get(submission_id.encode())

    async def h_get_job(self, d, conn):
        j = self._find_job(d["submission_id"])
        return {"job": self._job_view(j) if j else None}

    async def h_job_update(self, d, conn):
        j = self._find_job(d["submission_id"])
        if j is None:
            return {"ok": False}
        j["state"] = d["state"]
        if d.get("message"):
            j["message"] = d["message"]
        if d["state"] in ("SUCCEEDED", "FAILED", "STOPPED"):
            j["end_time"] = time.time()
        return {"ok": True}

    async def h_job_log_append(self, d, conn):
        j = self._find_job(d["submission_id"])
        if j is None:
            return {"ok": False}
        logs = j["logs"]
        logs.append(d["data"])
        # Bound memory: keep the newest ~4 MB of log text.
        total = sum(len(c) for c in logs)
        while len(logs) > 1 and total > 4_000_000:
            total -= len(logs.pop(0))
        return {"ok": True}

    async def h_job_logs(self, d, conn):
        j = self._find_job(d["submission_id"])
        if j is None:
            return {"logs": None}
        return {"logs": "".join(j["logs"])}

    async def h_stop_job(self, d, conn):
        j = self._find_job(d["submission_id"])
        if j is None:
            return {"ok": False, "error": "no such job"}
        if j["state"] in ("SUCCEEDED", "FAILED", "STOPPED"):
            return {"ok": True}
        node_conn = self.node_conns.get(j.get("node_id"))
        if node_conn is None:
            return {"ok": False, "error": "supervising node is unreachable"}
        await node_conn.push("stop_job", {"submission_id": j["submission_id"]})
        return {"ok": True}

    # -- actor scheduling ------------------------------------------------
    def _pick_node_for_resources(self, resources: Dict[str, float],
                                 exclude: Set[bytes] = frozenset(),
                                 claimant: Optional[bytes] = None) -> Optional[bytes]:
        """Least-utilized feasible node (GcsActorScheduler::ScheduleByGcs).

        Feasibility is judged against the node's *current availability*
        (advisory view: deducted on placement, corrected by heartbeats).
        Judging by totals would double-book chips a placement group has
        reserved — and, worse, keep an infeasible high-priority actor out
        of the pending queue, which is what arms the reclamation pass.
        An actor nothing can hold right now stays PENDING and is retried
        as the view changes (GcsActorManager's pending queue does the
        same). Nodes fenced for a preemption claimant are invisible to
        everyone but that claimant — freed chips must not leak to
        bystanders.
        """
        best, best_score = None, None
        for node_id, info in self.nodes.items():
            if (info["state"] != "ALIVE" or node_id in exclude
                    or info.get("draining")):
                continue
            fence = info.get("fenced_for")
            if fence is not None and fence != claimant:
                continue
            avail, total = info["resources_available"], info["resources_total"]
            if not all(avail.get(k, 0.0) + 1e-9 >= v
                       for k, v in resources.items()):
                continue
            util = 0.0
            for k, t in total.items():
                if t > 0:
                    util = max(util, 1.0 - avail.get(k, 0.0) / t)
            if best_score is None or util < best_score:
                best, best_score = node_id, util
        return best

    async def h_register_actor(self, d, conn):
        actor_id = d["actor_id"]
        name, ns = d.get("name"), d.get("namespace", "")
        if name:
            key = (ns, name)
            if key in self.named_actors and \
               self.actors[self.named_actors[key]]["state"] != "DEAD":
                return {"ok": False, "error": f"actor name {name!r} already taken"}
            self.named_actors[key] = actor_id
        self.actors[actor_id] = {
            "actor_id": actor_id,
            "name": name,
            "namespace": ns,
            "class_name": d.get("class_name", ""),
            "job_id": d.get("job_id"),
            "state": "PENDING",
            "resources": d.get("resources", {}),
            "max_restarts": d.get("max_restarts", 0),
            "restarts_used": 0,
            "create_spec": d["create_spec"],  # opaque: replayed on restart
            "node_id": None,
            "address": None,
            "port": None,
            "death_cause": None,
            "detached": d.get("detached", False),
            "scheduling": d.get("scheduling"),
            "priority": int(d.get("priority") or 0),
        }
        if d.get("subscribe"):
            # Bundle the caller's actor_update subscription into the
            # registration (saves the separate subscribe round trip the
            # driver otherwise pays per actor).
            self.subscribers["actor_update:" + actor_id.hex()].add(conn)
        ok = await self._schedule_actor(actor_id)
        if not ok:
            # Stay PENDING and retry as the cluster view changes — actor
            # creation is asynchronous in the reference too
            # (GcsActorManager keeps pending actors, gcs_actor_manager.cc).
            self.pending_actors.add(actor_id)
        return {"ok": True}

    async def _schedule_actor(self, actor_id: bytes) -> bool:
        a = self.actors[actor_id]
        node_id = None
        sched = a.get("scheduling") or {}
        if sched.get("type") == "node_affinity":
            nid = sched["node_id"]
            info = self.nodes.get(nid)
            placeable = (
                info and info["state"] == "ALIVE"
                and not info.get("draining")
            )
            if placeable:
                node_id = nid
            elif not sched.get("soft", False):
                # Hard affinity to a dead/draining node: stay pending
                # (retried each reconcile; resolves when the drain is
                # undone or the node comes back).
                return False
        if node_id is None and sched.get("type") == "placement_group":
            pg = self.placement_groups.get(sched["pg_id"])
            if not pg or pg["state"] != "CREATED":
                return False
            node_id = pg["bundle_nodes"][sched.get("bundle_index") or 0]
        if node_id is None and sched.get("type") == "node_label":
            hard, soft = sched.get("hard", {}), sched.get("soft", {})
            best, best_soft = None, -1
            for nid, info in self.nodes.items():
                if info["state"] != "ALIVE" or info.get("draining"):
                    continue
                labels = info.get("labels") or {}
                if not all(labels.get(k) == v for k, v in hard.items()):
                    continue
                nsoft = sum(1 for k, v in soft.items() if labels.get(k) == v)
                if nsoft > best_soft:
                    best, best_soft = nid, nsoft
            node_id = best
            if node_id is None:
                return False
        if node_id is None:
            node_id = self._pick_node_for_resources(
                a["resources"], claimant=actor_id
            )
        if node_id is None:
            return False
        # Deduct from the advisory view so a burst of registrations spreads
        # correctly; the raylet heartbeat is the ground truth.
        if sched.get("type") != "placement_group":
            info = self.nodes.get(node_id)
            if info:
                for k, v in a["resources"].items():
                    info["resources_available"][k] = (
                        info["resources_available"].get(k, 0) - v
                    )
        a["node_id"] = node_id
        a["state"] = "PENDING"
        conn = self.node_conns.get(node_id)
        if conn is None:
            return False
        # Fire-and-forget: the raylet spawns a dedicated worker and the worker
        # reports back via actor_ready (gcs_actor_scheduler.cc lease flow).
        await conn.push(
            "create_actor",
            {"actor_id": actor_id, "create_spec": a["create_spec"],
             "resources": a["resources"], "scheduling": a.get("scheduling")},
        )
        # A placed claimant no longer needs its reclamation fences.
        self._clear_fences(actor_id)
        return True

    async def h_actor_unplaceable(self, d, conn):
        """A raylet refused a placement (the advisory view it was chosen
        under went stale before the create arrived): return the advisory
        deduction and re-queue the actor — the pending retry re-places it
        or, for a high-priority claimant, arms the reclamation pass."""
        a = self.actors.get(d["actor_id"])
        if a is None or a["state"] not in ("PENDING", "RESTARTING"):
            return {"ok": True}
        nid = a.get("node_id")
        if nid is not None and nid == d.get("node_id"):
            info = self.nodes.get(nid)
            if info is not None and \
                    (a.get("scheduling") or {}).get("type") != "placement_group":
                for k, v in (a.get("resources") or {}).items():
                    info["resources_available"][k] = (
                        info["resources_available"].get(k, 0) + v
                    )
            a["node_id"] = None
        self.pending_actors.add(d["actor_id"])
        return {"ok": True}

    async def h_actor_ready(self, d, conn):
        a = self.actors.get(d["actor_id"])
        if not a:
            return {"ok": False}
        if d.get("error"):
            a["state"] = "DEAD"
            a["death_cause"] = d["error"]
        else:
            a["state"] = "ALIVE"
            a["address"] = d["address"]
            a["port"] = d["port"]
            a["worker_id"] = d.get("worker_id")
            a["methods"] = d.get("methods") or []
        journal.emit("gcs.actor", actor_id=d["actor_id"].hex(),
                     state=a["state"], name=a.get("name") or "",
                     class_name=a.get("class_name", ""))
        await self.publish(
            "actor_update:" + d["actor_id"].hex(), self._actor_view(a)
        )
        return {"ok": True}

    def _actor_view(self, a: dict) -> dict:
        return {
            "actor_id": a["actor_id"],
            "state": a["state"],
            "address": a["address"],
            "port": a["port"],
            "node_id": a["node_id"],
            "name": a["name"],
            "namespace": a["namespace"],
            "class_name": a["class_name"],
            "death_cause": a["death_cause"],
            "restarts_used": a["restarts_used"],
            "methods": a.get("methods") or [],
        }

    async def h_get_actor(self, d, conn):
        a = self.actors.get(d["actor_id"])
        return {"actor": self._actor_view(a) if a else None}

    async def h_get_named_actor(self, d, conn):
        aid = self.named_actors.get((d.get("namespace", ""), d["name"]))
        a = self.actors.get(aid) if aid else None
        return {"actor": self._actor_view(a) if a else None}

    async def h_list_actors(self, d, conn):
        return {"actors": [self._actor_view(a) for a in self.actors.values()]}

    async def _on_actor_failure(self, actor_id: bytes, reason: str):
        a = self.actors[actor_id]
        if a["restarts_used"] < a["max_restarts"] or a["max_restarts"] == -1:
            a["restarts_used"] += 1
            a["state"] = "RESTARTING"
            from ray_tpu.util.event import record_event

            record_event(
                "gcs", f"actor restarting ({reason})", severity="WARNING",
                actor_id=actor_id.hex(), class_name=a.get("class_name", ""),
                restarts_used=a["restarts_used"],
            )
            journal.emit("gcs.actor", actor_id=actor_id.hex(),
                         state="RESTARTING", reason=reason,
                         name=a.get("name") or "",
                         class_name=a.get("class_name", ""))
            await self.publish("actor_update:" + actor_id.hex(), self._actor_view(a))
            ok = await self._schedule_actor(actor_id)
            if not ok:
                self.pending_actors.add(actor_id)
            return
        a["state"] = "DEAD"
        a["death_cause"] = reason
        journal.emit("gcs.actor", actor_id=actor_id.hex(), state="DEAD",
                     reason=reason, name=a.get("name") or "",
                     class_name=a.get("class_name", ""))
        await self.publish("actor_update:" + actor_id.hex(), self._actor_view(a))

    async def h_worker_dead(self, d, conn):
        """Raylet reports a worker process exit; fail any actor it hosted."""
        actor_id = d.get("actor_id")
        journal.emit("gcs.worker_dead",
                     actor_id=actor_id.hex() if actor_id else "",
                     intended=bool(d.get("intended")),
                     reason=d.get("reason", ""))
        if actor_id and actor_id in self.actors:
            a = self.actors[actor_id]
            if a["state"] != "DEAD":
                if d.get("intended") and d.get("no_restart", True):
                    a["state"] = "DEAD"
                    a["death_cause"] = d.get("reason", "killed")
                    journal.emit("gcs.actor", actor_id=actor_id.hex(),
                                 state="DEAD",
                                 reason=d.get("reason", "killed"),
                                 name=a.get("name") or "")
                    await self.publish(
                        "actor_update:" + actor_id.hex(), self._actor_view(a)
                    )
                else:
                    await self._on_actor_failure(
                        actor_id, d.get("reason", "worker process died")
                    )
            # ActorDied capture: an UNINTENDED worker exit is a primary
            # failure — freeze every process's ring while the evidence of
            # why is still in the buffers (cooldown keeps crash loops to
            # one bundle per window).
            if not d.get("intended") and get_config().journal_autodump:
                await self._journal_postmortem(
                    f"worker_dead:{d.get('reason', 'unknown')}",
                    source="gcs",
                )
        return {"ok": True}

    async def h_kill_actor(self, d, conn):
        actor_id = d["actor_id"]
        a = self.actors.get(actor_id)
        if not a:
            return {"ok": False}
        if d.get("no_restart", True):
            a["max_restarts"] = 0
        will_restart = (
            a["max_restarts"] == -1
            or a["restarts_used"] < a["max_restarts"]
        )
        node = self.node_conns.get(a.get("node_id"))
        if node:
            # will_restart gates worker recycling: a restarted actor would
            # be adopted onto the same worker/port and the caller's cached
            # connection would resume stale seq counters (they reset only
            # with the connection). Restartable kills take a fresh process.
            await node.push(
                "kill_actor_worker",
                {"actor_id": actor_id, "will_restart": will_restart},
            )
        return {"ok": True}

    # -- object directory ------------------------------------------------
    async def h_object_location_add(self, d, conn):
        if d.get("partial"):
            # An in-progress pull: the node can serve its filled prefix
            # (chain/tree replication, reference object_manager.cc:339
            # any-holder pulls). seq gives chain seniority: a puller may
            # only chain to partials with a LOWER seq, which keeps the
            # replication graph acyclic.
            entry = self.object_dir.setdefault(
                oid := d["object_id"], {"nodes": set(), "size": 0}
            )
            partial = entry.setdefault("partial", {})
            if d["node_id"] not in partial:
                self._partial_seq += 1
                partial[d["node_id"]] = self._partial_seq
            return {"ok": True, "seq": partial[d["node_id"]]}
        self._location_add(d["object_id"], d["node_id"], d.get("size"))
        return {"ok": True}

    async def h_object_locations_add(self, d, conn):
        """Batched location registration (one frame per raylet flush)."""
        node_id = d["node_id"]
        for o in d["objects"]:
            self._location_add(o["object_id"], node_id, o.get("size"))
        return {"ok": True}

    def _location_add(self, oid: bytes, node_id: bytes, size):
        entry = self.object_dir.setdefault(oid, {"nodes": set(), "size": 0})
        entry["nodes"].add(node_id)
        entry.get("partial", {}).pop(node_id, None)
        if size is not None:
            entry["size"] = size
        for ev in self.object_waiters.pop(oid, []):
            ev.set()

    @staticmethod
    def _loc_view(entry) -> dict:
        out = {"nodes": list(entry["nodes"]), "size": entry["size"],
               "known": True}
        if entry.get("spilled"):
            out["spilled"] = entry["spilled"]
        partial = entry.get("partial")
        if partial:
            # [node_id, seq] sorted senior-first: pullers may chain only
            # to partials with seq lower than their own.
            out["partial_nodes"] = sorted(
                ([nid, seq] for nid, seq in partial.items()),
                key=lambda x: x[1],
            )
        return out

    async def h_object_location_get(self, d, conn):
        entry = self.object_dir.get(d["object_id"])
        if not entry:
            # known=False: never registered — may simply not be produced yet
            # (vs. known+empty = every copy is gone).
            return {"nodes": [], "size": 0, "known": False}
        return self._loc_view(entry)

    async def h_object_location_wait(self, d, conn):
        """Block until the object has a location or a spill copy (or
        timeout)."""
        oid = d["object_id"]
        timeout = d.get("timeout", 60.0)
        entry = self.object_dir.get(oid)
        if entry and (entry["nodes"] or entry.get("spilled")):
            return self._loc_view(entry)
        ev = asyncio.Event()
        self.object_waiters[oid].append(ev)
        try:
            await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            return {"nodes": [], "size": 0, "timeout": True}
        finally:
            # Clients probing a never-produced object every few seconds
            # would otherwise grow the waiter list without bound.
            waiters = self.object_waiters.get(oid)
            if waiters is not None:
                try:
                    waiters.remove(ev)
                except ValueError:
                    pass
                if not waiters:
                    self.object_waiters.pop(oid, None)
        entry = self.object_dir.get(oid, {"nodes": set(), "size": 0})
        return self._loc_view(entry)

    async def h_object_spilled(self, d, conn):
        """A raylet spilled its primary copy: record the restore URI and
        drop the in-memory location."""
        oid = d["object_id"]
        entry = self.object_dir.setdefault(oid, {"nodes": set(), "size": 0})
        entry["nodes"].discard(d["node_id"])
        entry["spilled"] = {"node_id": d["node_id"], "uri": d["uri"]}
        return {"ok": True}

    async def h_list_objects(self, d, conn):
        limit = d.get("limit", 10_000)
        out = []
        for oid, entry in self.object_dir.items():
            if len(out) >= limit:
                break
            out.append(
                {"object_id": oid, "nodes": list(entry["nodes"]),
                 "size": entry["size"]}
            )
        return {"objects": out}

    async def h_object_location_remove(self, d, conn):
        entry = self.object_dir.get(d["object_id"])
        if entry:
            entry.get("partial", {}).pop(d["node_id"], None)
            if not d.get("partial_only"):
                entry["nodes"].discard(d["node_id"])
            if d.get("clear_spilled"):
                # Loss injection / spill-file reclaim: the spilled copy
                # is gone too, so restores must not be offered.
                entry.pop("spilled", None)
        return {"ok": True}

    async def h_objects_freed(self, d, conn):
        """Owner freed these objects: drop the directory entries and tell
        every node still holding a copy (or a spill file) to reclaim it.
        The eviction-notification role of the reference's pubsub object
        channels (protobuf/pubsub.proto:30-48), owner-initiated."""
        for oid in d["object_ids"]:
            entry = self.object_dir.pop(oid, None)
            targets: set = set()
            if entry:
                targets |= set(entry["nodes"])
                sp = entry.get("spilled")
                if sp:
                    targets.add(sp["node_id"])
            for nid in targets:
                node_conn = self.node_conns.get(nid)
                if node_conn is not None and node_conn is not conn:
                    try:
                        await node_conn.push(
                            "free_objects", {"object_ids": [oid]}
                        )
                    except Exception:  # noqa: BLE001
                        pass
            # Wake location waiters: they observe the empty entry instead
            # of hanging until timeout.
            for ev in self.object_waiters.pop(oid, []):
                ev.set()
        return {"ok": True}

    # -- placement groups -------------------------------------------------
    async def h_create_pg(self, d, conn):
        """Two-phase reserve of bundles across raylets.

        Mirrors GcsPlacementGroupScheduler's prepare/commit
        (gcs/gcs_server/gcs_placement_group_scheduler.h): all bundles are
        prepared on their raylets first; any failure rolls back. Failed
        reservations stay PENDING and are retried from the health loop as
        the resource view changes.
        """
        pg_id = d["pg_id"]
        self.pg_counter += 1
        pg = {
            "pg_id": pg_id,
            "name": d.get("name", ""),
            "bundles": d["bundles"],
            "strategy": d.get("strategy", "PACK"),
            "state": "PENDING",
            "bundle_nodes": [None] * len(d["bundles"]),
            # Preemption tier: when this group cannot place, strictly
            # lower-priority CREATED groups are eviction candidates (and
            # this group is itself a candidate for higher tiers).
            "priority": int(d.get("priority") or 0),
            # Creation order: ties inside a priority tier evict the
            # youngest gang first (it has the least sunk work).
            "seq": self.pg_counter,
        }
        self.placement_groups[pg_id] = pg
        result = await self._try_reserve_pg(pg)
        if not result.get("ok"):
            self.pending_pgs.add(pg_id)
        return result

    async def _try_reserve_pg(self, pg: dict):
        pg_id = pg["pg_id"]
        bundles: List[Dict[str, float]] = pg["bundles"]
        strategy = pg["strategy"]
        nodes = self._place_bundles(bundles, strategy, claimant=pg_id)
        if nodes is None:
            return {"ok": False, "error": "infeasible placement group"}
        # Phase 1: prepare.
        prepared = []
        ok = True
        for i, node_id in enumerate(nodes):
            node_conn = self.node_conns.get(node_id)
            if node_conn is None:
                ok = False
                break
            try:
                # The GCS view is the source of truth for reservation; the
                # raylet is informed so its local dispatcher accounts for the
                # bundle (prepare). Resource deltas roll back on failure.
                info = self.nodes[node_id]
                avail = info["resources_available"]
                b = bundles[i]
                if not all(avail.get(k, 0) + 1e-9 >= v for k, v in b.items()):
                    ok = False
                    break
                for k, v in b.items():
                    avail[k] = avail.get(k, 0) - v
                await node_conn.push(
                    "reserve_bundle",
                    {"pg_id": pg_id, "bundle_index": i, "resources": b},
                )
                prepared.append((i, node_id))
            except Exception:
                ok = False
                break
        if not ok:
            for i, node_id in prepared:
                node_conn = self.node_conns.get(node_id)
                if node_conn:
                    await node_conn.push(
                        "cancel_bundle", {"pg_id": pg_id, "bundle_index": i}
                    )
                info = self.nodes.get(node_id)
                if info:
                    for k, v in bundles[i].items():
                        info["resources_available"][k] = (
                            info["resources_available"].get(k, 0) + v
                        )
            pg["state"] = "PENDING"
            return {"ok": False, "error": "placement group reservation failed"}
        pg["bundle_nodes"] = nodes
        pg["state"] = "CREATED"
        journal.emit("gcs.pg", pg_id=pg_id.hex(), state="CREATED",
                     bundles=len(nodes))
        # A placed claimant no longer needs its reclamation fences.
        self._clear_fences(pg_id)
        await self.publish("pg_update:" + pg_id.hex(), {"state": "CREATED"})
        return {"ok": True, "bundle_nodes": nodes}

    def _place_bundles(self, bundles, strategy, claimant=None,
                       avail_override=None) -> Optional[List[bytes]]:
        """Bundle placement policies (bundle_scheduling_policy.cc:
        PACK/SPREAD/STRICT_PACK/STRICT_SPREAD).

        Nodes fenced for a preemption claimant only admit that claimant.
        avail_override substitutes the availability map — the reclamation
        pass uses it to ask "would this demand fit if those victims were
        gone?" without touching live state.
        """
        if avail_override is not None:
            alive = {nid: dict(av) for nid, av in avail_override.items()}
        else:
            alive = {}
            for nid, info in self.nodes.items():
                if info["state"] != "ALIVE" or info.get("draining"):
                    continue
                fence = info.get("fenced_for")
                if fence is not None and fence != claimant:
                    continue
                alive[nid] = dict(info["resources_available"])

        def fits(avail, b):
            return all(avail.get(k, 0) + 1e-9 >= v for k, v in b.items())

        def take(avail, b):
            for k, v in b.items():
                avail[k] = avail.get(k, 0) - v

        if strategy in ("STRICT_PACK",):
            for nid, avail in alive.items():
                trial = dict(avail)
                good = True
                for b in bundles:
                    if not fits(trial, b):
                        good = False
                        break
                    take(trial, b)
                if good:
                    return [nid] * len(bundles)
            return None
        if strategy in ("STRICT_SPREAD",):
            result, used = [], set()
            for b in bundles:
                placed = False
                for nid, avail in alive.items():
                    if nid in used or not fits(avail, b):
                        continue
                    take(avail, b)
                    result.append(nid)
                    used.add(nid)
                    placed = True
                    break
                if not placed:
                    return None
            return result
        # PACK (soft): prefer fewest nodes; SPREAD (soft): prefer distinct.
        result = []
        order = list(alive.items())
        if strategy == "SPREAD":
            idx = 0
            for b in bundles:
                placed = False
                for j in range(len(order)):
                    nid, avail = order[(idx + j) % len(order)] if order else (None, None)
                    if nid is not None and fits(avail, b):
                        take(avail, b)
                        result.append(nid)
                        idx = (idx + j + 1) % len(order)
                        placed = True
                        break
                if not placed:
                    return None
            return result
        # PACK
        for b in bundles:
            placed = False
            for nid, avail in order:
                if fits(avail, b):
                    take(avail, b)
                    result.append(nid)
                    placed = True
                    break
            if not placed:
                return None
        return result

    async def h_remove_pg(self, d, conn):
        pg = self.placement_groups.get(d["pg_id"])
        if not pg:
            return {"ok": False}
        if pg["state"] == "CREATED":
            for i, node_id in enumerate(pg["bundle_nodes"]):
                info = self.nodes.get(node_id)
                if info and info["state"] == "ALIVE":
                    for k, v in pg["bundles"][i].items():
                        info["resources_available"][k] = (
                            info["resources_available"].get(k, 0) + v
                        )
                    node_conn = self.node_conns.get(node_id)
                    if node_conn:
                        await node_conn.push(
                            "cancel_bundle", {"pg_id": d["pg_id"], "bundle_index": i}
                        )
        pg["state"] = "REMOVED"
        journal.emit("gcs.pg", pg_id=d["pg_id"].hex(), state="REMOVED")
        # Preemption hooks: a removed group may be a draining victim
        # handing its chips back (finish the record, un-drain its nodes)
        # or a pending claimant giving up (cancel its eviction).
        rec = self.preemptions.get(d["pg_id"])
        if rec is not None and rec["state"] == "draining":
            self._finish_preemption(rec, outcome="graceful")
        self._cancel_preemptions_for_claimant(d["pg_id"])
        # Resize-obligation hooks: a removed claimant releases the chips
        # it partially reclaimed (the victim may now grow back); a
        # removed victim no longer has anything to grow back into.
        self._lift_resize_obligations(d["pg_id"])
        self.resize_obligations.pop(d["pg_id"], None)
        return {"ok": True}

    async def h_release_pg_bundles(self, d, conn):
        """Elastic shrink: a CREATED gang gives individual bundles back.

        The chips are credited to their nodes immediately. When the
        release satisfies a partial-reclamation drain (the record's
        bundle_indices are all released), the eviction record closes
        with outcome "resized" and a *resize obligation* is recorded so
        the victim can reclaim exactly these bundles after the claimant
        releases — the gang resized instead of dying.
        """
        pg = self.placement_groups.get(d["pg_id"])
        if not pg or pg["state"] != "CREATED":
            return {"ok": False, "error": "placement group not CREATED"}
        indices = sorted({int(i) for i in d.get("indices") or []})
        if not indices:
            return {"ok": False, "error": "no bundle indices"}
        released: List[int] = pg.setdefault("released_bundles", [])
        bad = [
            i for i in indices
            if i < 0 or i >= len(pg["bundles"]) or i in released
            or pg["bundle_nodes"][i] is None
        ]
        if bad:
            return {"ok": False, "error": f"invalid bundle index(es) {bad}"}
        homes: Dict[int, bytes] = pg.setdefault("released_nodes", {})
        for i in indices:
            nid = pg["bundle_nodes"][i]
            info = self.nodes.get(nid)
            if info and info["state"] == "ALIVE":
                for k, v in pg["bundles"][i].items():
                    info["resources_available"][k] = (
                        info["resources_available"].get(k, 0) + v
                    )
                node_conn = self.node_conns.get(nid)
                if node_conn:
                    await node_conn.push(
                        "cancel_bundle",
                        {"pg_id": d["pg_id"], "bundle_index": i},
                    )
            homes[i] = nid
            pg["bundle_nodes"][i] = None
            released.append(i)
        released.sort()
        rec = self.preemptions.get(d["pg_id"])
        if (
            rec is not None and rec["state"] == "draining"
            and rec.get("partial")
            and set(rec.get("bundle_indices") or []) <= set(released)
        ):
            self._finish_preemption(rec, outcome="resized")
            if rec.get("claimant") is not None:
                self.resize_obligations[d["pg_id"]] = {
                    "victim": d["pg_id"],
                    "claimant": rec["claimant"],
                    "claimant_tenant": rec.get("claimant_tenant") or "",
                    "bundle_indices": sorted(rec["bundle_indices"]),
                    "state": "armed",
                    "created": time.monotonic(),
                    "lifted_at": None,
                }
                journal.emit(
                    "gcs.resize", pg_id=d["pg_id"].hex(), state="armed",
                    bundles=len(rec["bundle_indices"]),
                )
            from ray_tpu.util.event import record_event

            record_event(
                "gcs",
                f"tenant {rec['victim_tenant']!r} resized instead of "
                f"evicting: released bundle(s) {sorted(rec['bundle_indices'])} "
                f"to {rec.get('claimant_tenant') or 'claimant'!r}",
                pg_id=d["pg_id"].hex(),
            )
        return {"ok": True, "released": released}

    async def h_reserve_pg_bundles(self, d, conn):
        """Elastic grow-back: re-reserve previously released bundles.

        Refused while a resize obligation is still armed (the claimant
        holds the chips) or while the chips are fenced/occupied. Each
        bundle prefers its original node; STRICT_SPREAD groups keep
        node-distinctness."""
        pg = self.placement_groups.get(d["pg_id"])
        if not pg or pg["state"] != "CREATED":
            return {"ok": False, "error": "placement group not CREATED"}
        indices = sorted({int(i) for i in d.get("indices") or []})
        released = pg.get("released_bundles") or []
        bad = [i for i in indices if i not in released]
        if bad:
            return {"ok": False, "error": f"bundle(s) {bad} not released"}
        ob = self.resize_obligations.get(d["pg_id"])
        if (
            ob is not None and ob["state"] == "armed"
            and set(indices) & set(ob["bundle_indices"])
        ):
            return {
                "ok": False,
                "error": "resize obligation not lifted: claimant "
                         f"{ob['claimant_tenant'] or 'claimant'!r} still "
                         "holds the chips",
            }
        homes = pg.get("released_nodes") or {}
        distinct = pg["strategy"] == "STRICT_SPREAD"
        placed: List[tuple] = []

        async def rollback():
            for j, njd in placed:
                info = self.nodes.get(njd)
                if info:
                    for k, v in pg["bundles"][j].items():
                        info["resources_available"][k] = (
                            info["resources_available"].get(k, 0) + v
                        )
                node_conn = self.node_conns.get(njd)
                if node_conn:
                    await node_conn.push(
                        "cancel_bundle",
                        {"pg_id": d["pg_id"], "bundle_index": j},
                    )
                pg["bundle_nodes"][j] = None

        for i in indices:
            b = pg["bundles"][i]
            orig = homes.get(i)
            candidates = ([orig] if orig is not None else []) + [
                n for n in self.nodes if n != orig
            ]
            nid = None
            for cand in candidates:
                info = self.nodes.get(cand)
                if (not info or info["state"] != "ALIVE"
                        or info.get("draining")):
                    continue
                fence = info.get("fenced_for")
                if fence is not None and fence != d["pg_id"]:
                    continue
                if distinct and cand in pg["bundle_nodes"]:
                    continue
                avail = info["resources_available"]
                if all(avail.get(k, 0) + 1e-9 >= v for k, v in b.items()):
                    nid = cand
                    break
            if nid is None:
                await rollback()
                return {"ok": False,
                        "error": f"bundle {i} cannot place anywhere"}
            info = self.nodes[nid]
            for k, v in b.items():
                info["resources_available"][k] = (
                    info["resources_available"].get(k, 0) - v
                )
            node_conn = self.node_conns.get(nid)
            if node_conn:
                await node_conn.push(
                    "reserve_bundle",
                    {"pg_id": d["pg_id"], "bundle_index": i, "resources": b},
                )
            pg["bundle_nodes"][i] = nid
            homes.pop(i, None)
            placed.append((i, nid))
        pg["released_bundles"] = [x for x in released if x not in set(indices)]
        if ob is not None:
            remaining = sorted(set(ob["bundle_indices"]) - set(indices))
            if remaining:
                ob["bundle_indices"] = remaining
            else:
                self.resize_obligations.pop(d["pg_id"], None)
        return {"ok": True,
                "bundle_nodes": [pg["bundle_nodes"][i] for i in indices]}

    async def h_get_resize_state(self, d, conn):
        """Resize obligations + released bundles for one group — the
        trainer's grow-back path polls this for the fence-lift signal."""
        pg = self.placement_groups.get(d["pg_id"])
        ob = self.resize_obligations.get(d["pg_id"])
        out = []
        if ob is not None:
            now = time.monotonic()
            out.append({
                "claimant": ob["claimant"],
                "claimant_tenant": ob["claimant_tenant"],
                "bundle_indices": list(ob["bundle_indices"]),
                "state": ob["state"],
                "age_s": now - ob["created"],
            })
        return {
            "obligations": out,
            "released_bundles": sorted(pg.get("released_bundles") or [])
            if pg else [],
        }

    def _lift_resize_obligations(self, claimant_id: bytes):
        """The claimant released its chips (group removed or actor dead):
        flip its obligations to "lifted" — the victims' grow-back signal."""
        for ob in self.resize_obligations.values():
            if ob["state"] == "armed" and ob.get("claimant") == claimant_id:
                ob["state"] = "lifted"
                ob["lifted_at"] = time.monotonic()
                journal.emit("gcs.resize", pg_id=ob["victim"].hex()
                             if isinstance(ob.get("victim"), bytes) else "",
                             state="lifted")
                from ray_tpu.util.event import record_event

                record_event(
                    "gcs",
                    f"resize obligation lifted: claimant "
                    f"{ob['claimant_tenant'] or 'claimant'!r} released "
                    f"bundle(s) {ob['bundle_indices']} back to tenant",
                    pg_id=ob["victim"].hex(),
                )

    async def h_get_pg(self, d, conn):
        pg = self.placement_groups.get(d["pg_id"])
        return {"pg": pg and {k: v for k, v in pg.items()}}

    async def h_list_pgs(self, d, conn):
        return {"pgs": list(self.placement_groups.values())}

    # -- preemption ------------------------------------------------------
    # The reclamation pass: when higher-priority demand (a pending
    # placement group or actor) cannot place, pick victim gangs from the
    # lowest-priority tier, mark their nodes draining (the PR 2 train
    # migration path and the serve controller's eviction both key off
    # that flag), fence the nodes for the claimant, and back the graceful
    # window with a hard-kill deadline (RT_PREEMPT_GRACE_S).

    def _pg_tenant(self, pg: dict) -> str:
        return pg.get("name") or ("pg:" + pg["pg_id"].hex()[:8])

    def _clear_fences(self, owner_id: bytes):
        for info in self.nodes.values():
            if info.get("fenced_for") == owner_id:
                info.pop("fenced_for", None)

    def _count_preempt(self, tenant: str, reason: str):
        key = (("reason", reason), ("tenant", tenant))
        self.preempt_counts[key] = self.preempt_counts.get(key, 0.0) + 1.0

    def _maybe_preempt(self, owner_id: bytes, tenant: str, priority: int,
                       bundles: List[Dict[str, float]], strategy: str) -> bool:
        """One reclamation attempt for an infeasible pending demand.

        Called from the health loop after a failed placement retry.
        Greedy victim selection: walk CREATED groups from the lowest
        priority tier up (youngest first inside a tier), hypothetically
        credit each victim's bundles back, and stop at the first set
        whose release makes the claimant feasible.
        """
        cfg = get_config()
        if not cfg.preemption_enabled:
            return False
        # One in-flight reclamation per claimant: while victims drain,
        # don't widen the blast radius — the retry loop re-enters here
        # only if the claimant is still infeasible after they release.
        for rec in self.preemptions.values():
            if rec["state"] == "draining" and rec.get("claimant") == owner_id:
                return False
        # Hypothetical availability: nodes this claimant could use today.
        hyp = {}
        for nid, info in self.nodes.items():
            if info["state"] != "ALIVE" or info.get("draining"):
                continue
            fence = info.get("fenced_for")
            if fence is not None and fence != owner_id:
                continue
            hyp[nid] = dict(info["resources_available"])
        cands = []
        for pg in self.placement_groups.values():
            if pg["state"] != "CREATED":
                continue
            if int(pg.get("priority") or 0) >= priority:
                continue
            vrec = self.preemptions.get(pg["pg_id"])
            if vrec is not None and vrec["state"] == "draining":
                continue
            # The head node cannot drain; a gang with a bundle there is
            # not evictable through the node-drain machinery.
            if any(
                (self.nodes.get(n) or {}).get("is_head")
                for n in pg["bundle_nodes"]
            ):
                continue
            cands.append(pg)
        cands.sort(
            key=lambda p: (int(p.get("priority") or 0), -p.get("seq", 0))
        )
        # Partial reclamation: credit victim bundles ONE at a time,
        # highest index first (trailing ranks hold the trailing data
        # shards — the cheapest for an elastic victim to shed), and stop
        # at the first bundle whose release makes the claimant feasible.
        # A victim losing k < gang_size bundles gets a partial record:
        # only those bundles' nodes drain, and releasing them counts as
        # honoring the eviction (the gang resizes instead of dying).
        partial_ok = cfg.preempt_partial_enabled
        chosen: List[tuple] = []  # (pg, [credited bundle indices])
        feasible = False
        for pg in cands:
            indices: List[int] = []
            for i in range(len(pg["bundle_nodes"]) - 1, -1, -1):
                nid = pg["bundle_nodes"][i]
                if nid not in hyp:
                    continue
                for k, v in pg["bundles"][i].items():
                    hyp[nid][k] = hyp[nid].get(k, 0) + v
                indices.append(i)
                if partial_ok and self._place_bundles(
                        bundles, strategy, avail_override=hyp) is not None:
                    feasible = True
                    break
            if not indices:
                continue
            chosen.append((pg, sorted(indices)))
            if not feasible and self._place_bundles(
                    bundles, strategy, avail_override=hyp) is not None:
                feasible = True
            if feasible:
                break
        if not feasible:
            return False  # no victim set makes the claimant feasible
        for pg, indices in chosen:
            partial = partial_ok and len(indices) < len(pg["bundles"])
            self._register_preemption(
                pg, reason="priority", claimant=owner_id,
                claimant_tenant=tenant, claimant_priority=priority,
                fence_for=owner_id,
                bundle_indices=indices if partial else None,
            )
        return True

    def _register_preemption(self, pg: dict, reason: str,
                             claimant: Optional[bytes] = None,
                             claimant_tenant: str = "",
                             claimant_priority: int = 0,
                             fence_for: Optional[bytes] = None,
                             only_node: Optional[bytes] = None,
                             bundle_indices: Optional[List[int]] = None):
        """Mark one victim gang draining and open its eviction record.

        bundle_indices (partial reclamation): only those bundles' nodes
        drain, and the victim honors the eviction by releasing exactly
        those bundles (release_pg_bundles) instead of its whole group —
        an elastic gang resizes; the hard-kill deadline still covers the
        whole gang if it does neither in time.
        """
        cfg = get_config()
        now = time.monotonic()
        wanted = (
            {pg["bundle_nodes"][i] for i in bundle_indices}
            if bundle_indices is not None else None
        )
        # Refcount semantics: the record lists every node it needs drained
        # (idempotently re-marking already-draining ones); a node is
        # un-drained only when no draining record still lists it.
        nodes_marked = []
        for nid in dict.fromkeys(pg["bundle_nodes"]):
            if only_node is not None and nid != only_node:
                continue
            if wanted is not None and nid not in wanted:
                continue
            info = self.nodes.get(nid)
            if not info or info["state"] != "ALIVE" or info.get("is_head"):
                continue
            info["draining"] = True
            nodes_marked.append(nid)
            if fence_for is not None:
                info["fenced_for"] = fence_for
        tenant = self._pg_tenant(pg)
        self.preemptions[pg["pg_id"]] = {
            "victim": pg["pg_id"],
            "victim_tenant": tenant,
            "victim_priority": int(pg.get("priority") or 0),
            "claimant": claimant,
            "claimant_tenant": claimant_tenant,
            "claimant_priority": claimant_priority,
            "nodes": nodes_marked,
            "started": now,
            "deadline": now + cfg.preempt_grace_s,
            "state": "draining",
            "reason": reason,
            "released_at": None,
            "outcome": None,
        }
        if bundle_indices is not None:
            rec = self.preemptions[pg["pg_id"]]
            rec["partial"] = True
            rec["bundle_indices"] = sorted(bundle_indices)
        self._count_preempt(tenant, reason)
        from ray_tpu.util.event import record_event

        record_event(
            "gcs",
            f"preempting placement group ({reason}): tenant {tenant!r} "
            f"(priority {int(pg.get('priority') or 0)}) drains for "
            f"{claimant_tenant or 'node reclaim'!r} "
            f"(priority {claimant_priority}); grace {cfg.preempt_grace_s}s",
            severity="WARNING", pg_id=pg["pg_id"].hex(),
        )
        journal.emit("gcs.preemption", pg_id=pg["pg_id"].hex(),
                     state="draining", reason=reason, tenant=tenant,
                     claimant_tenant=claimant_tenant)

    def _finish_preemption(self, rec: dict, outcome: str):
        """Victim released its chips (or was hard-killed): close the
        record, observe the grace histogram, un-drain the nodes this
        preemption marked (the fence persists until the claimant places)."""
        rec["state"] = "released"
        rec["outcome"] = outcome
        rec["released_at"] = time.monotonic()
        journal.emit("gcs.preemption", pg_id=rec["victim"].hex()
                     if isinstance(rec.get("victim"), bytes) else "",
                     state="released", outcome=outcome)
        took = rec["released_at"] - rec["started"]
        h = self.preempt_grace
        h["buckets"][bisect_left(_PREEMPT_GRACE_BOUNDS, took)] += 1
        h["sum"] += took
        h["count"] += 1
        if outcome == "hard_kill":
            self._count_preempt(rec["victim_tenant"], "hard_kill")
        for nid in rec["nodes"]:
            if any(
                r is not rec and r["state"] == "draining"
                and nid in r["nodes"]
                for r in self.preemptions.values()
            ):
                continue  # another eviction still needs this node drained
            info = self.nodes.get(nid)
            if info is not None:
                info.pop("draining", None)
        self._prune_preemptions()

    def _cancel_preemptions_for_claimant(self, owner_id: bytes):
        """The claimant withdrew (its group was removed while pending):
        stand the victims back up — un-drain, un-fence, drop records."""
        for rec in list(self.preemptions.values()):
            if rec["state"] != "draining" or rec.get("claimant") != owner_id:
                continue
            rec["state"] = "released"
            rec["outcome"] = "cancelled"
            rec["released_at"] = time.monotonic()
            for nid in rec["nodes"]:
                if any(
                    r is not rec and r["state"] == "draining"
                    and nid in r["nodes"]
                    for r in self.preemptions.values()
                ):
                    continue
                info = self.nodes.get(nid)
                if info is not None:
                    info.pop("draining", None)
        self._clear_fences(owner_id)
        self._prune_preemptions()

    def _prune_preemptions(self):
        limit = get_config().preempt_history_limit
        released = [
            (rec["released_at"] or 0.0, vid)
            for vid, rec in self.preemptions.items()
            if rec["state"] == "released"
        ]
        if len(self.preemptions) <= limit:
            return
        released.sort()
        for _, vid in released[: len(self.preemptions) - limit]:
            self.preemptions.pop(vid, None)

    async def _preemption_tick(self):
        """Health-loop step: enforce hard-kill deadlines and sweep fences
        whose claimant is no longer waiting."""
        now = time.monotonic()
        for rec in list(self.preemptions.values()):
            if rec["state"] != "draining" or now < rec["deadline"]:
                continue
            victim_id = rec["victim"]
            from ray_tpu.util.event import record_event

            record_event(
                "gcs",
                f"preemption grace expired: hard-killing tenant "
                f"{rec['victim_tenant']!r}",
                severity="ERROR", pg_id=victim_id.hex(),
            )
            # The deadline is the guarantee: kill every actor living in
            # the victim group, then force-release its bundles.
            rec["state"] = "hard_killing"
            journal.emit("gcs.preemption", pg_id=victim_id.hex(),
                         state="hard_killing")
            for actor_id, a in list(self.actors.items()):
                sched = a.get("scheduling") or {}
                if (
                    sched.get("type") == "placement_group"
                    and sched.get("pg_id") == victim_id
                    and a["state"] in ("ALIVE", "PENDING", "RESTARTING")
                ):
                    a["max_restarts"] = 0
                    node = self.node_conns.get(a.get("node_id"))
                    if node is not None:
                        try:
                            await node.push(
                                "kill_actor_worker",
                                {"actor_id": actor_id, "will_restart": False},
                            )
                        except Exception:
                            pass
            pg = self.placement_groups.get(victim_id)
            if pg is not None and pg["state"] == "CREATED":
                # state "hard_killing" makes h_remove_pg's graceful-release
                # hook skip this record; we close it ourselves below.
                await self.h_remove_pg({"pg_id": victim_id}, None)
                self._mark_dirty()
            self._finish_preemption(rec, outcome="hard_kill")
        # Fence sweep: a fence whose owner is neither pending nor waiting
        # on a drain is stale (owner died, was cancelled, or placed
        # through a path that missed the inline clear).
        owners = {
            info.get("fenced_for")
            for info in self.nodes.values()
            if info.get("fenced_for") is not None
        }
        for owner in owners:
            waiting = (
                owner in self.pending_pgs
                or owner in self.pending_actors
                # Chaos sentinel claimants hold their fences until
                # chaos.lift_fence releases them.
                or owner in self.chaos_claims
                or any(
                    r["state"] == "draining" and r.get("claimant") == owner
                    for r in self.preemptions.values()
                )
            )
            if not waiting:
                self._clear_fences(owner)
        # Obligation sweep: an armed resize obligation whose claimant is
        # gone (actor died, group removed through a path that missed the
        # inline lift) flips to lifted so the victim can grow back.
        for ob in list(self.resize_obligations.values()):
            if (ob["state"] == "armed"
                    and not self._claimant_active(ob["claimant"])):
                self._lift_resize_obligations(ob["claimant"])

    def _claimant_active(self, owner: Optional[bytes]) -> bool:
        """Does this claimant still hold (or await) the chips it
        reclaimed? Chaos sentinels count as active until lifted."""
        if owner is None:
            return False
        if owner in self.chaos_claims:
            return True
        pg = self.placement_groups.get(owner)
        if pg is not None and pg["state"] in ("PENDING", "CREATED"):
            return True
        a = self.actors.get(owner)
        if a is not None and a["state"] in ("PENDING", "ALIVE",
                                            "RESTARTING"):
            return True
        return False

    def _preemption_view(self, rec: dict) -> dict:
        now = time.monotonic()
        out = {
            "victim_pg_id": rec["victim"],
            "victim_tenant": rec["victim_tenant"],
            "victim_priority": rec["victim_priority"],
            "claimant": rec.get("claimant"),
            "claimant_tenant": rec.get("claimant_tenant") or "",
            "claimant_priority": rec.get("claimant_priority") or 0,
            "nodes": list(rec["nodes"]),
            "state": rec["state"],
            "reason": rec["reason"],
            "outcome": rec.get("outcome"),
            "age_s": now - rec["started"],
            "grace_remaining_s": (
                max(0.0, rec["deadline"] - now)
                if rec["state"] == "draining" and rec["deadline"] != float("inf")
                else 0.0
            ),
        }
        if rec.get("partial"):
            out["partial"] = True
            out["bundle_indices"] = list(rec.get("bundle_indices") or [])
        if rec["state"] == "draining":
            # Victim actors still alive mid-drain — chaos's
            # kill_victim_mid_drain picks from these.
            out["victim_actors"] = [
                aid for aid, a in self.actors.items()
                if (a.get("scheduling") or {}).get("type")
                == "placement_group"
                and (a.get("scheduling") or {}).get("pg_id") == rec["victim"]
                and a["state"] == "ALIVE"
            ]
        return out

    async def h_get_preemptions(self, d, conn):
        """Preemption records, active first (rt top's `preemptions`
        section and chaos.kill_victim_mid_drain read this)."""
        recs = sorted(
            self.preemptions.values(),
            key=lambda r: (r["state"] != "draining", -r["started"]),
        )
        return {"preemptions": [self._preemption_view(r) for r in recs]}

    async def h_preempt_node(self, d, conn):
        """Node-scope preemption (chaos.preempt_node / spot-reclaim
        model): cordon the node and open an eviction record — with the
        full grace-then-hard-kill guarantee — for every CREATED gang
        holding a bundle there."""
        info = self.nodes.get(d["node_id"])
        if not info or info["state"] != "ALIVE":
            return {"ok": False, "error": "node not alive"}
        if info.get("is_head"):
            return {"ok": False, "error": "refusing to preempt the head node"}
        victims = []
        for pg in self.placement_groups.values():
            if pg["state"] != "CREATED":
                continue
            if d["node_id"] not in pg["bundle_nodes"]:
                continue
            vrec = self.preemptions.get(pg["pg_id"])
            if vrec is not None and vrec["state"] == "draining":
                continue
            self._register_preemption(
                pg, reason=d.get("reason", "chaos"),
                only_node=d["node_id"],
            )
            victims.append(pg["pg_id"])
        # Cordon even when no gang lives there: new work must not land on
        # a node that is being reclaimed.
        info["draining"] = True
        return {"ok": True, "victims": victims}

    async def h_chaos_reclaim_chips(self, d, conn):
        """Chaos: reclaim `amount` chips through the real partial-
        reclamation pass under a synthetic top-priority claimant.

        The sentinel claimant never places, so its fences (and any armed
        resize obligations it produces) persist until chaos_lift_fence —
        a deterministic serve-spike stand-in for elastic-resize tests.
        """
        amount = float(d["amount"])
        resource = d.get("resource") or "TPU"
        per = float(d.get("bundle_chips") or amount)
        count = max(1, int(amount // per) + (1 if amount % per else 0))
        sentinel = b"chaos_claim:" + os.urandom(8)
        ok = self._maybe_preempt(
            sentinel, "chaos_reclaim",
            int(d.get("priority") or 1_000_000),
            [{resource: per} for _ in range(count)], "SPREAD",
        )
        if not ok:
            return {"ok": False,
                    "error": "no victim set frees the requested chips"}
        self.chaos_claims.add(sentinel)
        victims = [
            {
                "victim_pg_id": rec["victim"],
                "partial": bool(rec.get("partial")),
                "bundle_indices": list(rec.get("bundle_indices") or []),
            }
            for rec in self.preemptions.values()
            if rec["state"] == "draining"
            and rec.get("claimant") == sentinel
        ]
        return {"ok": True, "claim_id": sentinel, "victims": victims}

    async def h_chaos_lift_fence(self, d, conn):
        """Chaos: release every chaos reclamation claim — cancel
        still-draining chaos records, lift armed obligations, clear
        fences. The grow-back signal for elastic victims."""
        lifted = 0
        for sentinel in list(self.chaos_claims):
            self.chaos_claims.discard(sentinel)
            self._cancel_preemptions_for_claimant(sentinel)
            for ob in self.resize_obligations.values():
                if (ob["state"] == "armed"
                        and ob.get("claimant") == sentinel):
                    lifted += 1
            self._lift_resize_obligations(sentinel)
            self._clear_fences(sentinel)
        return {"ok": True, "lifted": lifted}

    # -- pubsub ----------------------------------------------------------
    #: Channels clients may publish to. System channels (actor_update:*,
    #: node_dead, ...) are GCS-originated only — a spoofed actor_update
    #: would poison every subscriber's actor cache.
    _CLIENT_PUBLISH_PREFIXES = ("serve_routes:", "user:")

    async def h_publish(self, d, conn):
        """Client-originated publish: fan a payload out to every subscriber
        of a namespaced channel (Publisher analog, pubsub/publisher.h:307 —
        used by e.g. the Serve controller to invalidate handle routing
        tables)."""
        channel = d["channel"]
        if not channel.startswith(self._CLIENT_PUBLISH_PREFIXES):
            return {
                "ok": False,
                "error": f"clients may not publish to {channel!r}; allowed "
                         f"prefixes: {list(self._CLIENT_PUBLISH_PREFIXES)}",
            }
        await self.publish(channel, d.get("payload"))
        return {"ok": True}

    async def h_subscribe(self, d, conn):
        self.subscribers[d["channel"]].add(conn)
        # Late joiners get a still-fresh dump trigger replayed: a
        # replacement replica spawned BECAUSE of the failure connects
        # after the publish, but its ring (spawn, first requests) is
        # exactly the recovery half of the postmortem story.
        if d["channel"] == "journal_dump" and self._pm_last_payload:
            age = time.time() - self._pm_last_payload.get("ts", 0)
            if age <= get_config().journal_window_s:
                try:
                    await conn.push("journal_dump", self._pm_last_payload)
                except Exception:  # noqa: BLE001 — replay is best-effort
                    pass
        return {"ok": True}

    # -- cluster black box (failure-triggered postmortem capture) --------
    async def _journal_postmortem(self, reason: str, source: str = "",
                                  force: bool = False,
                                  detail: Optional[dict] = None) -> Optional[str]:
        """Mint a postmortem bundle and fan the dump trigger out to every
        connected process over the journal_dump channel. Cooldown-gated
        (unless forced, the `rt timeline --cluster` path) so a failure
        storm produces one bundle, not a dump storm. Returns the bundle
        directory, or None when suppressed."""
        cfg = get_config()
        if not cfg.journal_enabled:
            return None
        now = time.monotonic()
        if not force and now - self._pm_last_mono < cfg.journal_cooldown_s:
            return None
        self._pm_last_mono = now
        self._pm_seq += 1
        slug = "".join(
            c if c.isalnum() else "-" for c in reason
        ).strip("-")[:48] or "trigger"
        trigger_id = f"pm-{int(time.time())}-{self._pm_seq:03d}-{slug}"
        bundle = os.path.join(journal.dump_dir(), trigger_id)
        try:
            os.makedirs(bundle, exist_ok=True)
        except OSError:
            return None
        journal.emit("journal.trigger", reason=reason, source=source,
                     bundle=trigger_id, **(detail or {}))
        payload = {
            "bundle": bundle, "trigger_id": trigger_id, "reason": reason,
            "source": source, "ts": time.time(),
            "window_s": cfg.journal_window_s, "hlc": journal.wire_stamp(),
        }
        self.postmortems.append({
            "bundle": bundle, "trigger_id": trigger_id, "reason": reason,
            "source": source, "ts": payload["ts"],
            "detail": dict(detail or {}),
        })
        self._pm_last_payload = payload
        del self.postmortems[:-64]
        await self.publish("journal_dump", payload)
        # This process's own ring (the GCS sees every state transition —
        # its file anchors the merged timeline).
        journal.on_dump_trigger(payload)
        return bundle

    async def h_journal_trigger(self, d, conn):
        """Client-requested dump trigger: typed failure observers
        (breaker-open, replica-death replacement, collective timeout,
        HOL, deadline storms, gang restart) and `rt timeline --cluster`
        land here."""
        bundle = await self._journal_postmortem(
            d.get("reason") or "manual", source=d.get("source") or "",
            force=bool(d.get("force")), detail=d.get("detail") or {},
        )
        return {"ok": True, "triggered": bundle is not None,
                "bundle": bundle or ""}

    async def h_get_postmortems(self, d, conn):
        return {"postmortems": list(self.postmortems)}

    # -- task events ------------------------------------------------------
    async def h_add_task_events(self, d, conn):
        self.task_events.extend(d["events"])
        overflow = len(self.task_events) - _TASK_EVENTS_CAP
        if overflow > 0:
            del self.task_events[:overflow]
            self._task_events_dropped += overflow
        return {"ok": True}

    async def h_list_task_events(self, d, conn):
        """Page through the task-event ring.

        With "offset": events[offset : offset+limit] from the ring's
        current start — consumers loop until offset reaches "total"
        (pages may shift if the ring evicts mid-pagination; "dropped"
        counts lifetime evictions so they can warn on truncated
        history). Without "offset": legacy tail slice of the newest
        `limit` events.
        """
        limit = d.get("limit", 1000)
        total = len(self.task_events)
        if "offset" in d:
            off = max(0, int(d["offset"]))
            events = self.task_events[off:off + limit]
        else:
            events = self.task_events[-limit:]
        return {
            "events": events,
            "total": total,
            "dropped": self._task_events_dropped,
        }

    # -- metrics ----------------------------------------------------------
    async def h_metrics_report(self, d, conn):
        """Merge a client's metric deltas into the cluster aggregate.

        Counters accumulate deltas; gauges are last-writer-wins per tag
        set; histogram bucket counts/sums accumulate. Reports carrying a
        (reporter, seq) pair are deduplicated so an at-least-once retry
        (reply lost after the report applied) cannot double-count.
        """
        reporter, seq = d.get("reporter"), d.get("seq")
        if reporter is not None and seq is not None:
            last = self._metrics_seq.get(reporter)
            if last is not None and seq <= last:
                return {"ok": True, "duplicate": True}
            self._metrics_seq[reporter] = seq
        for rec in d["records"]:
            m = self.metrics.setdefault(
                rec["name"],
                {
                    "type": rec["type"],
                    "description": rec.get("description", ""),
                    "boundaries": rec.get("boundaries"),
                    "series": {},
                },
            )
            if m["type"] != rec["type"] or (
                rec["type"] == "histogram"
                and m["boundaries"] != rec.get("boundaries")
            ):
                # Conflicting re-registration under the same name: skip this
                # record rather than corrupting (or aborting) the batch.
                continue
            series = m["series"]
            for tags_list, payload in rec["data"]:
                key = tuple(tuple(t) for t in tags_list)
                if rec["type"] == "counter":
                    series[key] = series.get(key, 0.0) + payload
                elif rec["type"] == "gauge":
                    series[key] = payload
                else:  # histogram
                    st = series.setdefault(
                        key,
                        {"buckets": [0] * len(payload["buckets"]),
                         "sum": 0.0, "count": 0},
                    )
                    for i, c in enumerate(payload["buckets"]):
                        st["buckets"][i] += c
                    st["sum"] += payload["sum"]
                    st["count"] += payload["count"]
        return {"ok": True}

    async def h_metrics_snapshot(self, d, conn):
        out = []
        # GCS-internal RPC accounting joins the cluster metric surface as
        # synthetic series (the GCS has no client-side flusher of its
        # own): counts as a counter, handler latency as a histogram, both
        # tagged by method — so Grafana's gcs_rpc_* panels and `rt top`
        # see them like any reported metric.
        if self.rpc_counts:
            out.append({
                "name": "gcs_rpc_calls_total",
                "type": "counter",
                "description": "GCS RPCs served, by method",
                "boundaries": None,
                "series": [
                    [[["method", m]], float(c)]
                    for m, c in self.rpc_counts.items()
                ],
            })
        if self.rpc_latency:
            out.append({
                "name": "gcs_rpc_server_seconds",
                "type": "histogram",
                "description": "GCS handler latency, by method",
                "boundaries": list(_RPC_LATENCY_BOUNDS),
                "series": [
                    [[["method", m]],
                     {"buckets": list(st["buckets"]), "sum": st["sum_s"],
                      "count": st["count"]}]
                    for m, st in self.rpc_latency.items()
                ],
            })
        # Preemption accounting (the reclamation pass lives in the GCS, so
        # these join the surface as synthetic series too).
        if self.preempt_counts:
            out.append({
                "name": "preempt_total",
                "type": "counter",
                "description": "placement groups preempted, by victim "
                               "tenant and reason",
                "boundaries": None,
                "series": [
                    [[list(t) for t in key], v]
                    for key, v in self.preempt_counts.items()
                ],
            })
        if self.preempt_grace["count"]:
            out.append({
                "name": "preempt_grace_seconds",
                "type": "histogram",
                "description": "eviction notice to bundle release, per "
                               "preempted gang",
                "boundaries": list(_PREEMPT_GRACE_BOUNDS),
                "series": [
                    [[],
                     {"buckets": list(self.preempt_grace["buckets"]),
                      "sum": self.preempt_grace["sum"],
                      "count": self.preempt_grace["count"]}],
                ],
            })
        active = sum(
            1 for r in self.preemptions.values() if r["state"] == "draining"
        )
        out.append({
            "name": "preempt_active",
            "type": "gauge",
            "description": "victim gangs currently draining",
            "boundaries": None,
            "series": [[[], float(active)]],
        })
        # Per-tenant chip occupancy: TPU chips reserved by CREATED gangs
        # (named by their placement group) and by bare actors holding
        # chips outside any group.
        occ: Dict[str, float] = {}
        for pg in self.placement_groups.values():
            if pg["state"] != "CREATED":
                continue
            chips = sum(float(b.get("TPU", 0.0)) for b in pg["bundles"])
            if chips:
                t = self._pg_tenant(pg)
                occ[t] = occ.get(t, 0.0) + chips
        for a in self.actors.values():
            if a["state"] != "ALIVE":
                continue
            if (a.get("scheduling") or {}).get("type") == "placement_group":
                continue  # counted through its group
            chips = float((a.get("resources") or {}).get("TPU", 0.0))
            if chips:
                t = a.get("name") or a.get("class_name") or "actor"
                occ[t] = occ.get(t, 0.0) + chips
        if occ:
            out.append({
                "name": "tenant_chip_occupancy",
                "type": "gauge",
                "description": "TPU chips held, by tenant",
                "boundaries": None,
                "series": [
                    [[["tenant", t]], v] for t, v in occ.items()
                ],
            })
        for name, m in self.metrics.items():
            out.append(
                {
                    "name": name,
                    "type": m["type"],
                    "description": m["description"],
                    "boundaries": m.get("boundaries"),
                    "series": [
                        [[list(t) for t in key], val]
                        for key, val in m["series"].items()
                    ],
                }
            )
        return {"metrics": out}

    async def h_ping(self, d, conn):
        return {"pong": True, "time": time.time()}


def main():  # pragma: no cover - exercised as a subprocess
    """Entry point when GCS runs as its own process (gcs_server_main.cc:40)."""
    import argparse
    import sys

    p = argparse.ArgumentParser()
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    args = p.parse_args()

    async def run():
        server = GcsServer(args.host, args.port)
        port = await server.start()
        print(f"GCS_PORT={port}", flush=True)
        await asyncio.Event().wait()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        sys.exit(0)


if __name__ == "__main__":
    main()
