"""Raylet: the per-node daemon.

TPU-native analog of the reference raylet (src/ray/raylet/main.cc:119,
NodeManager at raylet/node_manager.h:125). Collapses into one asyncio
process:

  * cluster + local task scheduling  (ClusterTaskManager::QueueAndScheduleTask
                                      cluster_task_manager.cc:44,
                                      LocalTaskManager::Dispatch...
                                      local_task_manager.cc:105; hybrid policy
                                      policy/hybrid_scheduling_policy.cc:186)
  * worker pool                      (WorkerPool, raylet/worker_pool.h — here
                                      sized for the TPU world: a handful of
                                      whole-host workers, not hundreds)
  * dependency management            (raylet/dependency_manager.h — waits for
                                      arg objects to land in the local store
                                      before dispatch)
  * object transfer                  (ObjectManager::Push/Pull,
                                      object_manager.cc:339 — chunked pulls
                                      over the raylet RPC connection)
  * placement group bundles          (raylet/placement_group_resource_manager.h)

The shared-memory store is created and owned here (the reference runs plasma
in-process in the raylet: object_manager/plasma/store_runner.h).
"""

from __future__ import annotations

import asyncio
import heapq
import os
import subprocess
import sys
import time
from collections import defaultdict, deque
from typing import Any, Dict, List, Optional

from ray_tpu._private.config import get_config
from ray_tpu._private.ids import NodeID, ObjectID
from ray_tpu._private.object_store import ObjectStore
from ray_tpu.exceptions import ObjectStoreFullError
from ray_tpu._private.protocol import Connection, RpcServer, ServerConnection, connect, spawn
from ray_tpu.util import journal, lifecycle


class _PullByteBudget:
    """Admission control for pull transfers, by bytes, smallest-first.

    The reference's PullManager activates pulls under a memory quota in
    priority order (pull_manager.h:52). Here: a transfer is admitted when
    it fits the byte budget (or the budget is idle — one oversized object
    may always proceed alone); contended waiters are woken smallest-first
    so bulk restores can't starve cheap ready objects.
    """

    def __init__(self, budget_bytes: int):
        self.budget = budget_bytes
        self.in_use = 0
        self._seq = 0
        self._waiters: list = []  # heap of (size, seq, future)

    def _admissible(self, size: int) -> bool:
        return self.in_use == 0 or self.in_use + size <= self.budget

    async def acquire(self, size: int):
        if not self._waiters and self._admissible(size):
            self.in_use += size
            return
        fut = asyncio.get_event_loop().create_future()
        self._seq += 1
        heapq.heappush(self._waiters, (size, self._seq, fut))
        try:
            await fut
        except asyncio.CancelledError:
            # Cancelled after release() already charged our bytes: give
            # them back or the budget shrinks permanently (the
            # asyncio.Semaphore cancellation-window pattern).
            if fut.done() and not fut.cancelled() and fut.exception() is None:
                self.release(size)
            raise

    def release(self, size: int):
        self.in_use = max(0, self.in_use - size)
        while self._waiters:
            wsize, _, fut = self._waiters[0]
            if fut.cancelled():
                heapq.heappop(self._waiters)
                continue
            if not self._admissible(wsize):
                break
            heapq.heappop(self._waiters)
            self.in_use += wsize
            fut.set_result(None)


import functools


@functools.lru_cache(maxsize=1)
def _machine_id() -> str:
    """Identity of the physical host (hostname + kernel boot id): two
    raylets with equal machine ids share /dev/shm and can move objects by
    direct store-to-store memcpy instead of TCP. Immutable for the
    process lifetime — cached (the pull hot path compares it per
    candidate holder)."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            boot = f.read().strip()
    except OSError:
        boot = ""
    import socket as _socket

    return f"{_socket.gethostname()}:{boot}"


class WorkerHandle:
    def __init__(self, proc: subprocess.Popen, worker_id: bytes,
                 runtime_env_hash: Optional[str] = None):
        self.proc = proc
        self.worker_id = worker_id
        self.conn: Optional[ServerConnection] = None  # worker -> raylet conn
        self.port: Optional[int] = None  # worker's own RPC port
        self.idle = True
        self.actor_id: Optional[bytes] = None
        self.actor_resources: Dict[str, float] = {}  # held while actor alive
        self.current_task: Optional[bytes] = None
        self.last_idle_time = time.monotonic()
        # Workers are cached per runtime-env hash (worker_pool.h); a task
        # only dispatches to a worker started with its env.
        self.runtime_env_hash = runtime_env_hash
        # Direct-transport lease: resources held by an owner pushing tasks
        # straight to this worker (direct_task_transport.cc OnWorkerIdle).
        self.lease_resources: Optional[Dict[str, float]] = None
        self.leased_by = None  # owner ServerConnection while leased
        # max_calls retirement: excluded from dispatch/leases, killed
        # shortly after (the worker announced it is done).
        self.retired = False
        # Set when the worker registers (or is forgotten): actor creation
        # waits on this instead of a 50ms poll.
        self.registered = asyncio.Event()
        # Cached raylet->worker dial (the worker's own RPC port); lazily
        # opened for request/response ops like release_actor.
        self.dial: Optional[Connection] = None
        # Per-process stats sampled from /proc each heartbeat.
        self.cpu_percent: float = 0.0
        self.rss_bytes: int = 0


class Raylet:
    def __init__(
        self,
        gcs_host: str,
        gcs_port: int,
        resources: Dict[str, float],
        labels: Dict[str, str] | None = None,
        object_store_memory: int | None = None,
        is_head: bool = False,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        cfg = get_config()
        self.node_id = NodeID.from_random()
        self.gcs_host, self.gcs_port = gcs_host, gcs_port
        self.host = host
        self.resources_total = dict(resources)
        self.resources_available = dict(resources)
        self.labels = labels or {}
        self.is_head = is_head
        self.store_name = f"/rtstore_{self.node_id.hex()[:12]}"
        self.store = ObjectStore(
            self.store_name,
            object_store_memory or cfg.object_store_memory,
            create=True,
        )
        self.rpc = RpcServer(host, port)
        self.gcs: Optional[Connection] = None
        self.workers: Dict[bytes, WorkerHandle] = {}
        # True once the node has spawned any worker: gates the warm-pool
        # replenisher so idle nodes never fork spares.
        self._pool_demand_seen = False
        self._replenish_timer: Optional[asyncio.Task] = None
        # Queues keyed by scheduling class (resource shape + runtime-env
        # hash + pg bundle) — the reference queues per scheduling class
        # (cluster_task_manager.cc) so one blocked shape never forces a
        # rescan of every queued task: dispatch cost is O(classes +
        # dispatched), not O(queued), per wake-up. A single global deque
        # made a 10k-task drain O(n^2) (~100 tasks/s sustained).
        self.task_queues: Dict[tuple, deque] = {}  # class -> (spec, fut)
        # Resources demanded by queued-but-undispatched tasks; makes the
        # submit-time spillover decision aware of committed local work
        # (ClusterResourceScheduler accounts for queued demand the same way).
        self.queued_demand: Dict[str, float] = {}
        self.inflight: Dict[bytes, dict] = {}  # task_id -> {spec, fut, worker}
        self.bundles: Dict[tuple, Dict[str, float]] = {}  # (pg_id, idx) -> resources
        self.peer_conns: Dict[bytes, Connection] = {}
        self._peer_locks: Dict[bytes, asyncio.Lock] = {}
        self.node_cache: Dict[bytes, dict] = {}
        self._dispatch_event = asyncio.Event()
        self._zygote = None  # lazy ZygoteManager (worker fork server)
        self._proc_samples: Dict[int, tuple] = {}  # pid -> (jiffies, t)
        self._stopping = False
        self._bg: List[asyncio.Task] = []
        # Task state-transition events, batched to the GCS task-event sink
        # (TaskEventBuffer -> GcsTaskManager, task_event_buffer.h:206).
        self._task_events: List[dict] = []
        self._jobs: Dict[str, subprocess.Popen] = {}  # submission_id -> driver
        self._job_stops: set = set()  # submission_ids with a stop requested
        # runtime_env hash -> (error, ts): envs whose setup failed recently;
        # tasks targeting them fail fast instead of crash-looping workers.
        self._bad_runtime_envs: Dict[Optional[str], tuple] = {}
        # Primary-copy pinning + spill bookkeeping (LocalObjectManager:
        # primary copies are pinned in plasma and spilled — never silently
        # evicted; raylet/local_object_manager.h:41).
        self._primary_pins: Dict[bytes, int] = {}  # oid -> size (pin order)
        self._last_infeasible_check = 0.0
        # task_id -> resources for every queued-undispatched task; stable
        # across a dispatch pass (items in the pass-local requeue list are
        # still here), so heartbeats report true demand.
        self._queued_specs: Dict[bytes, Dict[str, float]] = {}
        # Graceful drain: set from heartbeat replies once the GCS cordons
        # this node (h_cordon_node); new work then spills remote.
        self._draining = False
        # ray_syncer-style delta sync state (_sync_resources).
        self._sync_version = 0
        self._synced_resources: Optional[Dict[str, float]] = None
        self._synced_demand_sig: Optional[int] = None
        self._infeasible_warned: set = set()
        self._queued_since: Dict[bytes, float] = {}
        self._spilled: Dict[bytes, str] = {}  # oid -> restore uri
        self._storage = None  # lazy external storage
        self._spill_lock = asyncio.Lock()
        self._object_waiters: Dict[bytes, List[asyncio.Event]] = defaultdict(list)
        # Pull admission control (PullManager analog, pull_manager.h:52):
        # bound concurrent inbound transfers so a burst of dependency
        # fetches can't thrash the store/network; single-flight per object.
        self._pull_slots = asyncio.Semaphore(cfg.pull_max_concurrent)
        # Flow control (VERDICT r2 item 7):
        #  * pull admission by BYTES with smallest-first priority under
        #    contention (PullManager's memory-quota + prioritized queue,
        #    object_manager/pull_manager.h:52) — a storm of large pulls
        #    cannot overcommit the store while small ready objects wait;
        #  * push-side in-flight chunk cap (PushManager throttling,
        #    push_manager.h:30) — a popular node bounds concurrent chunk
        #    reads it serves so one broadcast can't monopolize its loop.
        self._pull_budget = _PullByteBudget(
            max(int((object_store_memory or cfg.object_store_memory)
                    * cfg.pull_budget_fraction), 64 * 1024 * 1024)
        )
        self._push_chunk_slots = asyncio.Semaphore(cfg.push_chunk_slots)
        self._active_pulls: Dict[bytes, asyncio.Future] = {}
        # In-progress pulls exposing their contiguous filled prefix for
        # chained pullers: oid -> {buf, filled, total, event, failed}.
        self._partial_pulls: Dict[bytes, dict] = {}
        # Attached same-host peer stores (store_name -> ObjectStore).
        self._peer_stores: Dict[str, Any] = {}
        self._proc_stats_cursor = 0  # round-robin /proc sampling window
        # Bounds concurrent worker interpreter boots (actor creation
        # bursts) so the raylet loop keeps heartbeating under fork storms.
        self._boot_gate = asyncio.Semaphore(
            max(1, get_config().worker_boot_concurrency)
        )
        # Open chunked remote-client puts: oid -> (buffer, abort deadline).
        self._client_creates: Dict[bytes, tuple] = {}
        # Runtime metric counters (reported as deltas on the heartbeat).
        self._metrics_seq = 0
        self._metric_tasks_dispatched = 0
        self._metric_tasks_failed = 0
        self._metric_objects_spilled = 0
        # Scheduler queue instrumentation (control-plane profiler): how
        # many dispatch passes ran, how many head-of-queue scans they
        # did, how many leases were granted — plus last-pass gauges, so
        # "queue scans per dispatched task" is a reported number.
        self._metric_dispatch_passes = 0
        self._metric_dispatch_scans = 0
        self._metric_lease_grants = 0
        self._last_dispatch_batch = 0
        self._last_dispatch_scan = 0
        self._metric_reported: Dict[str, int] = {}
        # Control-plane profiler: enqueue stamps for sampled specs
        # (task_id -> (monotonic, epoch)), closed into queue_wait at
        # dispatch; bounded against leaks from forwarded/failed tasks.
        self._lc_enqueue: Dict[bytes, tuple] = {}

        r = self.rpc.register
        r("register_worker", self.h_register_worker)
        r("worker_env_failed", self.h_worker_env_failed)
        r("submit_task", self.h_submit_task)
        r("task_done", self.h_task_done)
        r("pull_object", self.h_pull_object)
        r("fetch_chunk", self.h_fetch_chunk)
        r("fetch_chunk_raw", self.h_fetch_chunk_raw)
        r("wait_object_local", self.h_wait_object_local)
        r("object_created", self.h_object_created)
        r("objects_created", self.h_objects_created)
        r("spill_objects", self.h_spill_objects)
        r("restore_spilled", self.h_restore_spilled)
        r("free_objects", self.h_free_objects)
        r("client_put", self.h_client_put)
        r("client_create", self.h_client_create)
        r("client_put_chunk", self.h_client_put_chunk)
        r("client_seal", self.h_client_seal)
        r("client_get_info", self.h_client_get_info)
        r("get_info", self.h_get_info)
        r("prestart_workers", self.h_prestart_workers)
        r("worker_stacks", self.h_worker_stacks)
        r("lease_worker", self.h_lease_worker)
        r("release_lease", self.h_release_lease)
        r("retire_worker", self.h_retire_worker)
        r("list_logs", self.h_list_logs)
        r("read_log", self.h_read_log)
        # A crashed owner must not leak its leased workers' resources.
        self.rpc.on_disconnect = self._on_client_disconnect

    # ------------------------------------------------------------------
    _GCS_CHANNELS = ("create_actor", "kill_actor_worker", "reserve_bundle",
                     "cancel_bundle", "node_dead", "node_added", "run_job",
                     "stop_job", "free_objects")

    async def _register_with_gcs(self, gcs):
        await gcs.call(
            "register_node",
            {
                "node_id": self.node_id.binary(),
                "address": self.host,
                "port": self.port,
                "object_store_name": self.store_name,
                "machine_id": _machine_id(),
                "resources": self.resources_total,
                "labels": self.labels,
                "is_head": self.is_head,
            },
        )
        for ch in self._GCS_CHANNELS:
            await gcs.call("subscribe", {"channel": ch})

    async def _reconnect_gcs(self):
        """The GCS died: redial until it (or its restarted successor) is
        back, then re-register this node and its subscriptions. This is the
        raylet half of GCS fault tolerance — live cluster state is rebuilt
        from re-registration, durable tables from the GCS snapshot
        (gcs_redis_failure_detector analog with roles reversed: raylets
        outlive the GCS instead of suiciding)."""
        while not self._stopping:
            try:
                gcs = await connect(
                    self.gcs_host, self.gcs_port,
                    push_handler=self._on_gcs_push,
                    timeout=get_config().gcs_reconnect_dial_timeout_s,
                )
                await self._register_with_gcs(gcs)
                self.gcs = gcs
                return
            except Exception:  # noqa: BLE001
                await asyncio.sleep(0.5)

    async def start(self) -> int:
        journal.set_process_label("raylet", weak=True)
        port = await self.rpc.start()
        self.port = port
        self.gcs = await connect(
            self.gcs_host, self.gcs_port, push_handler=self._on_gcs_push
        )
        await self._register_with_gcs(self.gcs)
        self._bg.append(asyncio.ensure_future(self._dispatch_loop()))
        self._bg.append(asyncio.ensure_future(self._heartbeat_loop()))
        self._bg.append(asyncio.ensure_future(self._reap_loop()))
        self._bg.append(asyncio.ensure_future(self._spill_loop()))
        self._bg.append(asyncio.ensure_future(self._memory_monitor_loop()))
        return port

    async def stop(self):
        self._stopping = True
        for t in self._bg:
            t.cancel()
        for w in self.workers.values():
            try:
                w.proc.terminate()
            except Exception:
                pass
        # One shared grace period, polled asynchronously: blocking per-worker
        # wait() would stall the event loop that delivers zygote-fork death
        # notices (2s per worker instead of 2s total).
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            if all(
                w.proc is None or w.proc.poll() is not None
                for w in self.workers.values()
            ):
                break
            await asyncio.sleep(0.05)
        for w in self.workers.values():
            try:
                if w.proc is not None and w.proc.poll() is None:
                    w.proc.kill()
            except Exception:
                pass
        # The zygote is process-shared (atexit-owned): not stopped here.
        await self.rpc.stop()
        if self.gcs:
            await self.gcs.close()
        self.store.destroy()

    async def kill(self):
        """Abrupt death for fault injection: SIGKILL the workers, drop every
        connection, no draining, no GCS goodbye — the in-process equivalent
        of `kill -9` on a raylet (chaos tests; RayletKiller analog)."""
        self._stopping = True
        for t in self._bg:
            t.cancel()
        for w in self.workers.values():
            try:
                w.proc.kill()
            except Exception:  # noqa: BLE001
                pass
        await self.rpc.stop()
        if self.gcs:
            await self.gcs.close()
        for c in self.peer_conns.values():
            try:
                await c.close()
            except Exception:  # noqa: BLE001
                pass
        self.peer_conns.clear()

    # -- GCS pushes ------------------------------------------------------
    def _on_gcs_push(self, channel: str, payload: Any):
        spawn(self._handle_gcs_push(channel, payload))

    async def _handle_gcs_push(self, channel: str, payload: Any):
        if channel == "create_actor":
            await self._create_actor_worker(payload)
        elif channel == "kill_actor_worker":
            aid = payload["actor_id"]
            for w in list(self.workers.values()):
                if w.actor_id == aid:
                    await self._report_worker_dead(w, intended=True, reason="rt.kill")
                    if payload.get("will_restart") or not (
                        await self._try_recycle_actor_worker(w, aid)
                    ):
                        w.proc.kill()
                        self._forget_worker(w)
        elif channel == "reserve_bundle":
            # Prepare phase: deduct from local availability so heartbeats
            # reflect the reservation and plain tasks cannot steal the
            # gang-reserved resources (placement_group_resource_manager.h).
            key = (payload["pg_id"], payload["bundle_index"])
            if key not in self.bundles:
                self.bundles[key] = {
                    "resources": dict(payload["resources"]),
                    "available": dict(payload["resources"]),
                }
                self._acquire(payload["resources"])
        elif channel == "cancel_bundle":
            bundle = self.bundles.pop(
                (payload["pg_id"], payload["bundle_index"]), None
            )
            if bundle is not None:
                # Credit only the bundle's *unused* share back: tasks still
                # running inside the bundle physically hold the rest, and
                # their completion release falls through to
                # resources_available once the bundle is gone. Crediting
                # the full reservation here would transiently oversubscribe
                # the node — routine under preemption, where bundles are
                # cancelled mid-flight all the time.
                for k, v in bundle["available"].items():
                    self.resources_available[k] = (
                        self.resources_available.get(k, 0) + v
                    )
                self._dispatch_event.set()
        elif channel == "run_job":
            await self._run_job(payload)
        elif channel == "stop_job":
            proc = self._jobs.get(payload["submission_id"])
            self._job_stops.add(payload["submission_id"])
            if proc is not None and proc.poll() is None:
                # The entrypoint runs under a shell: signal the whole
                # process group so the driver (and its children) die too,
                # not just the shell — otherwise the inherited stdout pipe
                # keeps the log stream (and job state) alive.
                import signal

                try:
                    os.killpg(proc.pid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    proc.terminate()
        elif channel == "free_objects":
            for oid in payload["object_ids"]:
                self._free_local(oid)
        elif channel == "node_added":
            # A new node may satisfy queued-infeasible tasks: re-check now.
            self.node_cache.pop(payload.get("node_id"), None)
            self._last_infeasible_check = 0.0
            self._dispatch_event.set()
        elif channel == "node_dead":
            nid = payload["node_id"]
            conn = self.peer_conns.pop(nid, None)
            if conn:
                await conn.close()
            self.node_cache.pop(nid, None)
            self._peer_locks.pop(nid, None)

    # -- worker pool -----------------------------------------------------
    def _spawn_worker(self, runtime_env: Optional[dict] = None) -> WorkerHandle:
        """Fork a worker process (WorkerPool::StartWorkerProcess analog)."""
        self._pool_demand_seen = True
        worker_id = os.urandom(16)
        env = dict(os.environ)
        if runtime_env:
            import json as _json

            env["RT_RUNTIME_ENV"] = _json.dumps(runtime_env)
            for k, v in (runtime_env.get("env_vars") or {}).items():
                env[k] = str(v)
        import ray_tpu

        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
        # Propagate this process's import paths so by-reference cloudpickle
        # functions (modules outside site-packages, e.g. the driver's
        # project) resolve in workers — the role the reference's
        # working_dir runtime env plays for the common co-located case.
        # (Standalone raylet daemons on other machines still need proper
        # code shipping via the GCS — future runtime-env work.)
        # Keep zipimport entries (files); drop empties so no implicit-cwd
        # component is ever synthesized by a trailing separator.
        env["PYTHONPATH"] = self._propagated_pythonpath(env.get("PYTHONPATH", ""))
        env.update(getattr(self, "spawn_env_overrides", None) or {})
        # Defer TPU tunnel attach: with PALLAS_AXON_POOL_IPS set,
        # sitecustomize registers the remote-TPU jax backend (importing all
        # of jax, ~2s) in EVERY interpreter at startup. Workers stash the
        # tunnel config instead and re-attach lazily the first time a task
        # actually requests TPU resources (worker_main.ensure_tpu_backend) —
        # control-plane workers spawn ~6x faster.
        if env.get("PALLAS_AXON_POOL_IPS") and not env.get("RT_EAGER_TPU_ATTACH"):
            env["RT_DEFERRED_TPU_TUNNEL"] = env.pop("PALLAS_AXON_POOL_IPS")
            if env.get("JAX_PLATFORMS"):
                env["RT_DEFERRED_JAX_PLATFORMS"] = env.pop("JAX_PLATFORMS")
        env["RT_WORKER_ID"] = worker_id.hex()
        env["RT_NODE_ID"] = self.node_id.hex()
        env["RT_RAYLET_PORT"] = str(self.port)
        env["RT_GCS_ADDR"] = f"{self.gcs_host}:{self.gcs_port}"
        env["RT_STORE_NAME"] = self.store_name
        # Fast path: fork from the zygote (one warm interpreter, see
        # _private/zygote.py) instead of booting a fresh interpreter +
        # imports (~300ms) per worker. Falls back to Popen while the
        # zygote warms up or if it keeps dying.
        proc = None
        if get_config().zygote_enabled and not env.get("RT_DISABLE_ZYGOTE"):
            if self._zygote is None:
                from ray_tpu._private.zygote_client import get_shared_manager

                self._zygote = get_shared_manager()
            proc = self._zygote.spawn(env)
        if proc is None:
            proc = subprocess.Popen(  # rtlint: disable=RT008 — fork+exec is bounded; worker spawn is a rare control-plane op and stdout is drained off-loop
                [sys.executable, "-m", "ray_tpu._private.worker_main"],
                env=env,
                stdout=None,
                stderr=None,
            )
        handle = WorkerHandle(
            proc, worker_id,
            runtime_env_hash=runtime_env.get("hash") if runtime_env else None,
        )
        self.workers[worker_id] = handle
        return handle

    async def h_register_worker(self, d, conn: ServerConnection):
        w = self.workers.get(d["worker_id"])
        if w is None:  # externally started (tests)
            w = WorkerHandle(None, d["worker_id"])
            self.workers[d["worker_id"]] = w
        w.conn = conn
        w.port = d["port"]
        w.registered.set()
        conn.meta["worker_id"] = d["worker_id"]
        # A successful start clears any recorded env failure for this hash.
        self._bad_runtime_envs.pop(w.runtime_env_hash, None)
        self._dispatch_event.set()
        return {"node_id": self.node_id.binary()}

    async def h_worker_env_failed(self, d, conn):
        """A starting worker could not materialize its runtime env: fail
        queued tasks with that env instead of crash-looping spawns."""
        renv_hash = d.get("runtime_env_hash")
        error = d.get("error", "runtime_env setup failed")
        self._bad_runtime_envs[renv_hash] = (error, time.monotonic())
        w = self.workers.get(d.get("worker_id"))
        if w is not None:
            self._forget_worker(w)
        self._dispatch_event.set()
        return {"ok": True}

    async def _try_recycle_actor_worker(self, w: WorkerHandle, aid: bytes) -> bool:
        """Return a cleanly-killed actor's worker to the pool instead of
        forking its replacement from scratch. The worker refuses (and the
        process dies, the reference semantics) when any call is still
        running — a thread mid-call cannot be stopped. Workers are already
        reused across tasks of a job; a torn-down actor has the same
        contamination surface."""
        cfg = get_config()
        if not cfg.actor_worker_recycle or w.port is None:
            return False
        # Only recycle while the pool is below the node's worker cap: a
        # 1000-actor teardown must not strand 1000 idle interpreters (and
        # their per-worker release RPCs) — beyond the cap the process
        # just dies. Up to the cap, recycled workers are exactly the pool
        # the next creation burst adopts from.
        n_pooled = sum(
            1 for x in self.workers.values()
            if x.actor_id is None and x.runtime_env_hash is None
            and x.lease_resources is None and x.idle
        )
        if n_pooled >= cfg.max_workers_per_node:
            return False
        try:
            # w.conn is the worker->raylet push channel (ServerConnection,
            # no request/response); dial the worker's own RPC port (cached
            # across recycles).
            if w.dial is None or w.dial._closed:
                w.dial = await connect(
                    "127.0.0.1", w.port,
                    timeout=cfg.worker_dial_timeout_s,
                )
            r = await asyncio.wait_for(
                w.dial.call("release_actor", {"actor_id": aid}),
                cfg.release_actor_timeout_s,
            )
        except Exception:  # noqa: BLE001 — worker wedged; kill it
            return False
        if not r.get("recycled"):
            return False
        # Return the actor's held resources (the _forget_worker accounting,
        # without forgetting the worker).
        bundle_key = getattr(w, "actor_bundle", None)
        bundle = self.bundles.get(bundle_key) if bundle_key else None
        if bundle is not None:
            for k, v in w.actor_resources.items():
                bundle["available"][k] = bundle["available"].get(k, 0) + v
        else:
            for k, v in w.actor_resources.items():
                self.resources_available[k] = (
                    self.resources_available.get(k, 0) + v
                )
        w.actor_resources = {}
        w.actor_id = None
        w.actor_bundle = None
        w.idle = True
        w.last_idle_time = time.monotonic()
        self._dispatch_event.set()
        return True

    def _replenish_idle_pool(self):
        """Keep a few registered default-env workers warm so actor creation
        and lease grants skip the fork+boot on their critical path (the
        reference's worker-pool prestart role, worker_pool.h:347 — here
        demand-triggered: nothing forks until the node first spawns).

        Debounced: the fork happens a beat later, off the creation/kill
        critical path, and not at all if a recycled worker returns to the
        pool in the meantime."""
        if not get_config().worker_pool_min_idle or not self._pool_demand_seen:
            return
        if self._replenish_timer is None or self._replenish_timer.done():
            self._replenish_timer = spawn(self._replenish_after_debounce())

    async def _replenish_after_debounce(self):
        await asyncio.sleep(get_config().worker_pool_replenish_debounce_s)
        cfg = get_config()
        n_pooled = sum(
            1 for w in self.workers.values()
            if w.actor_id is None and w.runtime_env_hash is None
            and w.lease_resources is None and (w.idle or w.conn is None)
        )
        n_spawn = min(
            cfg.worker_pool_min_idle - n_pooled,
            cfg.max_workers_per_node - len(self.workers),
        )
        for _ in range(max(0, n_spawn)):
            self._spawn_worker(None)

    def _forget_worker(self, w: WorkerHandle):
        self.workers.pop(w.worker_id, None)
        w.registered.set()  # wake creation waiters; they re-check liveness
        if w.actor_id is not None:
            # An actor worker died: top the pool back up so the next
            # creation burst adopts instead of forking.
            self._replenish_idle_pool()
        # Return a direct-transport lease's held resources.
        if w.lease_resources is not None:
            for k, v in w.lease_resources.items():
                self.resources_available[k] = (
                    self.resources_available.get(k, 0) + v
                )
            w.lease_resources = None
        # Return an actor worker's held resources.
        if w.actor_id is not None and w.actor_resources:
            bundle_key = getattr(w, "actor_bundle", None)
            bundle = self.bundles.get(bundle_key) if bundle_key else None
            if bundle is not None:
                for k, v in w.actor_resources.items():
                    bundle["available"][k] = bundle["available"].get(k, 0) + v
            else:
                for k, v in w.actor_resources.items():
                    self.resources_available[k] = (
                        self.resources_available.get(k, 0) + v
                    )
            w.actor_resources = {}

    async def _report_worker_dead(self, w: WorkerHandle, intended=False, reason=""):
        # The raylet death notice: first link after an injected kill in
        # the postmortem causal chain (it sees the process exit before
        # the GCS or any serve-layer observer).
        journal.emit(
            "raylet.worker_dead",
            actor_id=w.actor_id.hex() if w.actor_id else "",
            intended=bool(intended), reason=reason,
        )
        if not intended:
            from ray_tpu.util.event import record_event

            record_event(
                "raylet", f"worker died unexpectedly: {reason}",
                severity="WARNING",
                node_id=self.node_id.hex(),
                worker_id=w.worker_id.hex()
                if isinstance(w.worker_id, bytes) else str(w.worker_id),
                actor_id=w.actor_id.hex() if w.actor_id else None,
            )
        if w.actor_id is not None:
            await self.gcs.call(
                "worker_dead",
                {
                    "actor_id": w.actor_id,
                    "intended": intended,
                    "reason": reason,
                    "no_restart": False,
                },
            )

    async def _reap_loop(self):
        """Detect dead worker processes; fail their tasks/actors."""
        cfg = get_config()
        while True:
            await asyncio.sleep(cfg.reap_interval_s)
            # Abort chunked remote-client puts whose client vanished.
            now = time.monotonic()
            for oid, (buf, deadline) in list(self._client_creates.items()):
                if now > deadline:
                    self._client_creates.pop(oid, None)
                    del buf
                    try:
                        self.store.abort(ObjectID(oid))
                    except Exception:  # noqa: BLE001
                        pass
            for w in list(self.workers.values()):
                if w.proc is not None and w.proc.poll() is not None:
                    self._forget_worker(w)
                    # fail in-flight task
                    if w.current_task is not None:
                        entry = self.inflight.pop(w.current_task, None)
                        if entry and not entry["fut"].done():
                            entry["fut"].set_result(
                                {"status": "worker_crashed",
                                 "error": f"worker exited with code {w.proc.returncode}"}
                            )
                        if entry:
                            self._metric_tasks_failed += 1
                            self._release_task_resources(entry["spec"])
                            self._record_task_event(
                                entry["spec"], "FAILED", worker_id=w.worker_id
                            )
                    await self._report_worker_dead(
                        w, intended=False,
                        reason=f"worker process exited ({w.proc.returncode})",
                    )
                    self._dispatch_event.set()

    # -- memory monitor / OOM policy --------------------------------------
    def _sample_proc_stats(self):
        """Per-worker CPU%% + RSS from /proc (the reference's per-process
        native stats role, src/ray/stats/; sampled each heartbeat).
        Bounded per tick: at most proc_stats_sample_max workers are read
        per pass (round-robin), so observability cost stays O(1) per tick
        however many workers the node hosts."""
        page = os.sysconf("SC_PAGE_SIZE")
        hz = os.sysconf("SC_CLK_TCK")
        now = time.monotonic()
        workers = list(self.workers.values())
        cap = get_config().proc_stats_sample_max
        if len(workers) > cap:
            start = self._proc_stats_cursor % len(workers)
            self._proc_stats_cursor = (start + cap) % len(workers)
            workers = (workers + workers)[start:start + cap]
        for w in workers:
            pid = getattr(w.proc, "pid", None)
            if pid is None:
                continue
            try:
                with open(f"/proc/{pid}/stat") as f:
                    parts = f.read().rsplit(") ", 1)[-1].split()
                utime, stime = int(parts[11]), int(parts[12])
                with open(f"/proc/{pid}/statm") as f:
                    rss_pages = int(f.read().split()[1])
            except (OSError, IndexError, ValueError):
                continue
            jiffies = utime + stime
            prev = self._proc_samples.get(pid)
            cpu = 0.0
            if prev is not None and now > prev[1]:
                cpu = 100.0 * (jiffies - prev[0]) / hz / (now - prev[1])
            self._proc_samples[pid] = (jiffies, now)
            w.cpu_percent = round(max(cpu, 0.0), 1)
            w.rss_bytes = rss_pages * page
        # Prune exited workers: a recycled pid must not inherit a stale
        # jiffies baseline (wrong first sample), nor may the dict grow
        # with worker churn.
        live = {
            getattr(w.proc, "pid", None) for w in self.workers.values()
        }
        for pid in [p for p in self._proc_samples if p not in live]:
            del self._proc_samples[pid]

    def _memory_usage_fraction(self) -> float:
        """Node memory usage (tests override this).

        Prefers the memory cgroup when limited — in a container the cgroup
        OOM killer fires long before host MemAvailable moves, so reading
        /proc/meminfo alone would never trip the policy (the reference's
        MemoryMonitor reads cgroup usage the same way)."""
        try:
            # cgroup v2, then v1; a limit of "max"/huge means unlimited.
            for cur_p, max_p in (
                ("/sys/fs/cgroup/memory.current", "/sys/fs/cgroup/memory.max"),
                ("/sys/fs/cgroup/memory/memory.usage_in_bytes",
                 "/sys/fs/cgroup/memory/memory.limit_in_bytes"),
            ):
                try:
                    with open(max_p) as f:
                        raw = f.read().strip()
                    if raw == "max":
                        continue
                    limit = int(raw)
                    if limit <= 0 or limit > 1 << 60:
                        continue
                    with open(cur_p) as f:
                        current = int(f.read().strip())
                    return current / limit
                except (FileNotFoundError, ValueError):
                    continue
            info = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    key, _, rest = line.partition(":")
                    info[key] = int(rest.strip().split()[0])
            total = info.get("MemTotal", 0)
            if total <= 0 or "MemAvailable" not in info:
                return 0.0  # can't measure: never report pressure
            return 1.0 - info["MemAvailable"] / total
        except Exception:  # noqa: BLE001 — non-Linux or restricted /proc
            return 0.0

    def _pick_oom_victim(self):
        """Newest retriable task first, newest task as fallback — the
        reference's retriable-FIFO killing policy
        (raylet/worker_killing_policy.cc)."""
        candidates = []
        for entry in self.inflight.values():
            w = entry.get("worker")
            if w is None or w.proc is None:
                continue
            candidates.append(
                (bool(entry["spec"].get("retriable", True)),
                 entry.get("start", 0.0), w, entry)
            )
        if not candidates:
            return None
        retriable = [c for c in candidates if c[0]]
        pool = retriable or candidates
        pool.sort(key=lambda c: c[1])
        _, _, w, entry = pool[-1]
        return w, entry

    async def _memory_monitor_loop(self):
        """Kill a task's worker before the OS OOM-killer takes the raylet
        (reference: MemoryMonitor + worker_killing_policy.cc; threshold
        memory_usage_threshold, ray_config_def.h:77)."""
        cfg = get_config()
        if not cfg.memory_monitor_enabled or cfg.memory_monitor_interval_s <= 0:
            return
        while True:
            await asyncio.sleep(cfg.memory_monitor_interval_s)
            try:
                frac = self._memory_usage_fraction()
                if frac < cfg.memory_usage_threshold:
                    continue
                victim = self._pick_oom_victim()
                if victim is None:
                    continue
                w, entry = victim
                spec = entry["spec"]
                print(
                    f"[ray_tpu] memory monitor: node at "
                    f"{frac:.0%} >= {cfg.memory_usage_threshold:.0%}; "
                    f"killing worker of task "
                    f"{spec.get('name') or spec['task_id'].hex()[:8]} "
                    f"(newest retriable) — it will be retried.",
                    file=sys.stderr, flush=True,
                )
                self._record_task_event(
                    spec, "OOM_KILLED", worker_id=w.worker_id,
                    memory_fraction=frac,
                )
                try:
                    w.proc.kill()  # reap loop fails the task as retriable
                    from ray_tpu.util.event import record_event

                    record_event(
                        "raylet", "OOM policy killed a worker",
                        severity="ERROR",
                        node_id=self.node_id.hex(),
                        task=(entry["spec"].get("name") or ""),
                        memory_fraction=round(frac, 3),
                    )
                except Exception:  # noqa: BLE001
                    pass
            except Exception:  # noqa: BLE001
                pass

    @staticmethod
    def _set_actor_fields(w: WorkerHandle, payload, resources, sched, bundle):
        w.actor_id = payload["actor_id"]
        w.actor_resources = dict(resources)
        w.actor_bundle = (
            (sched["pg_id"], sched.get("bundle_index") or 0)
            if bundle is not None else None
        )

    async def _create_actor_worker(self, payload):
        """Spawn a dedicated worker for an actor and hand it the create spec.

        The actor's resources are held for the worker's lifetime (the
        reference acquires them through the lease protocol; tasks here
        release per-call, actors release on death)."""
        resources = payload.get("resources", {})
        sched = payload.get("scheduling") or {}
        bundle = None
        if sched.get("type") == "placement_group":
            bundle = self.bundles.get(
                (sched["pg_id"], sched.get("bundle_index") or 0)
            )
        if bundle is not None:
            for k, v in resources.items():
                bundle["available"][k] = bundle["available"].get(k, 0) - v
        else:
            if resources and not self._available_locally(resources):
                # The GCS placed against a stale advisory view (its
                # deduction raced a heartbeat overwrite). Acquiring anyway
                # would oversubscribe chips a placement group already
                # reserved — bounce the actor back to the pending queue,
                # where the retry loop re-places it (or arms preemption).
                await self.gcs.call(
                    "actor_unplaceable",
                    {"actor_id": payload["actor_id"],
                     "node_id": self.node_id.binary()},
                )
                return
            self._acquire(resources)
        renv = payload["create_spec"].get("runtime_env")
        # A registered idle pool worker with the right env adopts the actor
        # — the whole fork+boot disappears from the creation critical path
        # (the reference pops actors from the shared worker pool the same
        # way, worker_pool.cc PopWorker). A background replacement fork
        # keeps the pool warm for the next creation burst.
        w = self._idle_worker(renv.get("hash") if renv else None)
        if w is not None:
            w.idle = False
            self._replenish_idle_pool()
            self._set_actor_fields(w, payload, resources, sched, bundle)
        else:
            # Fork under the boot gate: a 1000-actor burst must not start
            # 1000 interpreter boots at once — unbounded boots starve the
            # raylet loop long enough for the GCS to declare the NODE dead
            # (health check timeout). K boots in flight keeps heartbeats
            # flowing; queued creations wait their turn.
            async with self._boot_gate:
                w = self._spawn_worker(renv)
                w.idle = False
                self._set_actor_fields(w, payload, resources, sched, bundle)
                self._replenish_idle_pool()
                # Wait for registration INSIDE the gate (the boot is the
                # resource being bounded; a second wait outside would
                # double the stall for a worker that never registers).
                # Budget covers runtime-env download/extraction in the
                # starting worker.
                try:
                    await asyncio.wait_for(
                        w.registered.wait(),
                        get_config().worker_register_timeout_s,
                    )
                except asyncio.TimeoutError:
                    pass
        if w.conn is None:
            await self.gcs.call(
                "worker_dead",
                {"actor_id": w.actor_id, "reason": "actor worker failed to start"},
            )
            return
        create_spec = dict(payload["create_spec"])
        # The worker gates its lazy TPU-backend attach on the resource shape.
        create_spec.setdefault("resources", resources)
        await w.conn.push("create_actor", create_spec)

    @staticmethod
    def _propagated_pythonpath(existing: str = "") -> str:
        """This process's import paths, for child processes (workers, job
        drivers) so by-reference code and ray_tpu itself resolve."""
        import ray_tpu

        pkg_root = os.path.dirname(
            os.path.dirname(os.path.abspath(ray_tpu.__file__))
        )
        extra = [p for p in sys.path if p and os.path.exists(p)]
        return os.pathsep.join(p for p in [pkg_root, *extra, existing] if p)

    # -- job supervision -------------------------------------------------
    async def _run_job(self, payload):
        """Spawn a detached driver subprocess for a submitted job and
        stream its output + exit state to the GCS (JobSupervisor analog,
        dashboard/modules/job/job_manager.py:140)."""
        submission_id = payload["submission_id"]
        env = dict(os.environ)
        env["RT_GCS_ADDR"] = f"{self.gcs_host}:{self.gcs_port}"
        env["RT_JOB_SUBMISSION_ID"] = submission_id
        renv = payload.get("runtime_env") or {}
        for k, v in (renv.get("env_vars") or {}).items():
            env[k] = str(v)
        cwd = None
        pkg_uris = list(renv.get("py_module_uris") or ())
        wd_uri = renv.get("working_dir_uri")
        if wd_uri or pkg_uris:
            from ray_tpu.runtime_env.runtime_env import GcsKvAdapter, _materialize

            kv = GcsKvAdapter(self.gcs, asyncio.get_event_loop())
            loop = asyncio.get_event_loop()
            try:
                extra_paths = []
                for uri in pkg_uris:
                    extra_paths.append(
                        await loop.run_in_executor(None, _materialize, kv, uri)
                    )
                if wd_uri:
                    cwd = await loop.run_in_executor(None, _materialize, kv, wd_uri)
                    extra_paths.insert(0, cwd)
                env["PYTHONPATH"] = os.pathsep.join(
                    [*extra_paths, env.get("PYTHONPATH", "")]
                ).rstrip(os.pathsep)
            except Exception as e:  # noqa: BLE001
                await self.gcs.call(
                    "job_update",
                    {"submission_id": submission_id, "state": "FAILED",
                     "message": f"runtime_env setup failed: {e}"},
                )
                return
        env["PYTHONPATH"] = self._propagated_pythonpath(env.get("PYTHONPATH", ""))
        if renv:
            import json as _json

            # The driver's ray_tpu.init() picks this up so the job's own
            # tasks/actors inherit the job runtime env.
            env["RT_JOB_RUNTIME_ENV"] = _json.dumps(renv)
        if submission_id in self._job_stops:
            # A stop arrived while the runtime env was materializing (the
            # proc was not in self._jobs yet): honor it instead of running
            # the driver to completion and reporting SUCCEEDED.
            self._job_stops.discard(submission_id)
            await self.gcs.call(
                "job_update",
                {"submission_id": submission_id, "state": "STOPPED",
                 "message": "stopped before start"},
            )
            return
        try:
            proc = subprocess.Popen(  # rtlint: disable=RT008 — fork+exec is bounded; job launch is rare and the streaming reads below are executor-shipped
                payload["entrypoint"],
                shell=True,
                env=env,
                cwd=cwd,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        except OSError as e:
            await self.gcs.call(
                "job_update",
                {"submission_id": submission_id, "state": "FAILED",
                 "message": f"failed to start: {e}"},
            )
            return
        self._jobs[submission_id] = proc
        if submission_id in self._job_stops:
            # Stop raced the Popen window: kill the fresh process group now;
            # _stream_job reports STOPPED when it reaps the signal exit.
            import signal

            try:
                os.killpg(proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                proc.terminate()
        await self.gcs.call(
            "job_update", {"submission_id": submission_id, "state": "RUNNING"}
        )
        spawn(self._stream_job(submission_id, proc))

    async def _stream_job(self, submission_id: str, proc: subprocess.Popen):
        import codecs

        loop = asyncio.get_event_loop()
        fd = proc.stdout.fileno()
        # Incremental decoder: a multibyte UTF-8 character split across a
        # read boundary carries over instead of becoming U+FFFD garbage.
        decoder = codecs.getincrementaldecoder("utf-8")(errors="replace")
        while True:
            # Raw fd read: returns as soon as ANY bytes arrive, so sparse
            # driver output streams live instead of waiting for a full
            # 64 KB buffered-read quantum.
            chunk = await loop.run_in_executor(None, os.read, fd, 65536)
            text = decoder.decode(chunk, final=not chunk)
            if not chunk and not text:
                break
            try:
                await self.gcs.call(
                    "job_log_append",
                    {"submission_id": submission_id, "data": text},
                )
            except Exception:
                pass
            if not chunk:
                break
        rc = await loop.run_in_executor(None, proc.wait)
        self._jobs.pop(submission_id, None)
        stop_requested = submission_id in self._job_stops
        self._job_stops.discard(submission_id)
        # A signal exit counts as STOPPED only when a stop was actually
        # requested; an OOM-kill or external SIGKILL is a failure.
        state = "SUCCEEDED" if rc == 0 else (
            "STOPPED" if rc < 0 and stop_requested else "FAILED"
        )
        try:
            await self.gcs.call(
                "job_update",
                {"submission_id": submission_id, "state": state,
                 "message": f"driver exited with code {rc}"},
            )
        except Exception:
            pass

    async def h_prestart_workers(self, d, conn):
        n = d.get("num", 1)
        for _ in range(n):
            self._spawn_worker()
        return {"ok": True}

    # -- task events -----------------------------------------------------
    def _record_task_event(self, spec: dict, state: str, **extra):
        ev = {
            "task_id": spec.get("task_id", b""),
            "name": spec.get("name") or "",
            "job_id": spec.get("job_id", b""),
            "node_id": self.node_id.binary(),
            "type": "NORMAL_TASK",
            "state": state,
            "ts": time.time(),
        }
        ev.update(extra)
        self._task_events.append(ev)

    # -- scheduling ------------------------------------------------------
    def _feasible_locally(self, resources: Dict[str, float]) -> bool:
        return all(
            self.resources_total.get(k, 0) + 1e-9 >= v for k, v in resources.items()
        )

    def _available_locally(self, resources: Dict[str, float]) -> bool:
        return all(
            self.resources_available.get(k, 0) + 1e-9 >= v
            for k, v in resources.items()
        )

    def _available_for_new_work(self, resources: Dict[str, float]) -> bool:
        """Availability minus demand already committed to the local queue."""
        return all(
            self.resources_available.get(k, 0) - self.queued_demand.get(k, 0) + 1e-9
            >= v
            for k, v in resources.items()
        )

    def _queued_demand_add(self, resources: Dict[str, float], sign: float,
                           spec: Optional[dict] = None):
        for k, v in resources.items():
            self.queued_demand[k] = self.queued_demand.get(k, 0) + sign * v
        # Mirror the queue in a pass-stable map so the heartbeat's demand
        # snapshot never observes the transient mid-dispatch empty queue.
        if spec is not None:
            if sign > 0:
                self._queued_specs[spec["task_id"]] = resources
            else:
                self._queued_specs.pop(spec["task_id"], None)
                self._queued_since.pop(spec["task_id"], None)
                self._infeasible_warned.discard(spec["task_id"])

    def _acquire(self, resources: Dict[str, float]):
        for k, v in resources.items():
            self.resources_available[k] = self.resources_available.get(k, 0) - v

    def _bundle_for(self, spec) -> Optional[dict]:
        pb = spec.get("pg_bundle")
        if not pb:
            return None
        return self.bundles.get((pb[0], pb[1]))

    def _could_acquire(self, spec) -> bool:
        """Non-mutating twin of _try_acquire_for: would this task's
        resources be acquirable right now? Used by the worker-spawn gate."""
        resources = spec.get("resources", {})
        if spec.get("pg_bundle") is not None:
            bundle = self._bundle_for(spec)
            return bundle is not None and all(
                bundle["available"].get(k, 0) + 1e-9 >= v
                for k, v in resources.items()
            )
        return self._available_locally(resources)

    def _try_acquire_for(self, spec) -> bool:
        """Acquire task resources — from its placement-group bundle if the
        task targets one, else from node availability."""
        resources = spec.get("resources", {})
        bundle = self._bundle_for(spec)
        if spec.get("pg_bundle") is not None:
            if bundle is None:
                return False  # bundle cancelled; caller errors the task
            avail = bundle["available"]
            if not all(avail.get(k, 0) + 1e-9 >= v for k, v in resources.items()):
                return False
            for k, v in resources.items():
                avail[k] = avail.get(k, 0) - v
            return True
        if not self._available_locally(resources):
            return False
        self._acquire(resources)
        return True

    def _release_task_resources(self, spec):
        resources = spec.get("resources", {})
        bundle = self._bundle_for(spec)
        if spec.get("pg_bundle") is not None:
            if bundle is not None:
                for k, v in resources.items():
                    bundle["available"][k] = bundle["available"].get(k, 0) + v
                return
            # Bundle cancelled while the task ran (preemption's normal
            # case): cancel_bundle credited only the bundle's unused
            # share, so this task's share goes straight back to the node
            # — dropping it would leak the resources for good.
        for k, v in resources.items():
            self.resources_available[k] = self.resources_available.get(k, 0) + v

    def _critical_utilization(self) -> float:
        util = 0.0
        for k, total in self.resources_total.items():
            if total > 0:
                util = max(
                    util, 1.0 - self.resources_available.get(k, 0) / total
                )
        return util

    async def _pick_node_by_labels(self, hard: Dict[str, str],
                                   soft: Dict[str, str]) -> Optional[bytes]:
        """NodeLabelSchedulingStrategy (util/scheduling_strategies.py:135
        in the reference): hard labels must all match; soft labels break
        ties."""
        resp = await self.gcs.call("get_nodes", {})
        best, best_soft = None, -1
        for n in resp["nodes"]:
            if n["state"] != "ALIVE" or n.get("draining"):
                continue
            labels = n.get("labels") or {}
            if not all(labels.get(k) == v for k, v in hard.items()):
                continue
            nsoft = sum(1 for k, v in soft.items() if labels.get(k) == v)
            if nsoft > best_soft:
                best, best_soft = n["node_id"], nsoft
        return best

    def _pick_remote_node_from(self, nodes, resources) -> Optional[dict]:
        """Best remote node by lowest utilization (hybrid policy tail)."""
        best, best_util = None, None
        for n in nodes:
            if (n["state"] != "ALIVE" or n.get("draining")
                    or n["node_id"] == self.node_id.binary()):
                continue
            avail, total = n["resources_available"], n["resources_total"]
            if not all(avail.get(k, 0) + 1e-9 >= v for k, v in resources.items()):
                continue
            util = 0.0
            for k, t in total.items():
                if t > 0:
                    util = max(util, 1.0 - avail.get(k, 0) / t)
            if best_util is None or util < best_util:
                best, best_util = n, util
        return best

    async def _pick_remote_node(self, resources) -> Optional[dict]:
        resp = await self.gcs.call("get_nodes", {})
        return self._pick_remote_node_from(resp["nodes"], resources)

    async def h_submit_task(self, d, conn):
        """Queue a task; the response resolves when the task completes.

        This fuses the reference's RequestWorkerLease
        (node_manager.cc:1722) + PushTask into a single call: the driver's
        submit RPC stays open (pipelined with others on the connection) and
        its response carries the result or its location.
        """
        spec = d
        fut = asyncio.get_event_loop().create_future()

        sched = spec.get("scheduling") or {}
        resources = spec.get("resources", {})
        target_node: Optional[bytes] = None

        hard_here = spec.get("hard_affinity") or (
            sched.get("type") == "node_affinity"
            and sched.get("node_id") == self.node_id.binary()
            and not sched.get("soft", False)
        )
        if self._draining and hard_here:
            # Hard affinity to a draining node can never be honored
            # (PG-scheduled work is exempt: its bundle holds resources
            # and the drain waits for the group's removal).
            return {
                "status": "error",
                "error": "node is draining: hard node affinity cannot "
                         "be honored",
            }

        if sched.get("type") == "node_affinity":
            target_node = sched["node_id"]
            if not sched.get("soft", False):
                # Survives the forward's scheduling strip so a draining
                # target can tell pinned-affinity work (reject) from
                # ordinary spillover (accept: it pre-dates the cordon).
                spec["hard_affinity"] = True
        elif sched.get("type") == "placement_group":
            pg = await self.gcs.call("get_placement_group", {"pg_id": sched["pg_id"]})
            if not pg["pg"] or pg["pg"]["state"] != "CREATED":
                return {"status": "error", "error": "placement group not ready"}
            idx = sched.get("bundle_index") or 0
            target_node = pg["pg"]["bundle_nodes"][idx]
            spec["pg_bundle"] = [sched["pg_id"], idx]
        elif sched.get("type") == "node_label":
            target_node = await self._pick_node_by_labels(
                sched.get("hard", {}), sched.get("soft", {})
            )
            if target_node is None:
                return {
                    "status": "error",
                    "error": f"no node matches hard labels {sched.get('hard')}",
                }
        elif sched.get("type") == "spread":
            node = await self._pick_remote_node(resources)
            if node is not None and self._critical_utilization() > 0:
                target_node = node["node_id"]

        if target_node is not None and target_node != self.node_id.binary():
            return await self._forward_task(spec, target_node)

        if target_node is None and not spec.get("forwarded"):
            # Hybrid policy (hybrid_scheduling_policy.cc:186): prefer local
            # until the critical resource passes the spread threshold, then
            # pick the least-utilized feasible remote node. Queued-but-
            # undispatched demand counts as local load. Forwarded tasks are
            # pinned here (single spillback, like the reference's lease
            # spillback counting).
            cfg = get_config()
            if (self._draining or not self._feasible_locally(resources)
                    or not self._available_for_new_work(resources)):
                node = await self._pick_remote_node(resources)
                if node is not None:
                    result = await self._forward_task(spec, node["node_id"])
                    if not (
                        result.get("status") == "error"
                        and "target node unavailable"
                        in str(result.get("error", ""))
                    ):
                        return result
                    # The chosen peer died mid-handoff: fall through and
                    # queue locally — retries/rescheduling own it from here.
                # No node fits today: stay queued — the dispatch loop
                # re-evaluates remote placement as nodes join (the
                # reference keeps infeasible tasks pending for the
                # autoscaler to satisfy).

        self._enqueue_task(spec, fut)
        self._queued_demand_add(resources, +1, spec)
        self._record_task_event(spec, "PENDING_SCHEDULING")
        if spec.get("sampled"):
            self._lc_enqueue[spec["task_id"]] = (time.monotonic(), time.time())
            if len(self._lc_enqueue) > 16384:
                # Entries for forwarded/cancelled tasks never close;
                # drop oldest rather than grow without bound.
                self._lc_enqueue.pop(next(iter(self._lc_enqueue)), None)
        self._dispatch_event.set()
        return await fut

    @staticmethod
    def _sched_class(spec) -> tuple:
        """Scheduling class: tasks in one class are interchangeable for
        dispatch (same resource shape, runtime env, bundle, and priority),
        so a blocked head task blocks only its own class. Priority leads
        the tuple: the dispatch loop walks classes highest-first, so a
        high-priority class never waits behind best-effort work for the
        same resources."""
        pg = spec.get("pg_bundle")
        return (
            int(spec.get("priority") or 0),
            spec.get("runtime_env_hash"),
            tuple(sorted((spec.get("resources") or {}).items())),
            tuple(pg) if pg else None,
        )

    def _enqueue_task(self, spec, fut):
        self.task_queues.setdefault(self._sched_class(spec), deque()).append(
            (spec, fut)
        )

    def _queued_task_count(self) -> int:
        return sum(len(q) for q in self.task_queues.values())

    async def h_lease_worker(self, d, conn):
        """Grant an idle worker to the calling owner for direct task
        pushes (RequestWorkerLease, direct_task_transport.cc:409). The
        lease holds the requested resources until release_lease, worker
        death, or owner disconnect; the owner streams run_task_direct
        calls straight to the worker, skipping this raylet per task."""
        if self._draining:
            # A cordoned node must not grant NEW leases: the lease path
            # bypasses h_submit's drain spill, so a colocated driver
            # would keep streaming work here and rt drain could only
            # time out. "none" pushes owners onto the submit path,
            # which spills remote.
            return {"status": "none"}
        resources = d.get("resources") or {}
        renv_hash = d.get("runtime_env_hash")
        worker = self._idle_worker(renv_hash)
        if worker is None or not self._available_locally(resources):
            # Opportunistically grow the pool so a later lease lands.
            if self._available_for_new_work(resources):
                cfg = get_config()
                n_live = sum(
                    1 for w in self.workers.values() if w.actor_id is None
                )
                n_starting = sum(
                    1 for w in self.workers.values()
                    if w.actor_id is None and w.conn is None
                    and w.runtime_env_hash == renv_hash
                )
                if n_live < cfg.max_workers_per_node and n_starting < 4:
                    self._spawn_worker(d.get("runtime_env"))
            return {"status": "none"}
        self._acquire(resources)
        worker.idle = False
        worker.lease_resources = dict(resources)
        worker.leased_by = conn  # released if this owner disconnects
        self._metric_lease_grants += 1
        return {
            "status": "ok",
            "worker_id": worker.worker_id,
            "host": self.host,
            "port": worker.port,
        }

    def _revoke_direct_leases(self):
        """Drain must also cover leases granted BEFORE the cordon: tell
        each lease's owner to stop streaming direct tasks here and hand
        the worker back (in-flight calls finish first, owner-side).
        Without this a colocated driver keeps the node busy via the
        lease path — which bypasses h_submit's drain spill — and
        rt drain can only time out."""
        for w in self.workers.values():
            conn = getattr(w, "leased_by", None)
            if w.lease_resources is not None and conn is not None \
                    and not conn.closed:
                spawn(conn.push("lease_revoked",
                                {"worker_id": w.worker_id}))

    def _release_lease_of(self, w: WorkerHandle):
        if w.lease_resources is None:
            return
        for k, v in w.lease_resources.items():
            self.resources_available[k] = (
                self.resources_available.get(k, 0) + v
            )
        w.lease_resources = None
        w.leased_by = None
        w.idle = True
        w.last_idle_time = time.monotonic()
        self._dispatch_event.set()

    async def h_release_lease(self, d, conn):
        w = self.workers.get(d["worker_id"])
        if w is not None:
            self._release_lease_of(w)
        return {"ok": True}

    @staticmethod
    def _log_dir() -> str:
        from ray_tpu._private.config import session_log_dir

        return session_log_dir()

    async def h_list_logs(self, d, conn):
        """This node's session log files (reference: the `ray logs` list
        served by per-node log agents, dashboard/modules/log)."""
        out = []
        base = self._log_dir()
        try:
            names = sorted(os.listdir(base))
        except FileNotFoundError:
            names = []
        for name in names:
            path = os.path.join(base, name)
            try:
                st = os.stat(path)
            except OSError:
                continue  # rotated/deleted mid-listing: skip just it
            if os.path.isfile(path):
                out.append({"name": name, "size": st.st_size,
                            "mtime": st.st_mtime})
        return {"logs": out}

    async def h_read_log(self, d, conn):
        """Tail of one named log file; the name is basename-sanitized so
        callers cannot escape the log directory."""
        name = os.path.basename(d.get("name", ""))
        if not name:
            return {"ok": False, "error": "missing log name"}
        path = os.path.join(self._log_dir(), name)  # basename: no escape
        n = int(d.get("tail_bytes", 64 * 1024))
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - n))
                data = f.read(n)
        except OSError as e:
            return {"ok": False, "error": str(e)}
        return {"ok": True, "data": data, "size": size}

    async def h_retire_worker(self, d, conn):
        """A worker crossed its max_calls threshold: stop dispatching to
        it and kill it shortly (reference: the worker exits after the
        task when @ray.remote(max_calls=N) is hit; here the raylet owns
        the removal so there is no window where a doomed worker still
        receives work)."""
        w = self.workers.get(d["worker_id"])
        if w is None:
            return {"ok": False}
        w.retired = True
        w.idle = False

        async def _kill_late():
            # Late fallback only: the worker flushes its in-flight
            # replies and self-exits (worker_main._retire). SIGTERM
            # here must not race the threshold-crossing task's reply
            # onto the worker->owner connection, so the grace period
            # is generous.
            await asyncio.sleep(3.0)
            try:
                if w.proc is not None and w.proc.poll() is None:
                    w.proc.terminate()
            except Exception:  # noqa: BLE001
                pass

        spawn(_kill_late())
        return {"ok": True}

    async def _on_client_disconnect(self, conn):
        """An owner connection died: return every lease it held (the
        reference's lease lifetime is likewise bounded by the owner,
        direct_task_transport.cc ReturnWorker on disconnect)."""
        for w in list(self.workers.values()):
            if getattr(w, "leased_by", None) is conn:
                self._release_lease_of(w)

    async def _forward_and_resolve(self, spec, fut, node_id: bytes):
        """Forward a queued task; on transport failure put it back in the
        queue (the task was promised to wait for capacity, not to fail on
        a flaky handoff)."""
        try:
            result = await self._forward_task(spec, node_id)
        except Exception as e:  # noqa: BLE001 — peer died mid-call
            result = {"status": "error",
                      "error": f"target node unavailable: {e}"}
        if (
            result.get("status") == "error"
            and "target node unavailable" in str(result.get("error", ""))
        ):
            if not fut.done():
                self._enqueue_task(spec, fut)
                self._queued_demand_add(spec.get("resources", {}), +1, spec)
                self._dispatch_event.set()
            return
        if not fut.done():
            fut.set_result(result)

    async def _forward_task(self, spec, node_id: bytes):
        conn = await self._peer(node_id)
        if conn is None:
            return {"status": "error", "error": "target node unavailable"}
        spec = dict(spec)
        spec["scheduling"] = None  # already routed
        spec["forwarded"] = True
        try:
            return await conn.call("submit_task", spec, timeout=None)
        except Exception as e:  # noqa: BLE001 — peer died mid-call
            return {
                "status": "error",
                "error": f"target node unavailable: {e}",
            }

    async def _peer(self, node_id: bytes) -> Optional[Connection]:
        # Single-flight per node: concurrent forwards must share one
        # connection (racing connects leaked Connections whose GC closed
        # sockets under pending calls).
        lock = self._peer_locks.setdefault(node_id, asyncio.Lock())
        async with lock:
            conn = self.peer_conns.get(node_id)
            if conn is not None and not conn._closed:
                return conn
            info = self.node_cache.get(node_id)
            if info is None:
                resp = await self.gcs.call("get_nodes", {})
                for n in resp["nodes"]:
                    self.node_cache[n["node_id"]] = n
                info = self.node_cache.get(node_id)
            if info is None or info["state"] != "ALIVE":
                return None
            try:
                # Short dial timeout: waiters queue behind this lock, so a
                # blackholed peer must fail fast, not serialize 10s stalls.
                conn = await connect(
                    info["address"], info["port"],
                    timeout=get_config().peer_dial_timeout_s,
                )
            except OSError:
                return None
            self.peer_conns[node_id] = conn
            return conn

    async def _dispatch_loop(self):
        """LocalTaskManager::DispatchScheduledTasksToWorkers analog.

        Per wake-up, each scheduling class dispatches from its own queue
        until that class blocks (no worker / no resources / infeasible).
        A blocked class costs O(1) per pass, so draining N homogeneous
        queued tasks is O(N) total, not O(N^2)."""
        cfg = get_config()
        while True:
            await self._dispatch_event.wait()
            self._dispatch_event.clear()
            ctx = {"nodes": None}  # one get_nodes snapshot per pass
            blocked = False
            self._metric_dispatch_passes += 1
            scans0 = self._metric_dispatch_scans
            dispatched0 = self._metric_tasks_dispatched
            # Highest priority class first (priority leads the class
            # tuple): a spike's tasks dispatch before best-effort work
            # contending for the same freed resources.
            for key in sorted(self.task_queues.keys(),
                              key=lambda k: -k[0]):
                q = self.task_queues.get(key)
                if not q:
                    self.task_queues.pop(key, None)
                    continue
                blocked |= await self._dispatch_class(q, ctx, cfg)
            self._last_dispatch_batch = self._metric_tasks_dispatched - dispatched0
            self._last_dispatch_scan = self._metric_dispatch_scans - scans0
            if self._last_dispatch_batch:
                # Per-PASS summary, never per task: dispatch decisions
                # reach the journal at wake-up granularity so a million-
                # task drain costs journal appends proportional to passes.
                journal.emit(
                    "raylet.dispatch",
                    granted=self._last_dispatch_batch,
                    scanned=self._last_dispatch_scan,
                    queued=sum(len(q) for q in self.task_queues.values()),
                )
            if blocked:
                # Blocked on resources/workers: rescan the moment anything
                # completes (h_task_done sets the event) instead of a fixed
                # sleep — the sleep gated every wave of a large batch to
                # 20ms and capped batched throughput at ~200 tasks/s. The
                # timeout keeps infeasible tasks re-checking for new nodes.
                try:
                    await asyncio.wait_for(
                        self._dispatch_event.wait(),
                        cfg.dispatch_rescan_interval_s,
                    )
                except asyncio.TimeoutError:
                    self._dispatch_event.set()

    async def _dispatch_class(self, q: deque, ctx: dict, cfg) -> bool:
        """Dispatch one scheduling class until it empties or blocks.
        Returns True if tasks remain queued (class is blocked)."""
        while q:
            spec, fut = q[0]
            self._metric_dispatch_scans += 1
            if fut.done():
                q.popleft()
                self._queued_demand_add(spec.get("resources", {}), -1, spec)
                continue
            resources = spec.get("resources", {})
            if spec.get("pg_bundle") is not None and self._bundle_for(spec) is None:
                q.popleft()
                self._queued_demand_add(resources, -1, spec)
                if not fut.done():
                    fut.set_result(
                        {"status": "error",
                         "error": "placement group bundle was removed"}
                    )
                continue
            if (self._draining or not self._feasible_locally(resources)) \
                    and not spec.get("forwarded"):
                # Infeasible here (or this node is draining): hand off
                # once a feasible node joins (autoscaled nodes register
                # with the GCS). One cluster snapshot per 0.5s pass
                # serves ALL infeasible classes — a poison class must not
                # starve placeable ones. While draining, queued demand
                # keeps the node's drain_status non-idle, so the drain
                # waits rather than stranding these tasks.
                now = time.monotonic()
                if ctx["nodes"] is None and now - self._last_infeasible_check >= 0.5:
                    self._last_infeasible_check = now
                    try:
                        ctx["nodes"] = (await self.gcs.call("get_nodes", {}))["nodes"]
                    except Exception:
                        ctx["nodes"] = []
                node = (
                    self._pick_remote_node_from(ctx["nodes"], resources)
                    if ctx["nodes"] is not None
                    else None
                )
                if node is not None:
                    node["resources_available"] = {
                        k: node["resources_available"].get(k, 0) - v
                        for k, v in resources.items()
                    } | {
                        k: v
                        for k, v in node["resources_available"].items()
                        if k not in resources
                    }
                    q.popleft()
                    self._queued_demand_add(resources, -1, spec)
                    spawn(
                        self._forward_and_resolve(spec, fut, node["node_id"])
                    )
                    continue
                tid = spec["task_id"]
                first = self._queued_since.setdefault(tid, now)
                if now - first > cfg.infeasible_warn_s and tid not in self._infeasible_warned:
                    self._infeasible_warned.add(tid)
                    print(
                        f"[ray_tpu] WARNING: task {spec.get('name') or tid.hex()[:8]} "
                        f"has been infeasible for 30s (needs {resources}); "
                        "no node in the cluster can satisfy it — waiting "
                        "for the autoscaler or a new node.",
                        file=sys.stderr, flush=True,
                    )
                return True
            deps = spec.get("deps") or []
            missing = [d for d in deps if not self.store.contains_raw(d)]
            if missing:
                q.popleft()
                spawn(self._fetch_then_requeue(spec, fut, missing))
                continue
            renv_hash = spec.get("runtime_env_hash")
            bad = self._bad_runtime_envs.get(renv_hash)
            if bad is not None and time.monotonic() - bad[1] < cfg.bad_runtime_env_ttl_s:
                q.popleft()
                self._queued_demand_add(resources, -1, spec)
                if not fut.done():
                    fut.set_result(
                        {"status": "error",
                         "error": f"runtime_env setup failed: {bad[0]}"}
                    )
                continue
            worker = self._idle_worker(renv_hash)
            if worker is None:
                if not self._could_acquire(spec):
                    # Every matching resource is already acquired by
                    # running tasks — a fresh worker could not take this
                    # task either. Spawning here is the storm that burns
                    # CPU on worker startup instead of task execution.
                    # (Bundle-targeted tasks check their bundle's share:
                    # a bundle reserving the whole node zeroes node
                    # availability, yet its own tasks must still spawn.)
                    return True
                # Spawn only as many workers as there is queued work,
                # counting ones still starting up (WorkerPool prestart
                # logic, worker_pool.h:347) — never a spawn storm.
                n_live = sum(
                    1 for w in self.workers.values() if w.actor_id is None
                )
                n_starting = sum(
                    1
                    for w in self.workers.values()
                    if w.actor_id is None and w.conn is None
                    and w.runtime_env_hash == renv_hash
                )
                # Bound prestart by how many tasks of this footprint can
                # actually run at once — with 4 free CPUs and CPU:1
                # tasks, 4 workers saturate the node; the 5th..16th only
                # burn startup CPU the running tasks need.
                cap = None
                for k, v in resources.items():
                    if v > 0:
                        c = int(self.resources_available.get(k, 0) // v)
                        cap = c if cap is None else min(cap, c)
                wanted = len(q)
                if cap is not None:
                    wanted = min(wanted, max(cap, 1))
                if n_live >= cfg.max_workers_per_node and n_starting == 0:
                    # Pool full of other-env workers: replace an idle one
                    # so a new env hash can't starve (the reference kills
                    # idle workers to make room the same way).
                    victim = next(
                        (
                            w
                            for w in self.workers.values()
                            if w.idle and w.actor_id is None
                            and w.conn is not None
                            and w.runtime_env_hash != renv_hash
                        ),
                        None,
                    )
                    if victim is not None:
                        try:
                            victim.proc.kill()
                        except Exception:
                            pass
                        self._forget_worker(victim)
                        n_live -= 1
                if n_live < cfg.max_workers_per_node and n_starting < wanted:
                    self._spawn_worker(spec.get("runtime_env"))
                return True
            if not self._try_acquire_for(spec):
                # Preemption cancels bundles at arbitrary points: when
                # that is why acquisition failed, error the task now
                # rather than leaving the whole class blocked until the
                # next pass's head check notices.
                if spec.get("pg_bundle") is not None \
                        and self._bundle_for(spec) is None:
                    q.popleft()
                    self._queued_demand_add(resources, -1, spec)
                    if not fut.done():
                        fut.set_result(
                            {"status": "error",
                             "error": "placement group bundle was removed"}
                        )
                    continue
                return True
            lc = (
                self._lc_enqueue.pop(spec["task_id"], None)
                if spec.get("sampled")
                else None
            )
            t_disp = time.monotonic()
            q.popleft()
            self._queued_demand_add(resources, -1, spec)
            worker.idle = False
            worker.current_task = spec["task_id"]
            self.inflight[spec["task_id"]] = {
                "spec": spec,
                "fut": fut,
                "worker": worker,
                "start": time.monotonic(),
            }
            self._metric_tasks_dispatched += 1
            self._record_task_event(
                spec, "RUNNING", worker_id=worker.worker_id
            )
            await worker.conn.push("run_task", spec)
            if lc is not None:
                # queue_wait: submit-RPC arrival -> dispatch decision;
                # dispatch: decision -> run_task pushed to the worker.
                qw = max(0.0, t_disp - lc[0])
                self._task_events.append(lifecycle.event(
                    spec["task_id"], spec.get("name") or "",
                    spec.get("job_id", b""), self.node_id.binary(),
                    "raylet",
                    {"queue_wait": [lc[1], qw],
                     "dispatch": [lc[1] + qw,
                                  max(0.0, time.monotonic() - t_disp)]},
                    worker_id=worker.worker_id,
                ))
        return False

    def _idle_worker(self, renv_hash: Optional[str] = None) -> Optional[WorkerHandle]:
        for w in self.workers.values():
            if (
                w.idle
                and not w.retired
                and w.conn is not None
                and w.actor_id is None
                and w.runtime_env_hash == renv_hash
            ):
                return w
        return None

    async def _fetch_then_requeue(self, spec, fut, missing):
        """DependencyManager analog: pull remote deps then requeue."""
        try:
            await asyncio.gather(*[self._ensure_local(oid) for oid in missing])
        except Exception as e:  # noqa: BLE001
            self._queued_demand_add(spec.get("resources", {}), -1, spec)
            if not fut.done():
                fut.set_result({"status": "error", "error": f"dependency fetch failed: {e}"})
            return
        self._enqueue_task(spec, fut)
        self._dispatch_event.set()

    def _free_local(self, oid: bytes):
        """Drop this node's copies of a freed object: primary pin, store
        entry (best-effort: readers still mapping it defer to LRU eviction,
        which reclaims refcount-0 objects with zero IO), and spill file."""
        if oid in self._primary_pins:
            try:
                self.store.release(ObjectID(oid))
            except Exception:  # noqa: BLE001
                pass
            self._primary_pins.pop(oid, None)
        try:
            self.store.delete(ObjectID(oid))
        except Exception:  # noqa: BLE001
            pass
        uri = self._spilled.pop(oid, None)
        if uri:
            try:
                self._get_storage().delete([uri])
            except Exception:  # noqa: BLE001
                pass

    async def h_free_objects(self, d, conn):
        """Owner-driven free (the last ObjectRef died): reclaim local
        copies, then let the GCS fan the free out to every other node
        holding a copy or a spill file."""
        oids = list(d["object_ids"])
        for oid in oids:
            self._free_local(oid)
        try:
            await self.gcs.call("objects_freed", {"object_ids": oids})
        except Exception:  # noqa: BLE001
            pass
        return {"ok": True, "count": len(oids)}

    async def h_task_done(self, d, conn):
        """Worker reports task completion (the PushTask reply path)."""
        entry = self.inflight.pop(d["task_id"], None)
        if entry is None:
            return {"ok": False}
        w = entry["worker"]
        w.idle = True
        w.current_task = None
        w.last_idle_time = time.monotonic()
        self._release_task_resources(entry["spec"])
        if d["result"].get("status") != "ok":
            self._metric_tasks_failed += 1
        self._record_task_event(
            entry["spec"],
            "FINISHED" if d["result"].get("status") == "ok" else "FAILED",
            worker_id=w.worker_id,
        )
        if not entry["fut"].done():
            entry["fut"].set_result(d["result"])
        self._dispatch_event.set()
        return {"ok": True}

    # -- object transfer -------------------------------------------------
    async def _ensure_local(self, oid_bytes: bytes, timeout: float = 60.0):
        """Pull an object into the local store (PullManager analog):
        single-flight per object, bounded concurrent transfers; spilled
        objects are restored by their spill node first
        (AsyncRestoreSpilledObject, local_object_manager.h:122)."""
        if self.store.contains_raw(oid_bytes):
            return
        # Single-flight per object: loop (not a one-shot check) so waiters
        # that wake concurrently never register duplicate pulls over each
        # other; a failed pull propagates so waiters retry deliberately.
        while True:
            existing = self._active_pulls.get(oid_bytes)
            if existing is None:
                break
            try:
                await asyncio.shield(existing)
            except asyncio.CancelledError:
                if not existing.done():
                    raise  # WE were cancelled; the leader is still going
                # The LEADER was cancelled: fall through and retry.
            except Exception:  # noqa: BLE001 — leader failed; we may retry
                pass
            if self.store.contains_raw(oid_bytes):
                return
        fut = asyncio.get_event_loop().create_future()
        fut.add_done_callback(lambda f: f.exception())  # consumed by waiters
        self._active_pulls[oid_bytes] = fut
        try:
            await self._ensure_local_inner(oid_bytes, timeout)
        except BaseException as e:
            if not fut.done():
                fut.set_exception(e)
            raise
        finally:
            self._active_pulls.pop(oid_bytes, None)
            if not fut.done():
                fut.set_result(None)

    async def _ensure_local_inner(self, oid_bytes: bytes, timeout: float = 60.0):
        if self.store.contains_raw(oid_bytes):
            return
        resp = await self.gcs.call(
            "object_location_wait", {"object_id": oid_bytes, "timeout": timeout}
        )
        spilled = resp.get("spilled")
        if not resp["nodes"] and spilled:
            spill_node = spilled["node_id"]
            if spill_node == self.node_id.binary():
                r = await self.h_restore_spilled({"object_id": oid_bytes}, None)
                if not r.get("ok"):
                    raise KeyError(
                        f"restore of spilled object {oid_bytes.hex()} failed: "
                        f"{r.get('error')}"
                    )
                return
            peer = await self._peer(spill_node)
            if peer is None:
                raise KeyError(
                    f"spill node for {oid_bytes.hex()} is unreachable"
                )
            r = await peer.call("restore_spilled", {"object_id": oid_bytes})
            if not r.get("ok"):
                raise KeyError(f"remote restore failed: {r.get('error')}")
            resp = await self.gcs.call(
                "object_location_get", {"object_id": oid_bytes}
            )
        me = self.node_id.binary()
        if resp.get("timeout") or (
            not resp["nodes"] and not self.store.contains_raw(oid_bytes)
        ):
            if self.store.contains_raw(oid_bytes):
                return
            raise KeyError(f"object {oid_bytes.hex()} has no locations")
        if self.store.contains_raw(oid_bytes):
            return
        # Announce this pull as a PARTIAL location: once chunks land,
        # other pullers may chain off our filled prefix instead of all
        # fanning into the source (chain/tree replication; reference
        # object_manager.cc:339 any-holder pulls). seq keeps chains
        # acyclic: we only ever chain to partials senior to us.
        reg = await self.gcs.call(
            "object_location_add",
            {"object_id": oid_bytes, "node_id": me, "partial": True},
        )
        my_seq = reg.get("seq")
        progress = {
            "buf": None, "filled": 0, "total": None,
            "event": asyncio.Event(), "failed": False,
        }
        self._partial_pulls[oid_bytes] = progress
        ok = False
        try:
            last_err = None
            for attempt in range(3):
                full = [n for n in resp["nodes"] if n != me]
                partials = [
                    nid for nid, seq in resp.get("partial_nodes", [])
                    if nid != me and seq < my_seq
                ]
                # Same-host holders first: their store lives in the same
                # /dev/shm, so the object moves as ONE cross-store memcpy
                # (no TCP, no chunking) — the multi-raylet-per-host case
                # the test clusters and single-host pods hit.
                if get_config().same_host_shm_transfer:
                    for nid in full:
                        info = await self._node_info(nid)
                        if (
                            info
                            and info.get("machine_id")
                            and info.get("machine_id") == _machine_id()
                            and info.get("object_store_name")
                        ):
                            try:
                                if await self._shm_copy_from(
                                    info["object_store_name"], oid_bytes
                                ):
                                    await self.gcs.call(
                                        "object_location_add",
                                        {"object_id": oid_bytes, "node_id": me,
                                         "size": resp.get("size") or 0},
                                    )
                                    ok = True
                                    return
                            except Exception as e:  # noqa: BLE001
                                last_err = e
                for nid in full + partials:
                    peer = await self._peer(nid)
                    if peer is None:
                        continue
                    try:
                        async with self._pull_slots:
                            # Admission control bounds the TRANSFER only —
                            # holding a slot across object_location_wait
                            # would let 8 unproduced dependencies starve
                            # ready pulls for 60s. Byte budget on top:
                            # smallest-first under contention.
                            size = int(resp.get("size") or 0)
                            await self._pull_budget.acquire(size)
                            try:
                                await self._pull_from(
                                    peer, oid_bytes, size, progress
                                )
                            finally:
                                self._pull_budget.release(size)
                        await self.gcs.call(
                            "object_location_add",
                            {
                                "object_id": oid_bytes,
                                "node_id": me,
                                "size": resp["size"],
                            },
                        )
                        ok = True
                        return
                    except Exception as e:  # noqa: BLE001
                        last_err = e
                # Every candidate failed (e.g. our upstream partial
                # aborted): refresh the location view and retry.
                resp = await self.gcs.call(
                    "object_location_get", {"object_id": oid_bytes}
                )
                if self.store.contains_raw(oid_bytes):
                    ok = True
                    return
            raise KeyError(
                f"failed to pull object {oid_bytes.hex()}: {last_err}"
            )
        finally:
            self._partial_pulls.pop(oid_bytes, None)
            progress["failed"] = not ok
            progress["event"].set()  # wake chained servers either way
            if not ok:
                try:
                    await self.gcs.call(
                        "object_location_remove",
                        {"object_id": oid_bytes, "node_id": me,
                         "partial_only": True},
                    )
                except Exception:  # noqa: BLE001
                    pass

    async def _node_info(self, node_id: bytes) -> Optional[dict]:
        info = self.node_cache.get(node_id)
        if info is None:
            resp = await self.gcs.call("get_nodes", {})
            for n in resp["nodes"]:
                self.node_cache[n["node_id"]] = n
            info = self.node_cache.get(node_id)
        return info

    def _attach_peer_store(self, store_name: str):
        st = self._peer_stores.get(store_name)
        if st is None:
            try:
                st = ObjectStore(store_name)
            except Exception:  # noqa: BLE001 — peer store gone/unreachable
                return None
            self._peer_stores[store_name] = st
        return st

    async def _shm_copy_from(self, store_name: str, oid_bytes: bytes) -> bool:
        """Copy a sealed object straight out of a same-host peer's shared
        -memory store (cross-process get/release ride the store's robust
        shm mutex). Returns False if the peer doesn't hold it."""
        peer_store = self._attach_peer_store(store_name)
        if peer_store is None:
            return False
        from ray_tpu._private.ids import ObjectID

        oid = ObjectID(oid_bytes)
        view = peer_store.get(oid)  # refcount pin against peer eviction
        if view is None:
            return False
        try:
            total = len(view)
            buf = await self._create_with_spill(oid, total)
            if buf is None:
                return True  # a concurrent pull materialized it
            try:
                # Same teardown guard as the chunk path: no await between
                # the check and the write into our (possibly unmapped on
                # stop) store.
                if self._stopping:
                    raise asyncio.CancelledError("raylet stopping")
                buf[:] = view
            except BaseException:
                del buf
                self.store.abort(oid)
                raise
            del buf
            self.store.seal(oid)
            self.store.release(oid)
            return True
        finally:
            del view
            peer_store.release(oid)

    async def _pull_from(self, peer: Connection, oid_bytes: bytes, size: int,
                         progress: Optional[dict] = None):
        """Chunked pull (ObjectManager::Push sends 5MiB chunks,
        object_manager.cc:325; chunk size ray_config_def.h:362).
        A WINDOW of chunk fetches rides the connection concurrently
        (request/response round trips hide behind each other), and the
        contiguous filled prefix is published through `progress` so
        chained pullers can consume it mid-transfer."""
        cfg = get_config()
        from ray_tpu._private.ids import ObjectID

        oid = ObjectID(oid_bytes)
        meta = await peer.call("pull_object", {"object_id": oid_bytes})
        if not meta.get("ok"):
            raise KeyError(meta.get("error", "remote miss"))
        total = meta["size"]
        if self.store.contains(oid):
            return
        buf = await self._create_with_spill(oid, total)
        if buf is None:
            return  # concurrent pull is materializing it
        chunk = cfg.object_transfer_chunk_size
        offsets = list(range(0, total, chunk))
        received: set = set()
        if progress is not None:
            progress["buf"] = buf
            progress["total"] = total
        window = asyncio.Semaphore(max(1, cfg.pull_chunk_window))

        async def fetch(off: int):
            n = min(chunk, total - off)
            async with window:
                resp = await peer.call(
                    "fetch_chunk_raw",
                    {"object_id": oid_bytes, "offset": off, "size": n},
                )
            data = resp[1]  # (header, raw payload)
            if len(data) != n:
                raise KeyError(
                    f"short chunk at {off}: {len(data)} != {n}"
                )
            # No await between this check and the write: stop()/kill()
            # run on this same loop, so a raylet that began teardown (and
            # may have unmapped the store) can never interleave INSIDE
            # the write — writing after unmap is a segfault.
            if self._stopping:
                raise asyncio.CancelledError("raylet stopping")
            buf[off:off + n] = data
            received.add(off)
            if progress is not None:
                # Advance the contiguous prefix; wake chained servers.
                filled = progress["filled"]
                while filled < total and filled in received:
                    received.discard(filled)
                    filled = min(filled + chunk, total)
                progress["filled"] = filled
                progress["event"].set()
                progress["event"] = asyncio.Event()

        try:
            await asyncio.gather(*[fetch(off) for off in offsets])
        except BaseException:
            if progress is not None:
                progress["buf"] = None
            del buf
            if not self._stopping:  # teardown may have closed the store
                self.store.abort(oid)
            raise
        if self._stopping:
            del buf
            raise asyncio.CancelledError("raylet stopping")
        if progress is not None:
            progress["filled"] = total
            progress["buf"] = None
            progress["event"].set()
            progress["event"] = asyncio.Event()
        del buf
        self.store.seal(oid)
        self.store.release(oid)

    async def h_pull_object(self, d, conn):
        from ray_tpu._private.ids import ObjectID

        oid = ObjectID(d["object_id"])
        view = self.store.get(oid)
        if view is not None:
            size = len(view)
            del view
            self.store.release(oid)
            return {"ok": True, "size": size}
        p = self._partial_pulls.get(d["object_id"])
        if p is not None and not p["failed"] and p["total"] is not None:
            return {"ok": True, "size": p["total"]}
        return {"ok": False, "error": "not found"}

    async def _read_chunk(self, oid_bytes: bytes, off: int, size: int) -> bytes:
        """One chunk from the sealed copy or an in-progress pull's filled
        prefix (chained replication), waiting briefly for the prefix to
        advance."""
        from ray_tpu._private.ids import ObjectID

        oid = ObjectID(oid_bytes)
        deadline = time.monotonic() + get_config().chunk_serve_wait_s
        while True:
            view = self.store.get(oid)
            if view is not None:
                # Sealed copy: serve under the PushManager in-flight cap.
                try:
                    async with self._push_chunk_slots:
                        return bytes(view[off:off + size])
                finally:
                    del view
                    self.store.release(oid)
            p = self._partial_pulls.get(oid_bytes)
            if p is None or p["failed"]:
                raise KeyError("object evicted mid-transfer")
            if p["buf"] is not None and p["filled"] >= off + size:
                async with self._push_chunk_slots:
                    return bytes(p["buf"][off:off + size])
            if time.monotonic() > deadline:
                raise KeyError("upstream pull stalled")
            # Wait (OUTSIDE the chunk slots — a stalled upstream must not
            # starve other transfers) for the prefix to advance.
            ev = p["event"]
            try:
                await asyncio.wait_for(ev.wait(), timeout=1.0)
            except asyncio.TimeoutError:
                pass

    async def h_fetch_chunk(self, d, conn):
        return {"data": await self._read_chunk(
            d["object_id"], d["offset"], d["size"])}

    async def h_fetch_chunk_raw(self, d, conn):
        """Raw-payload variant: the chunk bytes follow the response frame
        without a msgpack pass (the raylet<->raylet bulk path)."""
        from ray_tpu._private.protocol import BinResponse

        data = await self._read_chunk(d["object_id"], d["offset"], d["size"])
        return BinResponse({"n": len(data)}, data)

    # -- remote (rt://) clients -------------------------------------------
    # The reference's Ray Client (util/client/worker.py:81) proxies a
    # driver with no node-local runtime. Here a remote driver holds only
    # TCP connections: puts ship serialized bytes into this raylet's
    # store; gets read size here then stream chunks via fetch_chunk.

    async def h_worker_stacks(self, d, conn):
        """Collect live thread stacks from every registered worker on this
        node (the `rt stack` backend; reference: on-demand py-spy dumps
        via the dashboard reporter agent)."""
        from ray_tpu._private.protocol import connect as _connect

        out = []
        for wid, w in list(self.workers.items()):
            if not w.port:
                continue
            try:
                wconn = await _connect("127.0.0.1", w.port, timeout=5)
                try:
                    dump = await asyncio.wait_for(
                        wconn.call("dump_stacks", {}), 10
                    )
                finally:
                    await wconn.close()
                out.append(dump)
            except Exception as e:  # noqa: BLE001 — dead/busy worker
                out.append({
                    "worker_id": wid, "error": f"{type(e).__name__}: {e}",
                })
        return {"node_id": self.node_id.binary(), "workers": out}

    async def h_client_put(self, d, conn):
        oid = ObjectID(d["object_id"])
        data = d["data"]
        if not self.store.contains_raw(d["object_id"]):
            buf = await self._create_with_spill(oid, len(data))
            if buf is not None:
                buf[:] = data
                self.store.seal(oid)
                self.store.release(oid)
            else:
                # Concurrent writer owns the buffer: wait until it seals so
                # the ok below really means "readable" (mirrors
                # h_restore_spilled's handling of the same race).
                if not await self._wait_sealed(d["object_id"]):
                    return {"ok": False, "error": "concurrent put never sealed"}
        r = await self.h_object_created(
            {"object_id": d["object_id"], "size": len(data)}, conn
        )
        return {"ok": bool(r.get("ok", True))}

    async def h_client_create(self, d, conn):
        """Begin a chunked remote put: allocate the buffer, hold it until
        client_seal (reaped if the client vanishes)."""
        oid = ObjectID(d["object_id"])
        if self.store.contains_raw(d["object_id"]):
            return {"ok": True, "exists": True}
        buf = await self._create_with_spill(oid, d["size"])
        if buf is None:
            if not await self._wait_sealed(d["object_id"]):
                return {"ok": False, "error": "concurrent put never sealed"}
            return {"ok": True, "exists": True}
        self._client_creates[d["object_id"]] = (
            buf, time.monotonic() + get_config().client_create_ttl_s
        )
        return {"ok": True, "exists": False}

    async def h_client_put_chunk(self, d, conn):
        entry = self._client_creates.get(d["object_id"])
        if entry is None:
            return {"ok": False, "error": "no open create for object"}
        buf, _ = entry
        off = d["offset"]
        buf[off:off + len(d["data"])] = d["data"]
        return {"ok": True}

    async def h_client_seal(self, d, conn):
        entry = self._client_creates.pop(d["object_id"], None)
        if entry is None:
            return {"ok": False, "error": "no open create for object"}
        oid = ObjectID(d["object_id"])
        self.store.seal(oid)
        self.store.release(oid)
        r = await self.h_object_created(
            {"object_id": d["object_id"], "size": d["size"]}, conn
        )
        return {"ok": bool(r.get("ok", True))}

    async def h_client_get_info(self, d, conn):
        """Ensure the object is local and return its size (the client then
        streams it out with fetch_chunk)."""
        oid = d["object_id"]
        await self._ensure_local(oid, timeout=d.get("timeout", 60.0))
        view = self.store.get(ObjectID(oid))
        if view is None:
            return {"ok": False, "error": "object not available"}
        try:
            size = len(view)
        finally:
            del view
            self.store.release(ObjectID(oid))
        return {"ok": True, "size": size}

    async def h_wait_object_local(self, d, conn):
        """Driver asks: make this object available in the local store."""
        from ray_tpu._private import chaos

        delay = chaos.take_pull_delay()
        if delay is not None:  # chaos-only: modelled slow transfer
            await asyncio.sleep(delay)
        await self._ensure_local(d["object_id"], d.get("timeout", 60.0))
        return {"ok": True}

    # -- spilling (LocalObjectManager analog) ----------------------------
    def _get_storage(self):
        if self._storage is None:
            from ray_tpu._private.external_storage import create_storage

            self._storage = create_storage(
                self.node_id.hex(), get_config().spill_dir or None
            )
        return self._storage

    def _pin_created(self, oid: bytes, size: int) -> bool:
        """Pin a freshly sealed primary copy so LRU eviction cannot drop
        the only copy."""
        if oid not in self._primary_pins:
            view = self.store.get(ObjectID(oid))
            if view is None:
                return False
            del view  # the store-side refcount holds the pin, not the view
            self._primary_pins[oid] = size
        self._spilled.pop(oid, None)
        return True

    async def h_object_created(self, d, conn):
        """A local client sealed a primary copy: pin + register location."""
        oid = d["object_id"]
        if not self._pin_created(oid, d.get("size", 0)):
            return {"ok": False, "error": "object not found at pin time"}
        await self.gcs.call(
            "object_location_add",
            {"object_id": oid, "node_id": self.node_id.binary(),
             "size": d.get("size", 0)},
        )
        return {"ok": True}

    async def h_objects_created(self, d, conn):
        """Batched seal notifications from one client flush: pin each and
        register every location with the GCS in a single frame."""
        registered = []
        for o in d["objects"]:
            if self._pin_created(o["object_id"], o.get("size", 0)):
                registered.append(
                    {"object_id": o["object_id"], "size": o.get("size", 0)}
                )
        if registered:
            await self.gcs.call(
                "object_locations_add",
                {"node_id": self.node_id.binary(), "objects": registered},
            )
        return {"ok": True}

    def _utilization(self) -> float:
        s = self.store.stats()
        return s["used_bytes"] / max(1, s["heap_size"])

    async def _create_with_spill(self, obj: ObjectID, size: int):
        """store.create with spill-and-retry under pressure. Returns the
        writable buffer, or None if the object already exists (concurrent
        writer). Raises ObjectStoreFullError when room cannot be made."""
        for attempt in range(6):
            try:
                return self.store.create(obj, size)
            except ObjectStoreFullError:
                n = await self._spill_until(
                    get_config().object_spilling_low_water
                )
                # A concurrent spill (shared _spill_lock) may have freed
                # room between our failed create and this pass — always
                # retry; back off only when nothing moved.
                if not n and attempt >= 2:
                    await asyncio.sleep(0.25)
            except ValueError:
                return None
        raise ObjectStoreFullError(f"no room for {size} bytes after spilling")

    async def _wait_sealed(self, oid: bytes, timeout: float = 30.0) -> bool:
        """Wait until a concurrently-written object is sealed (readable)."""
        deadline = time.monotonic() + timeout
        obj = ObjectID(oid)
        while time.monotonic() < deadline:
            view = self.store.get(obj)
            if view is not None:
                del view
                self.store.release(obj)
                return True
            if not self.store.contains_raw(oid):
                return False  # aborted/evicted mid-write
            await asyncio.sleep(0.02)
        return False

    async def _spill_until(self, target_utilization: float) -> int:
        """Spill pinned primaries (oldest first) until below the target."""
        async with self._spill_lock:
            spilled = 0
            storage = self._get_storage()
            loop = asyncio.get_event_loop()
            for oid in list(self._primary_pins):
                if self._utilization() <= target_utilization:
                    break
                obj = ObjectID(oid)
                view = self.store.get(obj)
                if view is None:
                    self._primary_pins.pop(oid, None)
                    continue
                try:
                    uri = await loop.run_in_executor(
                        None, storage.spill, oid, view
                    )
                finally:
                    del view
                    self.store.release(obj)  # drop the read pin we just took
                self.store.release(obj)  # drop the primary pin
                self._primary_pins.pop(oid, None)
                if not self.store.delete(obj):
                    # A local client holds a live view: re-pin and keep it.
                    v = self.store.get(obj)
                    if v is not None:
                        del v
                        self._primary_pins[oid] = 0
                    storage.delete([uri])
                    continue
                self._spilled[oid] = uri
                self._metric_objects_spilled += 1
                spilled += 1
                await self.gcs.call(
                    "object_spilled",
                    {"object_id": oid, "node_id": self.node_id.binary(),
                     "uri": uri},
                )
            return spilled

    async def h_spill_objects(self, d, conn):
        """A client's put hit ObjectStoreFull: make room."""
        cfg = get_config()
        n = await self._spill_until(cfg.object_spilling_low_water)
        return {"ok": True, "spilled": n}

    async def h_restore_spilled(self, d, conn):
        """Restore a spilled object into the local store and re-register."""
        oid = d["object_id"]
        if self.store.contains_raw(oid):
            return {"ok": True}
        uri = self._spilled.get(oid)
        if uri is None:
            return {"ok": False, "error": "object was not spilled here"}
        storage = self._get_storage()
        data = await asyncio.get_event_loop().run_in_executor(
            None, storage.restore, uri
        )
        obj = ObjectID(oid)
        try:
            buf = await self._create_with_spill(obj, len(data))
        except ObjectStoreFullError:
            return {"ok": False,
                    "error": "store full; nothing left to spill"}
        if buf is None:
            # A concurrent restore is writing: only report ok once it has
            # sealed, or the requester may pull an unreadable object.
            ok = await self._wait_sealed(oid)
            return {"ok": ok} if ok else {
                "ok": False, "error": "concurrent restore did not complete"
            }
        buf[: len(data)] = data
        del buf
        self.store.seal(obj)
        # Keep the get-pin as the primary pin.
        self._primary_pins[oid] = len(data)
        self._spilled.pop(oid, None)
        await self.gcs.call(
            "object_location_add",
            {"object_id": oid, "node_id": self.node_id.binary(),
             "size": len(data), "restored": True},
        )
        return {"ok": True}

    async def _spill_loop(self):
        """Background pressure valve (SpillObjectsOfSize trigger)."""
        cfg = get_config()
        while True:
            await asyncio.sleep(0.25)
            try:
                if self._utilization() > cfg.object_spilling_threshold:
                    await self._spill_until(cfg.object_spilling_low_water)
            except Exception:
                if self._stopping:
                    return

    async def h_get_info(self, d, conn):
        return {
            "node_id": self.node_id.binary(),
            "store_name": self.store_name,
            "resources_total": self.resources_total,
            "resources_available": self.resources_available,
            "store_stats": self.store.stats(),
            "workers": [
                {
                    "worker_id": w.worker_id,
                    "pid": w.proc.pid if w.proc is not None else None,
                    "idle": w.idle,
                    "actor_id": w.actor_id,
                    "current_task": w.current_task,
                    "cpu_percent": w.cpu_percent,
                    "rss_bytes": w.rss_bytes,
                }
                for w in self.workers.values()
            ],
        }

    # -- sync ------------------------------------------------------------
    def _runtime_metric_deltas(self):
        """Per-component runtime metrics (stats/metric_defs.h:46-61 analog:
        task/worker/store counters), reported as deltas so the GCS
        aggregate matches its Counter semantics."""
        stats = self.store.stats()
        node = self.node_id.hex()[:12]
        counters = {
            "rt_raylet_tasks_dispatched_total": self._metric_tasks_dispatched,
            "rt_raylet_tasks_failed_total": self._metric_tasks_failed,
            "rt_raylet_objects_spilled_total": self._metric_objects_spilled,
            "rt_raylet_dispatch_passes_total": self._metric_dispatch_passes,
            "rt_raylet_dispatch_scans_total": self._metric_dispatch_scans,
            "rt_raylet_lease_grants_total": self._metric_lease_grants,
        }
        records = []
        commits = {}
        for name, value in counters.items():
            prev = self._metric_reported.get(name, 0)
            if value != prev:
                records.append(
                    {"name": name, "type": "counter",
                     "description": "raylet runtime counter",
                     "data": [[[["node", node]], value - prev]]}
                )
                commits[name] = value
        for name, value in (
            ("rt_raylet_store_used_bytes", stats.get("used_bytes", 0)),
            ("rt_raylet_store_objects", stats.get("num_objects", 0)),
            ("rt_raylet_workers", len(self.workers)),
            ("rt_raylet_tasks_queued", len(self._queued_specs)),
            ("rt_raylet_dispatch_batch_last", self._last_dispatch_batch),
            ("rt_raylet_dispatch_scan_last", self._last_dispatch_scan),
        ):
            records.append(
                {"name": name, "type": "gauge",
                 "description": "raylet runtime gauge",
                 "data": [[[["node", node]], value]]}
            )
        return records, commits

    async def _sync_resources(self, demand):
        """Versioned delta sync of this node's resource view
        (ray_syncer analog: common/ray_syncer/ray_syncer.h delta-syncs
        per-node views instead of broadcasting full state).

        Only resource entries that changed since the last acknowledged
        sync ride the wire, under a monotonically increasing version; the
        GCS detects gaps (its restart, a missed ack) and replies
        need_full, which resets the baseline so the next beat carries the
        whole view. Demand bundles ship only when they changed.
        """
        self._sync_version += 1
        payload = {
            "node_id": self.node_id.binary(),
            "version": self._sync_version,
            "proc_stats": {
                "workers": sum(
                    1 for w in self.workers.values() if w.conn is not None
                ),
                "rss_bytes": sum(w.rss_bytes for w in self.workers.values()),
                "cpu_percent": round(
                    sum(w.cpu_percent for w in self.workers.values()), 1
                ),
            },
        }
        avail = dict(self.resources_available)
        if self._synced_resources is None:
            payload["available"] = avail
        else:
            delta = {
                k: v for k, v in avail.items()
                if self._synced_resources.get(k) != v
            }
            removed = [k for k in self._synced_resources if k not in avail]
            if delta:
                payload["delta"] = delta
            if removed:
                payload["removed"] = removed
        demand_sig = hash(
            tuple(tuple(sorted(b.items())) for b in demand)
        )
        if demand_sig != self._synced_demand_sig:
            payload["demand_bundles"] = demand
        r = await self.gcs.call("resource_update", payload)
        if r.get("need_full"):
            # Gap on the GCS side (restart / lost state): resend the full
            # view on the next heartbeat.
            self._synced_resources = None
            self._synced_demand_sig = None
        else:
            self._synced_resources = avail
            self._synced_demand_sig = demand_sig
        # Graceful drain (cordon): once the GCS flags this node draining,
        # the hybrid policy stops keeping new work local (see h_submit's
        # draining check) and placement everywhere else skips us.
        was_draining = self._draining
        self._draining = bool(r.get("draining"))
        if self._draining and not was_draining:
            self._revoke_direct_leases()

    async def _heartbeat_loop(self):
        cfg = get_config()
        while True:
            await asyncio.sleep(cfg.health_check_period_s / 2)
            try:
                try:
                    self._sample_proc_stats()
                except Exception:  # noqa: BLE001 — stats are best-effort
                    pass
                try:
                    records, commits = self._runtime_metric_deltas()
                    self._metrics_seq += 1
                    await self.gcs.call(
                        "metrics_report",
                        {"records": records,
                         "reporter": self.node_id.binary(),
                         "seq": self._metrics_seq},
                    )
                    # Commit counter baselines only after a successful
                    # send; the (reporter, seq) pair makes a retried
                    # report idempotent if only the reply was lost.
                    self._metric_reported.update(commits)
                except Exception:  # noqa: BLE001 — observability is best-effort
                    pass
                # Demand bundles of queued-but-undispatched tasks feed the
                # autoscaler's binpacking (LoadMetrics / resource_demand_
                # scheduler in the reference). _queued_specs is stable
                # across a dispatch pass (unlike task_queue, whose items
                # sit in a pass-local requeue list during awaits).
                demand = list(self._queued_specs.values())[:64]
                await self._sync_resources(demand)
                if self._task_events:
                    events, self._task_events = self._task_events, []
                    try:
                        await self.gcs.call("add_task_events", {"events": events})
                    except Exception:
                        # Transient GCS hiccup: keep the batch for retry so
                        # tasks don't stick in stale states in the state API.
                        self._task_events = events + self._task_events
                        raise
            except Exception:
                if self._stopping:
                    return
                if self.gcs is not None and self.gcs._closed:
                    await self._reconnect_gcs()


def main():  # pragma: no cover - run as subprocess
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--gcs-host", default="127.0.0.1")
    p.add_argument("--gcs-port", type=int, required=True)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--resources", default="{}")
    p.add_argument("--object-store-memory", type=int, default=None)
    p.add_argument("--head", action="store_true")
    p.add_argument("--labels", default="{}")
    args = p.parse_args()

    import json

    resources = json.loads(args.resources)
    if args.num_cpus is not None:
        resources["CPU"] = args.num_cpus
    resources.setdefault("CPU", float(os.cpu_count() or 1))

    async def run():
        raylet = Raylet(
            args.gcs_host,
            args.gcs_port,
            resources,
            object_store_memory=args.object_store_memory,
            is_head=args.head,
            labels=json.loads(args.labels),
        )
        port = await raylet.start()
        print(f"RAYLET_PORT={port}", flush=True)
        print(f"RAYLET_NODE_ID={raylet.node_id.hex()}", flush=True)
        print(f"RAYLET_STORE={raylet.store_name}", flush=True)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
