"""Version-tolerant imports for jax APIs that moved between releases.

`shard_map` graduated from `jax.experimental.shard_map` to the top-level
`jax` namespace (jax >= 0.5); importing it from the wrong home raises
ImportError at module import time, which darkened the whole
parallel/collective test tree on the pinned image (ROADMAP item 4).
Import it from here instead:

    from ray_tpu._private.jax_compat import shard_map
"""

from __future__ import annotations

try:  # jax >= 0.5: public top-level API
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # older jax: experimental home
    from jax.experimental.shard_map import shard_map  # noqa: F401

__all__ = ["shard_map"]
