"""Runtime configuration flags.

TPU-native analog of the reference's RayConfig
(src/ray/common/ray_config.h:60; entries defined in
src/ray/common/ray_config_def.h — 220 RAY_CONFIG(type, name, default)
entries, each overridable via a `RAY_<name>` env var). We keep the same
pattern — a flat typed registry, env-overridable with an `RT_` prefix —
but only carry the entries this runtime actually consumes.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, fields
from typing import Any


def _env(name: str, default: Any, typ: type) -> Any:
    # Documented form is upper-case (RT_GCS_WAL_FSYNC, matching the
    # reference's RAY_<NAME> convention); the verbatim field-name form is
    # accepted too so nothing silently ignores an operator's setting.
    raw = os.environ.get(f"RT_{name.upper()}")
    if raw is None:
        raw = os.environ.get(f"RT_{name}")
    if raw is None:
        return default
    if typ is bool:
        return raw.lower() in ("1", "true", "yes")
    return typ(raw)


@dataclass
class Config:
    # -- object store ---------------------------------------------------
    # Default shared-memory store size; reference sizes plasma from system
    # memory in _private/services.py (object_store_memory).
    object_store_memory: int = 256 * 1024 * 1024
    # Objects at or below this size are passed inline in RPC replies instead
    # of the shared-memory store (reference: max_direct_call_object_size,
    # ray_config_def.h — 100KB).
    max_inline_object_size: int = 100 * 1024
    # Chunk size for node-to-node object transfer (reference:
    # object_manager_default_chunk_size, ray_config_def.h:362 — 5 MiB).
    object_transfer_chunk_size: int = 5 * 1024 * 1024
    # Store utilization that triggers spilling of pinned primary copies
    # (reference: object_spilling_threshold, ray_config_def.h).
    object_spilling_threshold: float = 0.8
    # Spill down to this utilization once triggered.
    object_spilling_low_water: float = 0.6
    # Directory for spilled objects (RT_SPILL_DIR env; reference:
    # object_spilling_config).
    spill_dir: str = ""

    # -- scheduling -----------------------------------------------------
    # Prefer the local node until its critical resource utilization crosses
    # this threshold (reference: scheduler_spread_threshold,
    # ray_config_def.h:196).
    scheduler_spread_threshold: float = 0.5
    # Max worker processes per node per job (reference sizes the pool from
    # num_cpus; we keep an explicit cap for tests).
    max_workers_per_node: int = 16
    # Seconds an idle worker lives before the pool reaps it (reference:
    # idle_worker_killing_time_threshold_ms).
    idle_worker_timeout_s: float = 300.0
    # How long a spawned worker may take to register (runtime-env download
    # and extraction happen before registration; reference:
    # worker_register_timeout_seconds).
    worker_register_timeout_s: float = 120.0

    # -- memory monitor / OOM policy -------------------------------------
    # Node memory fraction above which the raylet kills the newest
    # retriable task's worker instead of letting the OS OOM-kill the node
    # (reference: memory_usage_threshold, ray_config_def.h:77 — 0.95).
    memory_usage_threshold: float = 0.95
    # Monitor poll period (reference: memory_monitor_refresh_ms — 250ms).
    memory_monitor_interval_s: float = 0.25
    # 0 disables the monitor (reference disables via refresh_ms=0).
    memory_monitor_enabled: bool = True

    # -- fault tolerance ------------------------------------------------
    # Default task retries (reference: max_retries default 3,
    # python/ray/remote_function.py).
    task_max_retries: int = 3
    # GCS → raylet health check period/timeout (reference:
    # GcsHealthCheckManager, gcs_health_check_manager.h:39).
    health_check_period_s: float = 1.0
    health_check_failure_threshold: int = 5

    # -- rpc ------------------------------------------------------------
    rpc_connect_timeout_s: float = 10.0
    rpc_max_message_size: int = 512 * 1024 * 1024
    # Dial timeout for raylet->raylet peer connections (short: waiters
    # queue behind the per-peer lock, so a blackholed peer must fail fast).
    peer_dial_timeout_s: float = 2.0
    # Dial timeout when reconnecting to a (possibly restarting) GCS.
    gcs_reconnect_dial_timeout_s: float = 2.0
    # Backoff between GCS redial attempts.
    gcs_reconnect_backoff_s: float = 0.5
    # Default timeout for ordinary GCS table/KV operations.
    gcs_op_timeout_s: float = 120.0
    # Dial timeout for raylet->local-worker control connections.
    worker_dial_timeout_s: float = 2.0

    # -- client ----------------------------------------------------------
    # Probe period for blocking gets on remote objects (reference:
    # fetch_warn_timeout_milliseconds family).
    get_probe_interval_s: float = 5.0
    # Cap on one background prefetch() pull: an advisory pull for a
    # never-produced object must not park a loop task forever (blocking
    # semantics belong to get(), which re-issues its own pull).
    prefetch_pull_timeout_s: float = 120.0
    # Timeout resolving a store-argument dependency inside a worker.
    arg_fetch_timeout_s: float = 60.0
    # Timeout for the owner's batched free_objects RPC.
    free_objects_timeout_s: float = 30.0
    # Timeout for spill_objects round trips under store pressure, and the
    # backoff when nothing was spillable.
    spill_rpc_timeout_s: float = 120.0
    spill_retry_backoff_s: float = 0.25
    # Worker-lease RPCs (grant/release; reference lease RPC deadline).
    lease_rpc_timeout_s: float = 10.0
    # Idle-lease reaper tick.
    lease_reap_interval_s: float = 0.5
    # Actor-call retry backoff (per attempt, capped).
    actor_retry_backoff_s: float = 0.2
    actor_retry_backoff_max_s: float = 2.0
    # How long the first call waits for a pipelined (unnamed-actor)
    # registration still in flight before declaring the actor unknown.
    actor_register_wait_s: float = 5.0
    # In-process memory store bound (memory_store.h analog).
    memory_store_max_entries: int = 8192
    # Owner-side lineage table bound (lineage eviction).
    lineage_max_entries: int = 10_000
    # Debounce for batching dropped-ref free RPCs.
    free_flush_debounce_s: float = 0.05

    # -- raylet loops -----------------------------------------------------
    # Dead-worker reap / stale-client-create sweep period.
    reap_interval_s: float = 0.2
    # Workers whose /proc stats are read per heartbeat tick (round-robin
    # window: observability stays O(1)/tick on many-worker nodes).
    proc_stats_sample_max: int = 64
    # Concurrent worker interpreter boots per node (actor-creation burst
    # throttle; an unbounded fork storm starves heartbeats).
    worker_boot_concurrency: int = 16
    # Forced dispatch rescan period while tasks wait on resources.
    dispatch_rescan_interval_s: float = 0.1
    # How long a failed runtime env is remembered before retrying builds.
    bad_runtime_env_ttl_s: float = 60.0
    # Warn when a task has been infeasible this long.
    infeasible_warn_s: float = 30.0
    # Abort an open chunked remote-client put after this long.
    client_create_ttl_s: float = 600.0
    # Per-RPC timeout for remote (rt://) client store operations.
    remote_client_op_timeout_s: float = 120.0

    # -- gcs --------------------------------------------------------------
    # Snapshot debounce for GCS persistence (RT_GCS_PERSIST_PATH).
    gcs_persist_debounce_s: float = 0.05
    # WAL compaction threshold: a snapshot rewrite is scheduled once the
    # write-ahead log passes this size (gcs_table_storage compaction role).
    gcs_wal_compact_bytes: int = 4 * 1024 * 1024
    # fsync each WAL record (strict durability; default flushes only).
    gcs_wal_fsync: bool = False

    # -- direct task transport (worker leases) ---------------------------
    # Max leased workers per scheduling class per owner (the reference
    # bounds leases by cluster capacity; direct_task_transport.cc).
    direct_lease_max_workers: int = 16
    # Outstanding direct tasks on the least-loaded lease that trigger
    # acquiring another worker.
    direct_lease_grow_outstanding: int = 2
    # Idle seconds before an owner returns a leased worker.
    direct_lease_idle_release_s: float = 1.0
    # Max task specs coalesced into one direct-transport batch frame.
    direct_submit_batch_max: int = 32
    # Max pipelined calls to one actor coalesced into one batch frame
    # (also bounds the receiver's per-executor-hop ordered run).
    actor_call_batch_max: int = 64
    # Worker fork server (zygote.py). Off -> every spawn is a fresh
    # interpreter (RT_DISABLE_ZYGOTE also works per-spawn).
    zygote_enabled: bool = True
    # Re-exec the zygote after this many forks. Linux rmap (anon_vma)
    # chains grow with the number of COW-faulted siblings forked from
    # one parent, making every later child's page faults tens of ms
    # slower (measured: ~5ms -> ~27ms sys/boot by ~900 live workers).
    # A fresh zygote resets the chains; the next generation pre-warms in
    # the background so rotation never stalls a spawn.
    zygote_respawn_after: int = 150
    # Registered default-env workers kept warm once the node has seen
    # demand; actor creations and leases adopt them instead of forking
    # on the critical path (worker_pool.h:347 prestart role).
    worker_pool_min_idle: int = 4
    # Recycle a cleanly-killed idle actor's worker back into the pool
    # (workers with running calls still die with the actor).
    actor_worker_recycle: bool = True
    # Delay before the pool replenisher forks, letting recycled workers
    # return first (and keeping forks off creation critical paths).
    worker_pool_replenish_debounce_s: float = 0.25

    # -- object-manager flow control -------------------------------------
    # Concurrent pull transfers per node (PullManager admission).
    pull_max_concurrent: int = 8
    # Fraction of the object store reservable by in-flight pulls.
    pull_budget_fraction: float = 0.25
    # Concurrent outbound chunk reads served (PushManager throttling).
    push_chunk_slots: int = 16
    # Chunk fetches in flight per pull (round trips hide behind each
    # other; reference keeps a per-object chunk pipeline the same way).
    pull_chunk_window: int = 4
    # Same-machine peers move objects by direct store-to-store memcpy
    # through /dev/shm instead of TCP chunks.
    same_host_shm_transfer: bool = True
    # How long a chunk server waits for an in-progress (partial) pull's
    # prefix to advance before failing the chained consumer over to
    # another holder.
    chunk_serve_wait_s: float = 30.0
    # Timeout for the recycle handshake with a killed actor's worker.
    release_actor_timeout_s: float = 2.0
    # Worker-side task-event flush period (batched to the GCS).
    task_event_flush_interval_s: float = 1.0
    # Control-plane profiler head-sampling rate (0 disables, 1 traces
    # every task). Also flippable cluster-wide at runtime via
    # `rt profile --on` (GCS profile_config broadcast).
    task_trace_sample: float = 0.0
    # Bounded delay before buffered trace/profiling spans flush to the
    # GCS (replaces the old one-RPC-per-span eager flush).
    trace_flush_delay_s: float = 0.25

    # -- event journal (cluster black box, util/journal.py) --------------
    # Always-on per-process event journal with HLC stamps. Disabling also
    # drops the HLC field from RPC frames.
    journal_enabled: bool = True
    # Per-process ring capacity (events); oldest overwritten first.
    journal_ring: int = 4096
    # Seconds of ring history a postmortem dump freezes per process.
    journal_window_s: float = 30.0
    # Postmortem bundle root ($TMPDIR/ray_tpu/postmortem when empty).
    journal_dir: str = ""
    # Typed failure observers may publish cluster-wide dump triggers.
    journal_autodump: bool = True
    # Minimum spacing between dump triggers (per process AND GCS-wide):
    # a failure storm becomes one bundle, not a dump storm.
    journal_cooldown_s: float = 30.0

    # -- wire protocol ---------------------------------------------------
    # Frames at/above this size bypass coalescing and await drain.
    rpc_direct_write_threshold: int = 64 * 1024
    # Transport backlog that parks senders in drain() (backpressure).
    rpc_write_buffer_drain: int = 256 * 1024
    # StreamReader buffer limit: must comfortably exceed the transfer
    # chunk size or readexactly() of a bulk chunk thrashes the
    # transport's pause/resume flow control (asyncio default is 64KiB).
    rpc_stream_buffer_limit: int = 32 * 1024 * 1024

    # -- serve ------------------------------------------------------------
    # Controller reconcile tick (replica health, autoscaling, proxies).
    serve_reconcile_interval_s: float = 0.5
    # Consecutive failed health probes before a replica is replaced.
    serve_health_fail_threshold: int = 3
    # Data-plane replica call timeout (handle dispatch, streaming chunk
    # pulls, proxy-side gets).
    serve_rpc_timeout_s: float = 60.0
    # Replica/proxy readiness probes during deploys and reconciles.
    serve_ready_timeout_s: float = 30.0
    # serve.run() end-to-end deploy timeout (controller reports ready).
    serve_deploy_timeout_s: float = 300.0
    # serve.call()/.result() default completion timeout.
    serve_result_timeout_s: float = 120.0
    # Control-plane admin calls (status/delete/shutdown/proxy listing).
    serve_admin_timeout_s: float = 60.0
    # Short liveness/queue-length probes in the reconcile + autoscale loop.
    serve_probe_timeout_s: float = 5.0
    # Upper bound on each app's collective replica health-check wait per
    # reconcile pass (one rt.wait over all replicas' health probes).
    serve_health_wait_s: float = 10.0
    # Base/cap for the jittered backoff between replica re-dispatches on
    # ActorError (a flapping replica must not be hammered in a tight loop).
    serve_redispatch_backoff_s: float = 0.05
    serve_redispatch_backoff_max_s: float = 2.0
    # Request observatory: always-on per-request phase attribution,
    # per-tenant SLO accounting, and the ServeSignals autoscaling plane.
    serve_observatory: bool = True
    # Finished-request phase records retained per replica (ring buffer).
    serve_obs_ring: int = 256
    # Controller cadence for publishing the ServeSignals snapshot to the
    # GCS KV (rt serve / autoscalers read it).
    serve_signals_interval_s: float = 2.0
    # A prefill pass blocking active decode slots longer than this is
    # recorded as a head-of-line event (serve_hol_blocked_seconds_total).
    serve_hol_threshold_s: float = 0.05
    # Fast/slow sliding windows for per-tenant SLO burn-rate accounting
    # (multi-window burn alerting a la SRE workbook).
    serve_slo_fast_window_s: float = 60.0
    serve_slo_slow_window_s: float = 600.0
    # -- serve survival plane (overload/deadline/drain/failover) ----------
    # Bound on requests queued (admitted but unexecuted) per replica, on
    # top of the max_ongoing_requests executing; past it the replica
    # sheds with ServeOverloadedError instead of growing the queue.
    serve_max_queued_per_replica: int = 32
    # Bound on the engine admission queue (waiting for a decode slot);
    # past it submit() sheds instead of queueing unbounded.
    serve_max_queued_per_engine: int = 64
    # Handle-side per-replica circuit breaker: consecutive dispatch
    # failures that open the circuit, and how long it stays open before
    # a half-open trial request is allowed through.
    serve_cb_failure_threshold: int = 3
    serve_cb_reset_s: float = 5.0
    # Graceful drain: how long a drained replica may spend finishing its
    # in-flight requests before the controller hard-kills it.
    serve_drain_timeout_s: float = 10.0
    # Default request deadline when none is set on the handle/header.
    # 0 disables (requests then run under serve_result_timeout_s only).
    serve_default_deadline_s: float = 0.0
    # How many times the streaming generator resumes on a new replica
    # after replica death before giving up (resume-or-restart contract).
    serve_stream_resume_attempts: int = 2
    # Completed-request idempotency cache entries kept per replica (keyed
    # on the handle's idempotency key; redispatch/retry joins or reuses
    # the original execution instead of running it twice).
    serve_idem_cache_size: int = 1024
    # -- serve paged KV (engine memory plane, ray_tpu/serve/paged_kv) -----
    # KV layout: "paged" (page pool + block tables + prefix cache, the
    # default) or "slotted" (the original one-row-per-request cache,
    # kept for bit-exactness baselines). RT_SERVE_KV=slotted flips it.
    serve_kv: str = "paged"
    # Tokens per KV page (clamped to max_len; bit-exactness with the
    # slotted path needs max_len % page_size == 0).
    serve_kv_page_size: int = 16
    # Total pages in the pool, INCLUDING the reserved NULL page. 0 =
    # auto: num_slots * ceil(max_len / page_size) + 1, i.e. the same
    # HBM as the slotted cache it replaces.
    serve_kv_pages: int = 0
    # Prefix cache over full prompt pages (shared prefixes skip their
    # prefill and share pages copy-on-write). Disable to force every
    # request cold.
    serve_prefix_cache: bool = True

    # -- data -------------------------------------------------------------
    # Undelivered blocks buffered per streaming_split consumer before the
    # producer stalls (per-split backpressure).
    data_split_queue_depth: int = 4
    # Streaming-executor concurrency budget = cluster CPUs x this factor.
    data_cpu_budget_factor: float = 2.0
    # Blocks a DataIterator asks its _SplitCoordinator for per round trip
    # (and prefetches ahead of consumption). Override per-trainer through
    # train.DataConfig(prefetch_blocks=...).
    data_iterator_prefetch_blocks: int = 2
    # Default depth of the background device-feed pipeline for
    # Dataset.iter_jax_batches (batches staged ahead of the step loop).
    data_feed_prefetch_batches: int = 2

    # -- collective -----------------------------------------------------
    collective_rendezvous_timeout_s: float = 60.0
    # Deadline on each blocking send/recv inside an eager DCN collective:
    # a dead peer raises CollectiveTimeoutError instead of wedging the
    # surviving ranks (The Big Send-off failure-path-first principle).
    collective_op_timeout_s: float = 60.0

    # -- train fault tolerance -------------------------------------------
    # Bound on one poll() round trip to a training worker (detection
    # latency for a hung rank; replaces the old blanket 600 s get).
    train_poll_timeout_s: float = 60.0
    # Bound on launching the training loop on the gang.
    train_start_timeout_s: float = 600.0
    # Low-cost liveness probe (ping) timeout per worker.
    train_probe_timeout_s: float = 10.0
    # How often the trainer's result loop checks for draining nodes.
    train_drain_poll_interval_s: float = 0.5
    # How long a drain-requested gang gets to checkpoint and exit before
    # the restart proceeds with whatever checkpoint is registered.
    train_drain_grace_s: float = 30.0
    # Bound on one elastic resize: every rank must reach the
    # sync_resize barrier, hand off shards, and apply the new world
    # size within this window or the resize aborts (gang unchanged,
    # caller falls back to checkpoint-and-restart).
    train_resize_timeout_s: float = 60.0
    # Partial reclamation: a claimant needing fewer chips than a whole
    # victim gang drains only the bundles it needs (the victim resizes
    # instead of dying). Off → whole-gang eviction always.
    preempt_partial_enabled: bool = True

    # -- preemption ------------------------------------------------------
    # Master switch for the GCS reclamation pass: infeasible higher-priority
    # demand may evict lower-priority placement groups (RT_PREEMPTION_ENABLED).
    preemption_enabled: bool = True
    # Per-victim graceful-eviction deadline: a preempted gang gets this long
    # to checkpoint/drain and release its placement group before the GCS
    # hard-kills its actors and force-removes the group (RT_PREEMPT_GRACE_S).
    preempt_grace_s: float = 30.0
    # How many completed preemption records the GCS keeps for `rt top` /
    # `get_preemptions` before pruning the oldest.
    preempt_history_limit: int = 256

    # -- core worker ------------------------------------------------------
    # Owner-side object-directory lookups (location gets during restart
    # waits and lineage probes).
    object_directory_rpc_timeout_s: float = 30.0

    def __post_init__(self):
        for f in fields(self):
            cur = getattr(self, f.name)
            setattr(self, f.name, _env(f.name, cur, type(cur)))


def session_log_dir() -> str:
    """The session's per-process log directory — single definition shared
    by `rt start` (writer) and the raylet's log-serving RPCs (reader)."""
    return os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "ray_tpu", "logs"
    )


_config: Config | None = None


def get_config() -> Config:
    global _config
    if _config is None:
        _config = Config()
    return _config
