"""Accelerator manager registry.

Analog of python/ray/_private/accelerators/__init__.py:34 in the reference.
TPU is the first-class citizen here; the registry stays pluggable so other
accelerators can be added.
"""

from ray_tpu._private.accelerators.accelerator import AcceleratorManager
from ray_tpu._private.accelerators.tpu import TPUAcceleratorManager

_MANAGERS = {
    "TPU": TPUAcceleratorManager,
}


def get_all_accelerator_managers():
    return dict(_MANAGERS)


def get_accelerator_manager(resource_name: str):
    return _MANAGERS.get(resource_name)


__all__ = [
    "AcceleratorManager",
    "TPUAcceleratorManager",
    "get_all_accelerator_managers",
    "get_accelerator_manager",
]
