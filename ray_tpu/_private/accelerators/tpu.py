"""TPU accelerator manager: detection, pod topology, gang resources.

Rebuilt from the reference's TPUAcceleratorManager
(python/ray/_private/accelerators/tpu.py:75):

  * chip count via GKE env vars or /dev/vfio* & /dev/accel* globs (tpu.py:101)
  * pod type from GCE metadata (tpu.py:199) / env
  * TPU_VISIBLE_CHIPS isolation (ray_constants.py:414, set at tpu.py:158),
    with the all-chips passthrough: when a task takes every chip on the
    host, the env var is NOT set so libtpu owns the whole host — here that
    is first-class ("whole-host lease") because JAX SPMD wants exactly one
    process per host.
  * pod gang scheduling (tpu.py:335 get_current_node_additional_resources):
    every host in a pod advertises `{pod_name}: 1`; worker 0 additionally
    advertises `TPU-{pod_type}-head: 1`. A job targets the head resource,
    then fans out one whole-host task per worker via the pod-name resource.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional

from ray_tpu._private.accelerators.accelerator import AcceleratorManager

TPU_RESOURCE_NAME = "TPU"
TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
# GKE injects these (reference tpu.py:34-44).
GKE_TPU_ACCELERATOR_ENV = "TPU_ACCELERATOR_TYPE"
GKE_TPU_WORKER_ID_ENV = "TPU_WORKER_ID"
GKE_TPU_NAME_ENV = "TPU_NAME"
GKE_TPU_WORKER_HOSTNAMES_ENV = "TPU_WORKER_HOSTNAMES"
# GCE metadata paths would be queried on real TPU VMs (tpu.py:199); in this
# build metadata access is injected via env for testability.
TPU_CHIPS_PER_HOST_BOUNDS = {"v2": 4, "v3": 4, "v4": 4, "v5p": 4, "v5litepod": 8, "v6e": 8}

_VALID_CHIP_COUNTS = (1, 2, 4, 8)


class TPUAcceleratorManager(AcceleratorManager):
    @staticmethod
    def get_resource_name() -> str:
        return TPU_RESOURCE_NAME

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        return TPU_VISIBLE_CHIPS_ENV

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        """Chip count: explicit env > JAX local devices > device files."""
        explicit = os.environ.get("RT_TPU_CHIPS")
        if explicit:
            return int(explicit)
        try:
            vfio = glob.glob("/dev/vfio/*")
            accel = glob.glob("/dev/accel*")
            n = len([p for p in vfio if os.path.basename(p) != "vfio"]) or len(accel)
            if n:
                return n
        except OSError:
            pass
        # Last resort: a live jax runtime on a TPU VM.
        if os.environ.get("RT_DETECT_TPU_VIA_JAX") == "1":
            try:
                import jax

                return len([d for d in jax.devices() if d.platform == "tpu"])
            except Exception:  # noqa: BLE001
                return 0
        return 0

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        accel_type = os.environ.get(GKE_TPU_ACCELERATOR_ENV) or os.environ.get(
            "RT_TPU_ACCELERATOR_TYPE"
        )
        if accel_type:
            # "v5litepod-16" -> "TPU-V5LITEPOD"
            generation = accel_type.split("-")[0]
            return f"TPU-{generation.upper()}"
        return None

    @staticmethod
    def get_current_node_tpu_pod_type() -> Optional[str]:
        """e.g. "v5litepod-16" (reference tpu.py:199)."""
        return os.environ.get(GKE_TPU_ACCELERATOR_ENV) or os.environ.get(
            "RT_TPU_ACCELERATOR_TYPE"
        )

    @staticmethod
    def get_current_node_tpu_name() -> Optional[str]:
        """Unique pod/slice name (reference tpu.py:232)."""
        return os.environ.get(GKE_TPU_NAME_ENV) or os.environ.get("RT_TPU_NAME")

    @staticmethod
    def get_current_node_tpu_worker_id() -> Optional[int]:
        """This host's index within the pod slice (reference tpu.py:258)."""
        wid = os.environ.get(GKE_TPU_WORKER_ID_ENV) or os.environ.get(
            "RT_TPU_WORKER_ID"
        )
        return int(wid) if wid is not None else None

    @staticmethod
    def get_num_workers_in_current_tpu_pod() -> Optional[int]:
        """Hosts in this pod slice (reference tpu.py:275)."""
        hostnames = os.environ.get(GKE_TPU_WORKER_HOSTNAMES_ENV) or os.environ.get(
            "RT_TPU_WORKER_HOSTNAMES"
        )
        if hostnames:
            return len(hostnames.split(","))
        explicit = os.environ.get("RT_TPU_POD_WORKER_COUNT")
        if explicit:
            return int(explicit)
        return None

    @staticmethod
    def get_current_node_additional_resources() -> Dict[str, float]:
        """Pod gang resources (reference tpu.py:335).

        Every pod host advertises `{tpu_name}: 1`; worker 0 additionally
        advertises `TPU-{pod_type}-head: 1`.
        """
        out: Dict[str, float] = {}
        name = TPUAcceleratorManager.get_current_node_tpu_name()
        pod_type = TPUAcceleratorManager.get_current_node_tpu_pod_type()
        worker_id = TPUAcceleratorManager.get_current_node_tpu_worker_id()
        if name:
            out[name] = 1.0
        if pod_type is not None and worker_id == 0:
            out[f"TPU-{pod_type}-head"] = 1.0
        return out

    @staticmethod
    def validate_resource_request_quantity(quantity: float):
        if quantity not in _VALID_CHIP_COUNTS:
            return (
                False,
                f"TPU request must be one of {_VALID_CHIP_COUNTS} chips "
                f"(got {quantity}); multi-host slices use pod gang resources",
            )
        return True, None

    @staticmethod
    def get_current_process_visible_accelerator_ids() -> Optional[List[str]]:
        raw = os.environ.get(TPU_VISIBLE_CHIPS_ENV)
        if raw is None:
            return None
        if raw == "":
            return []
        return raw.split(",")

    @staticmethod
    def set_current_process_visible_accelerator_ids(ids: List[str]) -> None:
        """Confine this process to specific chips.

        The all-chips passthrough (reference tpu.py:158): when the process
        takes every chip on the host we *unset* the variable so libtpu owns
        the full host — the whole-host lease JAX SPMD needs.
        """
        total = TPUAcceleratorManager.get_current_node_num_accelerators()
        if total and len(ids) >= total:
            os.environ.pop(TPU_VISIBLE_CHIPS_ENV, None)
            return
        os.environ[TPU_VISIBLE_CHIPS_ENV] = ",".join(str(i) for i in ids)


def get_current_pod_name() -> Optional[str]:
    """Public helper (reference python/ray/util/accelerators/tpu.py:7)."""
    return TPUAcceleratorManager.get_current_node_tpu_name()


def get_current_pod_worker_count() -> Optional[int]:
    """Public helper (reference python/ray/util/accelerators/tpu.py:19)."""
    return TPUAcceleratorManager.get_num_workers_in_current_tpu_pod()
