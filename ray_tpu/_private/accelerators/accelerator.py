"""Accelerator manager interface.

Analog of the reference ABC (python/ray/_private/accelerators/accelerator.py:5)
— detection, type labeling, extra gang resources, and per-task visible-device
isolation.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, List, Optional


class AcceleratorManager(ABC):
    @staticmethod
    @abstractmethod
    def get_resource_name() -> str:
        """Scheduler resource name, e.g. "TPU"."""

    @staticmethod
    @abstractmethod
    def get_visible_accelerator_ids_env_var() -> str:
        """Env var that confines a process to specific accelerator ids."""

    @staticmethod
    @abstractmethod
    def get_current_node_num_accelerators() -> int:
        """How many accelerators this node has."""

    @staticmethod
    @abstractmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        """Label like "TPU-V5LITEPOD"."""

    @staticmethod
    def get_current_node_additional_resources() -> Dict[str, float]:
        """Extra custom resources this node should advertise."""
        return {}

    @staticmethod
    def validate_resource_request_quantity(quantity: float) -> tuple[bool, Optional[str]]:
        return True, None

    @staticmethod
    @abstractmethod
    def get_current_process_visible_accelerator_ids() -> Optional[List[str]]:
        ...

    @staticmethod
    @abstractmethod
    def set_current_process_visible_accelerator_ids(ids: List[str]) -> None:
        ...
