"""Worker fork server (zygote).

The raylet spawns ONE zygote interpreter that pays the worker's
interpreter-start + import cost once, then `os.fork()`s per worker
request — worker spawn drops from ~300ms to single-digit ms. This plays
the role the reference's worker pool prestart plays
(src/ray/raylet/worker_pool.h:347) but makes every spawn cheap instead
of hiding latency for the first N workers.

Protocol — line-delimited JSON over the zygote's stdin/stdout:

    -> {"op": "spawn", "env": {...}}     # complete desired child environ
    <- {"op": "spawned", "pid": N}       # replies in request order
    <- {"op": "dead", "pid": N, "rc": N} # interleaved as children reap

Fork-safety rules: the zygote is strictly single-threaded, runs no event
loop, and never imports jax (workers attach the TPU backend lazily — see
worker_main.ensure_tpu_backend). stdin is consumed with raw os.read into
an explicit line buffer — buffered TextIO.readline over a selector
silently strands any second line that arrived in the same pipe read.
Children are reaped with waitpid(WNOHANG) between protocol reads (<=1s
select timeout) and death notices stream to the raylet, which owns
worker-failure handling.
"""

from __future__ import annotations

import json
import os
import selectors
import sys


def _emit(obj) -> None:
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def _reap() -> None:
    while True:
        try:
            pid, status = os.waitpid(-1, os.WNOHANG)
        except ChildProcessError:
            return
        if pid == 0:
            return
        _emit({"op": "dead", "pid": pid, "rc": os.waitstatus_to_exitcode(status)})


def _become_worker(env: dict) -> None:
    """Runs in the forked child; never returns."""
    rc = 1
    try:
        os.setsid()
        # fd 1 is the zygote protocol pipe — worker prints must not
        # corrupt it. Route child stdout to the inherited stderr (the
        # raylet's), and detach stdin.
        os.dup2(2, 1)
        devnull = os.open(os.devnull, os.O_RDONLY)
        os.dup2(devnull, 0)
        os.close(devnull)
        os.environ.clear()
        os.environ.update(env)
        # The interpreter read PYTHONPATH at zygote start; changes in the
        # per-worker env must land on sys.path by hand or by-reference
        # cloudpickle functions from driver-side modules won't resolve.
        for entry in reversed(
            [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
        ):
            if entry not in sys.path:
                sys.path.insert(0, entry)
        # Drop config cached under the zygote's environment.
        from ray_tpu._private import config as _config

        _config._config = None
        from ray_tpu._private import worker_main

        worker_main.main()
        rc = 0
    except BaseException:  # noqa: BLE001 — child must never unwind into the zygote loop
        import traceback

        traceback.print_exc()
    finally:
        os._exit(rc)


def _handle(line: bytes) -> None:
    try:
        req = json.loads(line)
    except json.JSONDecodeError:
        return
    if req.get("op") == "spawn":
        pid = os.fork()
        if pid == 0:
            _become_worker(req.get("env") or {})
        _emit({"op": "spawned", "pid": pid})


def _prewarm() -> None:
    """Exercise first-use-lazy machinery pre-fork so every child inherits
    warm module state via COW instead of paying it on the boot path
    (measured: a cold ThreadPoolExecutor ctor alone costs ~8ms in a fresh
    fork; warm it's ~0.2ms)."""
    import asyncio
    import concurrent.futures

    ex = concurrent.futures.ThreadPoolExecutor(max_workers=1)
    ex.submit(lambda: None).result()
    ex.shutdown(wait=True)
    # Event-loop machinery (selector, policy) and the serializer's
    # first-use tables.
    asyncio.run(asyncio.sleep(0))
    from ray_tpu._private import serialization as ser

    ser.deserialize_from_bytes(ser.serialize_to_bytes(([], {})))
    from ray_tpu._private.protocol import pack_frame

    pack_frame({"k": "req", "i": 0, "m": "ping", "d": None})


def main() -> None:
    # Pay the import cost once, pre-fork.
    from ray_tpu._private import worker_main  # noqa: F401

    _prewarm()
    # Freeze the warm heap into the GC's permanent generation: a child's
    # first collection otherwise WRITES the gc header of every inherited
    # object, COW-copying nearly the whole heap (the Instagram prefork
    # lesson). With the freeze, children dirty only what they actually
    # mutate — measured ~5.1MB -> ~2MB private-dirty per idle worker,
    # which is what bounds actor density per host (thinly-backed VMs
    # penalize every fresh page touched).
    import gc

    gc.collect()
    gc.freeze()
    _emit({"op": "ready", "pid": os.getpid()})
    fd = sys.stdin.fileno()
    sel = selectors.DefaultSelector()
    sel.register(fd, selectors.EVENT_READ)
    buf = b""
    while True:
        events = sel.select(timeout=1.0)
        _reap()
        if not events:
            continue
        try:
            chunk = os.read(fd, 1 << 16)
        except OSError:
            return
        if not chunk:
            return  # raylet closed our stdin: shut down
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            _handle(line)


if __name__ == "__main__":
    main()
