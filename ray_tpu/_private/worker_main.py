"""Worker process entry point.

Analog of the reference's default_worker.py + the task-execution callback in
the Cython layer (_raylet.pyx:2177 task_execution_handler,
execute_task_with_cancellation_handler :2009): registers with the raylet,
receives task pushes, executes user code on executor threads, and serves
direct actor calls from other processes
(CoreWorkerDirectTaskReceiver::HandleTask,
transport/direct_actor_transport.cc:37).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import heapq
import inspect
import os
import threading
import time
import traceback
from typing import Any, Dict, Optional

from ray_tpu._private import serialization as ser
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.config import get_config
from ray_tpu._private.ids import JobID, ObjectID, TaskID, object_id_for_task
from ray_tpu._private.protocol import RpcServer, connect, spawn
from ray_tpu._private.worker import CoreClient, make_task_error
from ray_tpu.exceptions import ActorDiedError
from ray_tpu.util import lifecycle

_TPU_ATTACHED = False
_TPU_ATTACH_LOCK = threading.Lock()


def _wants_tpu(resources) -> bool:
    return any(
        k == "TPU" or k.startswith("TPU-") for k in (resources or {})
    )


def ensure_tpu_backend():
    """Attach the deferred remote-TPU jax backend, once.

    The raylet strips PALLAS_AXON_POOL_IPS from worker environments (so
    sitecustomize skips its eager ~2s jax import at interpreter start) and
    stashes it in RT_DEFERRED_TPU_TUNNEL. The first task/actor that
    requests TPU resources restores the env and re-runs sitecustomize,
    which performs the exact registration the interpreter would have done
    at startup. CPU-only workers never pay for the tunnel."""
    global _TPU_ATTACHED
    # Serialized + flag-set-last: a concurrent TPU task must block until
    # registration completes, not race past a pre-set flag into jax with
    # no backend.
    with _TPU_ATTACH_LOCK:
        if _TPU_ATTACHED:
            return
        ips = os.environ.get("RT_DEFERRED_TPU_TUNNEL", "")
        if not ips:
            return
        os.environ["PALLAS_AXON_POOL_IPS"] = ips
        jp = os.environ.get("RT_DEFERRED_JAX_PLATFORMS")
        if jp:
            os.environ["JAX_PLATFORMS"] = jp
        import sys as _sys

        _sys.modules.pop("sitecustomize", None)
        try:
            import sitecustomize  # noqa: F401 — re-runs TPU registration
        except Exception as e:  # noqa: BLE001
            # Leave the flag unset so the NEXT TPU task retries a
            # transient tunnel failure — and say something (rate-limited:
            # every TPU task retries, and a dead tunnel would spam one
            # line per task), or this worker silently computes on CPU
            # forever.
            from ray_tpu.util.debug import log_every_n_seconds

            if log_every_n_seconds("tpu-attach-failed", 30.0):
                print(
                    f"[worker] TPU backend attach failed "
                    f"({type(e).__name__}: {e}); will retry on next TPU task",
                    file=_sys.stderr, flush=True,
                )
            return
        _TPU_ATTACHED = True


def _retired_result() -> dict:
    return {"status": "worker_crashed", "not_executed": True,
            "error": "worker retired (max_calls)"}


class _RawObject:
    """Pre-framed bytes (RTX1 cross-language objects) presented with the
    SerializedObject store interface (total_size / write_into / to_bytes)."""

    def __init__(self, raw: bytes):
        self.raw = raw

    @property
    def total_size(self) -> int:
        return len(self.raw)

    def write_into(self, dest) -> int:
        dest[: len(self.raw)] = self.raw
        return len(self.raw)

    def to_bytes(self) -> bytes:
        return self.raw


class _CallerQueue:
    """Ordered execution state for one caller (SequentialActorSubmitQueue
    receiver side, transport/sequential_actor_submit_queue.cc)."""

    def __init__(self):
        self.next_seq = 0
        self.pending: list = []  # heap of (seq, tiebreak, request, future)
        self.draining = False


class ActorState:
    def __init__(self, actor_id: bytes, instance: Any, max_concurrency: int):
        self.actor_id = actor_id
        self.instance = instance
        self.max_concurrency = max_concurrency
        self.lock = threading.Lock()
        self.queues: Dict[bytes, _CallerQueue] = {}
        self.sema = asyncio.Semaphore(max(1, max_concurrency))


class WorkerProcess:
    def __init__(self):
        self._boot_stamp("init0")
        self.worker_id = bytes.fromhex(os.environ["RT_WORKER_ID"])
        self.node_id = bytes.fromhex(os.environ["RT_NODE_ID"])
        gcs_host, gcs_port = os.environ["RT_GCS_ADDR"].rsplit(":", 1)
        self.gcs_addr = (gcs_host, int(gcs_port))
        self.raylet_port = int(os.environ["RT_RAYLET_PORT"])
        self.store_name = os.environ["RT_STORE_NAME"]
        self._boot_stamp("init_env")
        self.rpc = RpcServer("127.0.0.1", 0)
        self.rpc.register("actor_call", self.h_actor_call)
        self.rpc.register("actor_call_batch", self.h_actor_call_batch)
        self.rpc.register("release_actor", self.h_release_actor)
        self.rpc.register("run_task_direct", self.h_run_task_direct)
        self.rpc.register("run_tasks_batch", self.h_run_tasks_batch)
        self.rpc.register("dag_start", self.h_dag_start)
        self.rpc.register("dag_stop", self.h_dag_stop)
        self.rpc.register("ping", self.h_ping)
        self.rpc.register("dump_stacks", self.h_dump_stacks)
        self._dag_loops: list = []  # (thread, stop_event)
        self.client: Optional[CoreClient] = None
        self.raylet_conn = None
        self.actor: Optional[ActorState] = None
        self._boot_stamp("init_rpc")
        _n_exec = max(4, get_config().max_workers_per_node)
        self._boot_stamp("init_config")
        self.executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=_n_exec
        )
        self._boot_stamp("init_executor")
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._direct_lock = asyncio.Lock()  # one leased task runs at a time
        # Actor-call state events (normal-task events are recorded by the
        # raylet; actor calls bypass it, so the receiving worker reports).
        self._task_events: list = []
        # In-flight actor calls (running or queued): a kill can only
        # recycle this worker back into the pool when zero — a thread
        # mid-call cannot be stopped, only the process can.
        self._active_actor_calls = 0
        # max_calls (reference: @ray.remote(max_calls=N), the leak
        # mitigation for tasks wrapping leaky native code): per-function
        # execution counts; crossing a task's threshold retires this
        # worker — later pushes are refused (the owner retries on a
        # fresh worker) and the process exits once replies flush.
        self._fn_calls: Dict[bytes, int] = {}
        self._retiring = False

    async def h_dump_stacks(self, d, conn):
        """Live thread stacks of this worker (the on-demand profiling
        role of the reference's dashboard py-spy integration,
        dashboard/modules/reporter/profile_manager.py — in-process
        cooperative sampling instead of an external native profiler)."""
        import sys
        import traceback

        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        threads = []
        for ident, frame in frames.items():
            threads.append({
                "thread": names.get(ident, str(ident)),
                "stack": "".join(traceback.format_stack(frame)),
            })
        return {
            "pid": os.getpid(),
            "worker_id": self.worker_id,
            "actor": bool(self.actor),
            "threads": threads,
        }

    def _boot_stamp(self, stage: str):
        log_path = os.environ.get("RT_WORKER_BOOT_LOG")
        if log_path:
            import time

            with open(log_path, "a") as f:
                f.write(f"{os.getpid()} {stage} {time.time()}\n")

    async def run(self):
        self.loop = asyncio.get_event_loop()
        port = await self.rpc.start()
        self._boot_stamp("rpc_up")
        self.raylet_conn = await connect(
            "127.0.0.1", self.raylet_port, push_handler=self._on_raylet_push
        )
        self._boot_stamp("raylet_conn")
        self.client = CoreClient(
            self.loop,
            self.gcs_addr,
            ("127.0.0.1", self.raylet_port),
            self.store_name,
            self.node_id,
            JobID.nil(),
            mode="worker",
        )
        self._boot_stamp("client_ctor")
        await self.client._connect(raylet_conn=self.raylet_conn)
        self.client._connected = True
        self._boot_stamp("client_up")
        worker_mod.set_client(self.client, "worker")
        # Materialize the runtime env (working_dir/py_modules download from
        # the GCS KV) before any task runs. Blocking KV reads must not run
        # on the event loop.
        renv_json = os.environ.get("RT_RUNTIME_ENV")
        if renv_json:
            import json

            from ray_tpu.runtime_env import apply_runtime_env

            try:
                await self.loop.run_in_executor(
                    self.executor, apply_runtime_env, json.loads(renv_json),
                    self.client,
                )
            except Exception as e:  # noqa: BLE001
                # Report so the raylet fails queued tasks for this env
                # instead of respawning us in a crash loop.
                try:
                    await self.raylet_conn.call(
                        "worker_env_failed",
                        {
                            "worker_id": self.worker_id,
                            "runtime_env_hash": json.loads(renv_json).get("hash"),
                            "error": f"{type(e).__name__}: {e}",
                        },
                    )
                finally:
                    raise SystemExit(1)
        resp = await self.raylet_conn.call(
            "register_worker", {"worker_id": self.worker_id, "port": port}
        )
        assert resp["node_id"] == self.node_id
        self._boot_stamp("registered")
        spawn(self._flush_events_loop())
        await asyncio.Event().wait()

    def _record_task_event(self, task_id: bytes, name: str, state: str):
        import time

        self._task_events.append(
            {
                "task_id": task_id,
                "name": name,
                "job_id": b"",
                "node_id": self.node_id,
                "worker_id": self.worker_id,
                "type": "ACTOR_TASK",
                "state": state,
                "ts": time.time(),
            }
        )

    def _lc_emit(self, task_id: bytes, name: str, phases: Dict[str, list],
                 job_id: bytes = b""):
        """Queue a worker-hop lifecycle span for a sampled task; rides the
        existing task-event flush loop (no extra RPC)."""
        self._task_events.append(lifecycle.event(
            task_id, name, job_id, self.node_id, "worker", phases,
            worker_id=self.worker_id,
        ))

    async def _flush_events_loop(self):
        while True:
            await asyncio.sleep(get_config().task_event_flush_interval_s)
            if self._task_events:
                events, self._task_events = self._task_events, []
                try:
                    await self.client._gcs_call("add_task_events", {"events": events})
                except Exception:
                    pass

    # -- raylet pushes ----------------------------------------------------
    def _on_raylet_push(self, channel: str, payload):
        if channel == "run_task":
            spawn(self._run_task(payload))
        elif channel == "create_actor":
            spawn(self._create_actor(payload))
        elif channel == "lease_revoked" and self.client is not None:
            # Workers own leases too (nested tasks): forward drain-time
            # revocations to the embedded client.
            self.client._on_raylet_push(channel, payload)

    async def _run_task(self, spec):
        if self._retiring:
            await self.raylet_conn.call(
                "task_done",
                {"task_id": spec["task_id"], "result": _retired_result()},
            )
            return
        result = await self.loop.run_in_executor(
            self.executor, self._execute_accounted, spec
        )
        await self.raylet_conn.call(
            "task_done", {"task_id": spec["task_id"], "result": result}
        )

    async def h_run_task_direct(self, d, conn):
        """Leased-worker fast path: the owner pushes the task spec straight
        to this worker and the result rides the RPC response — the raylet
        is not on the per-task path (direct_task_transport.cc PushTask).

        Execution is serialized: the lease holds resources for ONE task
        shape, so pipelined pushes queue here rather than running
        concurrently in the executor (which would oversubscribe the
        node's accounting)."""
        if self._retiring:
            return _retired_result()
        t0 = time.monotonic() if d.get("sampled") else None
        async with self._direct_lock:
            # Sampled: the wait for earlier pipelined pushes on this
            # lease IS the task's queue time (the raylet never sees
            # direct tasks, so the worker owns the queue_wait phase).
            if t0 is not None:
                d["_lc_queue_wait"] = time.monotonic() - t0
            # _execute_accounted re-checks _retiring inside (a push may
            # have queued on the lock behind the call that crossed the
            # threshold — it must refuse, not run-and-be-killed).
            return await self.loop.run_in_executor(
                self.executor, self._execute_accounted, d
            )

    async def h_run_tasks_batch(self, d, conn):
        """Batched direct transport: a burst of leased tasks executes in
        ONE executor hop, serially (the lease holds resources for one task
        shape — same contract as run_task_direct)."""
        if self._retiring:
            return {"results": [_retired_result() for _ in d["specs"]]}
        specs = d["specs"]
        t_recv = time.monotonic()

        def run_all():
            # Per-spec accounting: once the threshold is crossed the
            # REST of the batch is refused (not_executed -> the owner
            # resubmits it on a fresh worker), so the worker never
            # exceeds max_calls by the batch size.
            out = []
            for s in specs:
                # Sampled: batch-arrival -> this spec's turn is its
                # queue time (predecessors in the run + lock wait).
                if s.get("sampled"):
                    s["_lc_queue_wait"] = time.monotonic() - t_recv
                out.append(self._execute_accounted(s))
            return out

        async with self._direct_lock:
            results = await self.loop.run_in_executor(self.executor, run_all)
        return {"results": results}


    def _execute_accounted(self, spec) -> dict:
        """Execute a task with max_calls bookkeeping. Runs on an
        executor thread; the GIL covers the counter dict, and the retire
        coroutine is handed to the event loop thread-safely."""
        if self._retiring:
            return _retired_result()
        result = self._execute_task(spec)
        limit = spec.get("max_calls") or 0
        key = spec.get("fn_key")
        if limit and key is not None:
            n = self._fn_calls.get(key, 0) + 1
            self._fn_calls[key] = n
            if n >= limit and not self._retiring:
                self._retiring = True
                self.loop.call_soon_threadsafe(
                    lambda: asyncio.ensure_future(self._retire())
                )
        return result

    async def _retire(self):
        # Tell the raylet first so it stops dispatching here; it only
        # terminate()s as a late fallback — this worker owns its exit
        # once every in-flight reply is on the wire.
        try:
            await self.raylet_conn.call(
                "retire_worker", {"worker_id": self.worker_id}, timeout=5
            )
        except Exception:  # noqa: BLE001
            pass
        # The threshold-crossing task's reply travels on a direct
        # worker->owner connection; exiting before it flushes would
        # surface as worker_crashed on an already-executed task. Wait
        # out any running batch, give its respond() coroutine a tick to
        # write, then drain every server connection.
        try:
            async with self._direct_lock:
                pass
            await asyncio.sleep(0.05)
            for conn in list(self.rpc.connections):
                try:
                    conn._sender.flush()
                    await conn.writer.drain()
                except Exception:  # noqa: BLE001
                    pass
        except Exception:  # noqa: BLE001
            pass
        os._exit(0)

    def _execute_task(self, spec) -> dict:
        from ray_tpu.util import tracing

        with tracing.activate(
            spec.get("trace_ctx"), spec.get("name") or "task"
        ):
            return self._execute_task_body(spec)

    def _execute_task_body(self, spec) -> dict:
        # Control-plane profiler (worker hop): sampled specs carry
        # "sampled"; stamp fn_fetch / arg_fetch / deserialize / exec /
        # result_store from monotonic deltas. Unsampled tasks pay one
        # dict miss.
        lc: Optional[Dict[str, list]] = {} if spec.get("sampled") else None
        if lc is not None:
            qw = spec.get("_lc_queue_wait")
            if qw:
                # Direct-transport queue time stamped by the push handler
                # (the raylet is off the per-task path for leased tasks).
                lc["queue_wait"] = [time.time() - qw, qw]  # rtlint: disable=RT011 — deliberate wall anchor: [start_wall, dur] lets the client stitch queue-wait onto its timeline
        try:
            if _wants_tpu(spec.get("resources")):
                ensure_tpu_backend()
            if spec.get("fn_name"):
                # Cross-language task (reference: cross_language.py /
                # function-descriptor calls from java/cpp frontends): the
                # function is named "module:attr", args are plain msgpack
                # values, and the result serializes as RTX1 so the foreign
                # caller can decode it.
                import importlib

                mod_name, _, attr = spec["fn_name"].partition(":")
                fn = getattr(importlib.import_module(mod_name), attr)
                value = fn(*(spec.get("plain_args") or []))
                return self._package_returns(spec, value, xlang=True)
            if lc is not None:
                t0, w0 = time.monotonic(), time.time()
            fn = self.client.fn_manager.fetch(spec["fn_key"])
            if lc is not None:
                now = time.monotonic()
                lc["fn_fetch"] = [w0, max(0.0, now - t0)]
                t0, w0 = now, time.time()
                lifecycle.begin_arg_capture()
            args, kwargs = self.client.deserialize_args(spec["args"])
            if lc is not None:
                total = max(0.0, time.monotonic() - t0)
                arg_s = min(lifecycle.end_arg_capture(), total)
                lc["arg_fetch"] = [w0, arg_s]
                lc["deserialize"] = [w0, max(0.0, total - arg_s)]
                t0, w0 = time.monotonic(), time.time()
            value = fn(*args, **kwargs)
            if lc is not None:
                lc["exec"] = [w0, max(0.0, time.monotonic() - t0)]
                t0, w0 = time.monotonic(), time.time()
            out = self._package_returns(spec, value)
            if lc is not None:
                lc["result_store"] = [w0, max(0.0, time.monotonic() - t0)]
                self._lc_emit(spec["task_id"], spec.get("name") or "", lc,
                              spec.get("job_id", b""))
            return out
        except BaseException as e:  # noqa: BLE001 — shipped to the caller
            return make_task_error(e)

    def _package_returns(self, spec, value, xlang: bool = False) -> dict:
        cfg = get_config()
        num_returns = spec.get("num_returns", 1)
        if num_returns == "dynamic":
            # Streaming generator task (reference: streaming_generator /
            # num_returns="dynamic"): each yielded item is serialized and
            # stored under (task_id, i) AS PRODUCED, so consumers holding
            # the ObjectRefGenerator read item i while the generator is
            # still running. The final count rides the task result.
            items = value if inspect.isgenerator(value) else iter([value])
            task_id = TaskID(spec["task_id"])
            n = 0
            for i, v in enumerate(items):
                so = ser.serialize(v)
                self.client.put_serialized_with_spill(
                    object_id_for_task(task_id, i), so
                )
                n += 1
            return {"status": "ok", "generator": True, "num_items": n}
        if num_returns == 1:
            values = [value]
        else:
            values = list(value)
            if len(values) != num_returns:
                raise ValueError(
                    f"task declared num_returns={num_returns} but returned "
                    f"{len(values)} values"
                )
        returns = []
        task_id = TaskID(spec["task_id"])
        for i, v in enumerate(values):
            so = (_RawObject(ser.serialize_xlang(v)) if xlang
                  else self.client.serialize_result(v))
            if so.total_size <= cfg.max_inline_object_size:
                returns.append({"kind": "inline", "data": so.to_bytes()})
            else:
                oid = object_id_for_task(task_id, i)
                self.client.put_serialized_with_spill(oid, so)
                returns.append({
                    "kind": "store", "size": so.total_size,
                    "object_id": oid.binary(),
                })
        return {"status": "ok", "returns": returns}

    # -- actor lifecycle --------------------------------------------------
    async def _create_actor(self, payload):
        def do_create():
            if _wants_tpu(payload.get("resources")):
                ensure_tpu_backend()
            cls = self.client.fn_manager.fetch(payload["cls_key"])
            args, kwargs = self.client.deserialize_args(payload["args"])
            return cls(*args, **kwargs)

        try:
            self._boot_stamp("create_recv")
            instance = await self.loop.run_in_executor(self.executor, do_create)
            self._boot_stamp("instantiated")
            self.actor = ActorState(
                payload["actor_id"], instance, payload.get("max_concurrency", 1)
            )
            methods = [
                m
                for m in dir(instance)
                if callable(getattr(instance, m, None)) and not m.startswith("__")
            ]
            # Method names ride the actor_ready report and live in the GCS
            # actor record (one RPC, not a separate per-actor KV write) —
            # get_actor() callers read them from the actor view.
            await self.client._gcs_call(
                "actor_ready",
                {
                    "actor_id": payload["actor_id"],
                    "address": "127.0.0.1",
                    "port": self.rpc.port,
                    "worker_id": self.worker_id,
                    "methods": methods,
                },
            )
        except BaseException as e:  # noqa: BLE001
            await self.client._gcs_call(
                "actor_ready",
                {
                    "actor_id": payload["actor_id"],
                    "error": f"{type(e).__name__}: {e}\n{traceback.format_exc()}",
                },
            )

    # -- actor calls -------------------------------------------------------
    async def h_actor_call(self, d, conn):
        actor = self.actor
        if actor is None or actor.actor_id != d["actor_id"]:
            return make_task_error(
                ActorDiedError("actor not hosted by this worker")
            )
        self._active_actor_calls += 1
        try:
            if d.get("xlang"):
                # Cross-language caller (C++ client): plain msgpack args,
                # RTX1 result, no per-caller sequence protocol — foreign
                # clients are synchronous request/response. The concurrency
                # bound still applies (the semaphore is sized
                # max(1, max_concurrency), so serial actors stay serial for
                # foreign callers too).
                async with actor.sema:
                    return await self._invoke_actor_method(actor, d)
            if actor.max_concurrency > 1:
                async with actor.sema:
                    return await self._invoke_actor_method(actor, d)
            # Ordered path: execute strictly by per-caller sequence number.
            fut = self._enqueue_ordered(actor, d)
            await self._drain_ordered(actor, d.get("caller", b""))
            return await fut
        finally:
            self._active_actor_calls -= 1

    async def h_actor_call_batch(self, d, conn):
        """A contiguous run of ordered calls from one caller: enqueue all
        BEFORE draining so the whole run executes in one executor hop."""
        actor = self.actor
        calls = d["calls"]
        if actor is None or any(actor.actor_id != c["actor_id"] for c in calls):
            err = make_task_error(
                ActorDiedError("actor not hosted by this worker")
            )
            return {"results": [err for _ in calls]}
        self._active_actor_calls += len(calls)
        try:
            if actor.max_concurrency > 1:
                async def one(c):
                    async with actor.sema:
                        return await self._invoke_actor_method(actor, c)

                return {"results": await asyncio.gather(*[one(c) for c in calls])}
            futs = [self._enqueue_ordered(actor, c) for c in calls]
            await self._drain_ordered(actor, calls[0].get("caller", b""))
            return {"results": await asyncio.gather(*futs)}
        finally:
            self._active_actor_calls -= len(calls)

    async def h_release_actor(self, d, conn):
        """Tear down the hosted actor so this worker returns to the pool
        (clean rt.kill only). Refuses — forcing a process kill — when any
        call is running or queued: a thread mid-call cannot be stopped."""
        actor = self.actor
        if actor is None or actor.actor_id != d["actor_id"]:
            return {"recycled": True}
        if self._active_actor_calls > 0 or self._dag_loops:
            return {"recycled": False}
        self.actor = None
        instance = actor.instance
        actor.instance = None

        def cleanup():
            nonlocal instance
            try:
                del instance
            finally:
                import gc

                gc.collect()

        await self.loop.run_in_executor(self.executor, cleanup)
        return {"recycled": True}

    def _enqueue_ordered(self, actor: ActorState, d):
        q = actor.queues.setdefault(d.get("caller", b""), _CallerQueue())
        fut = self.loop.create_future()
        heapq.heappush(q.pending, (d["seq"], id(d), d, fut))
        return fut

    async def _drain_ordered(self, actor: ActorState, caller: bytes):
        q = actor.queues.setdefault(caller, _CallerQueue())
        if q.draining:
            return
        q.draining = True
        try:
            while q.pending and q.pending[0][0] == q.next_seq:
                # Pop the whole contiguous seq run and execute it in ONE
                # executor hop — the thread handoff is the dominant cost
                # of a small actor call on a busy host.
                run = []
                limit = get_config().actor_call_batch_max
                while (q.pending and q.pending[0][0] == q.next_seq
                       and len(run) < limit):
                    _, _, req, rfut = heapq.heappop(q.pending)
                    q.next_seq += 1
                    run.append((req, rfut))
                if len(run) == 1:
                    result = await self._invoke_actor_method(actor, run[0][0])
                    if not run[0][1].done():
                        run[0][1].set_result(result)
                else:
                    results = await self._invoke_actor_run(
                        actor, [r for r, _ in run]
                    )
                    for (_, rfut), res in zip(run, results):
                        if not rfut.done():
                            rfut.set_result(res)
        finally:
            q.draining = False

    async def _invoke_actor_run(self, actor: ActorState, reqs) -> list:
        """Execute an ordered run of calls in a single executor hop."""
        from ray_tpu.util import tracing

        def do_run():
            results = []
            for d in reqs:
                self._record_task_event(d["task_id"], d["method"], "RUNNING")
                lc = {} if d.get("sampled") else None
                try:
                    method = getattr(actor.instance, d["method"])
                    if d.get("xlang"):
                        args, kwargs = tuple(d.get("plain_args") or ()), {}
                    else:
                        if lc is not None:
                            t0, w0 = time.monotonic(), time.time()
                            lifecycle.begin_arg_capture()
                        args, kwargs = self.client.deserialize_args(d["args"])
                        if lc is not None:
                            total = max(0.0, time.monotonic() - t0)
                            arg_s = min(lifecycle.end_arg_capture(), total)
                            lc["arg_fetch"] = [w0, arg_s]
                            lc["deserialize"] = [w0, max(0.0, total - arg_s)]
                    if lc is not None:
                        t0, w0 = time.monotonic(), time.time()
                    with tracing.activate(d.get("trace_ctx"), d["method"]):
                        with actor.lock:
                            if inspect.iscoroutinefunction(method):
                                value = asyncio.run(method(*args, **kwargs))
                            else:
                                value = method(*args, **kwargs)
                    if lc is not None:
                        lc["exec"] = [w0, max(0.0, time.monotonic() - t0)]
                        t0, w0 = time.monotonic(), time.time()
                    spec = {"task_id": d["task_id"],
                            "num_returns": d.get("num_returns", 1)}
                    results.append(
                        self._package_returns(spec, value,
                                              bool(d.get("xlang")))
                    )
                    if lc is not None:
                        lc["result_store"] = [
                            w0, max(0.0, time.monotonic() - t0)
                        ]
                        self._lc_emit(d["task_id"], f"{d['method']}()", lc)
                    self._record_task_event(
                        d["task_id"], d["method"], "FINISHED")
                except BaseException as e:  # noqa: BLE001 — to the caller
                    self._record_task_event(d["task_id"], d["method"], "FAILED")
                    results.append(make_task_error(e))
            return results

        return await self.loop.run_in_executor(self.executor, do_run)

    async def _invoke_actor_method(self, actor: ActorState, d) -> dict:
        self._record_task_event(d["task_id"], d["method"], "RUNNING")
        lc: Optional[Dict[str, list]] = {} if d.get("sampled") else None

        def do_call():
            from ray_tpu.util import tracing

            method = getattr(actor.instance, d["method"])
            if d.get("xlang"):
                args, kwargs = tuple(d.get("plain_args") or ()), {}
            else:
                if lc is not None:
                    t0, w0 = time.monotonic(), time.time()
                    lifecycle.begin_arg_capture()
                args, kwargs = self.client.deserialize_args(d["args"])
                if lc is not None:
                    total = max(0.0, time.monotonic() - t0)
                    arg_s = min(lifecycle.end_arg_capture(), total)
                    lc["arg_fetch"] = [w0, arg_s]
                    lc["deserialize"] = [w0, max(0.0, total - arg_s)]

            def invoke():
                t0, w0 = (time.monotonic(), time.time()) if lc is not None \
                    else (0.0, 0.0)
                try:
                    with tracing.activate(d.get("trace_ctx"), d["method"]):
                        if inspect.iscoroutinefunction(method):
                            return asyncio.run(method(*args, **kwargs))
                        return method(*args, **kwargs)
                finally:
                    if lc is not None:
                        lc["exec"] = [w0, max(0.0, time.monotonic() - t0)]

            if actor.max_concurrency == 1:
                # Shares the state lock with compiled-DAG loops so stages
                # and regular calls never mutate actor state concurrently.
                with actor.lock:
                    return invoke()
            return invoke()

        def call_and_package():
            # One executor hop covers both the user call and result
            # packaging (_package_returns may block on the raylet during
            # spill, so neither half may run on the event loop).
            value = do_call()
            spec = {"task_id": d["task_id"], "num_returns": d.get("num_returns", 1)}
            if lc is None:
                return self._package_returns(spec, value, bool(d.get("xlang")))
            t0, w0 = time.monotonic(), time.time()
            out = self._package_returns(spec, value, bool(d.get("xlang")))
            lc["result_store"] = [w0, max(0.0, time.monotonic() - t0)]
            self._lc_emit(d["task_id"], f"{d['method']}()", lc)
            return out

        try:
            result = await self.loop.run_in_executor(
                self.executor, call_and_package
            )
            self._record_task_event(d["task_id"], d["method"], "FINISHED")
            return result
        except BaseException as e:  # noqa: BLE001
            self._record_task_event(d["task_id"], d["method"], "FAILED")
            return make_task_error(e)

    # -- compiled DAG resident loop (do_exec_compiled_task analog,
    # dag/compiled_dag_node.py:34) ---------------------------------------
    async def h_dag_start(self, d, conn):
        actor = self.actor
        if actor is None or actor.actor_id != d["actor_id"]:
            return {"ok": False, "error": "actor not hosted by this worker"}
        try:
            stages = self._bind_dag_stages(d["stages"], actor.instance)
        except Exception as e:  # noqa: BLE001
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        stop = threading.Event()
        loop_id = os.urandom(8).hex()
        # Serialize stages with regular actor calls on single-threaded
        # actors: both paths take the actor's state lock.
        lock = actor.lock if actor.max_concurrency == 1 else None
        t = threading.Thread(
            target=self._dag_loop, args=(stages, stop, lock), daemon=True,
            name="rt-dag-loop",
        )
        t.start()
        self._dag_loops.append((loop_id, t, stop))
        return {"ok": True, "loop_id": loop_id}

    async def h_dag_stop(self, d, conn):
        target = d.get("loop_id")
        for loop_id, _, stop in self._dag_loops:
            if target is None or loop_id == target:
                stop.set()
        self._dag_loops = [
            (lid, t, s) for lid, t, s in self._dag_loops if t.is_alive()
        ]
        return {"ok": True}

    @staticmethod
    def _bind_dag_stages(stage_specs, instance):
        import pickle

        from ray_tpu.experimental.channel import Channel

        stages = []
        for spec in stage_specs:
            args = []
            for a in spec["args"]:
                if a["kind"] == "chan":
                    args.append(Channel(name=a["name"]))
                else:
                    args.append(("const", pickle.loads(a["value"])))
            kwargs = {}
            for k, v in spec["kwargs"].items():
                if v["kind"] == "chan":
                    kwargs[k] = Channel(name=v["name"])
                else:
                    kwargs[k] = ("const", pickle.loads(v["value"]))
            stages.append(
                {
                    "method": getattr(instance, spec["method"]),
                    "args": args,
                    "kwargs": kwargs,
                    "outs": [Channel(name=n) for n in spec["out_channels"]],
                }
            )
        return stages

    @staticmethod
    def _dag_loop(stages, stop: threading.Event, state_lock=None):
        from ray_tpu.dag.compiled_dag import _StageError
        from ray_tpu.experimental.channel import Channel, ChannelClosed

        def read_arg(a):
            if isinstance(a, Channel):
                while True:
                    try:
                        return a.read(timeout=0.5)
                    except TimeoutError:
                        if stop.is_set():
                            raise ChannelClosed(a.name) from None
            return a[1]  # ("const", value)

        try:
            while not stop.is_set():
                for stage in stages:
                    args = [read_arg(a) for a in stage["args"]]
                    kwargs = {k: read_arg(v) for k, v in stage["kwargs"].items()}
                    err = next(
                        (x for x in [*args, *kwargs.values()]
                         if isinstance(x, _StageError)),
                        None,
                    )
                    if err is not None:
                        value = err  # propagate without executing
                    else:
                        try:
                            if state_lock is not None:
                                with state_lock:
                                    value = stage["method"](*args, **kwargs)
                            else:
                                value = stage["method"](*args, **kwargs)
                        except BaseException as e:  # noqa: BLE001
                            value = _StageError(e)
                    for out in stage["outs"]:
                        while True:
                            try:
                                out.write(value, timeout=0.5)
                                break
                            except TimeoutError:
                                if stop.is_set():
                                    raise ChannelClosed(out.name) from None
        except ChannelClosed:
            pass
        finally:
            for stage in stages:
                for a in [*stage["args"], *stage["kwargs"].values()]:
                    if isinstance(a, Channel):
                        a.detach()
                for out in stage["outs"]:
                    out.detach()

    async def h_ping(self, d, conn):
        return {"pong": True, "actor": self.actor is not None}


def main():
    log_path = os.environ.get("RT_WORKER_BOOT_LOG")
    if log_path:
        import time

        with open(log_path, "a") as f:
            f.write(f"{os.getpid()} start {time.time()}\n")
    wp = WorkerProcess()
    if log_path:
        import time

        with open(log_path, "a") as f:
            f.write(f"{os.getpid()} constructed {time.time()}\n")
    try:
        asyncio.run(wp.run())
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass


if __name__ == "__main__":
    main()
