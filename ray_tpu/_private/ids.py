"""Unique identifiers for cluster entities.

TPU-native rework of the reference ID scheme (src/ray/common/id.h and the
Cython wrappers in python/ray/includes/unique_ids.pxi). We keep the same
taxonomy — Job, Task, Object, Actor, Node, PlacementGroup, Worker — but use a
flat 16-byte random payload for every kind instead of the reference's
embedded-field encodings; lineage metadata lives in the GCS tables rather
than in the ID bits.
"""

from __future__ import annotations

import hashlib
import os
import threading

_ID_SIZE = 16

# os.urandom costs ~25µs a call on this class of host — material on the
# per-task submit path. Each thread slices IDs from a private pre-filled
# entropy pool instead (one urandom syscall per 256 IDs).
_POOL_IDS = 256
_entropy = threading.local()

# A forked child would inherit the parent's partially-consumed pool and
# mint byte-identical IDs; drop it so the child refills from the kernel.
os.register_at_fork(after_in_child=lambda: setattr(_entropy, "buf", None))


def _random_id_bytes() -> bytes:
    buf = getattr(_entropy, "buf", None)
    off = getattr(_entropy, "off", 0)
    if buf is None or off >= len(buf):
        buf = _entropy.buf = os.urandom(_ID_SIZE * _POOL_IDS)
        off = 0
    _entropy.off = off + _ID_SIZE
    return buf[off:off + _ID_SIZE]


class BaseID:
    """A 16-byte identifier, hashable and cheaply comparable."""

    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        if not isinstance(id_bytes, bytes) or len(id_bytes) != _ID_SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {_ID_SIZE} bytes, got {id_bytes!r}"
            )
        self._bytes = id_bytes
        self._hash = None

    @classmethod
    def from_random(cls):
        return cls(_random_id_bytes())

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * _ID_SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * _ID_SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        if self._hash is None:
            self._hash = hash((type(self).__name__, self._bytes))
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ObjectID(BaseID):
    pass


class ActorID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class _Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value


_object_seq = _Counter()


def object_id_for_task(task_id: TaskID, return_index: int) -> ObjectID:
    """Deterministically derive a return-object ID from its creating task.

    Mirrors the reference's ObjectID::FromIndex (src/ray/common/id.h) so that
    lineage-based reconstruction can recompute the same IDs.
    """
    h = hashlib.blake2b(
        task_id.binary() + return_index.to_bytes(4, "little"), digest_size=_ID_SIZE
    )
    return ObjectID(h.digest())
