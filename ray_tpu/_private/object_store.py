"""Python client for the native shared-memory object store.

Binds ray_tpu/native/object_store.cc via ctypes (the reference binds plasma
through Cython: python/ray/_raylet.pyx + object_manager/plasma/client.cc).
Data access is zero-copy: `get()` returns a memoryview directly over the
shared mapping; `put_serialized()` writes pickle5 out-of-band buffers
straight into the allocation.
"""

from __future__ import annotations

import atexit
import ctypes
import os
import subprocess
import threading
from typing import Optional

from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import ObjectStoreFullError

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
# RT_STORE_LIB overrides the library (e.g. the ASAN build from
# `make -C ray_tpu/native asan` for sanitizer stress runs).
_LIB_PATH = os.environ.get("RT_STORE_LIB") or os.path.join(
    _NATIVE_DIR, "libray_tpu_store.so"
)

_lib = None
_lib_lock = threading.Lock()


def _load_lib() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        default_lib = _LIB_PATH == os.path.join(_NATIVE_DIR, "libray_tpu_store.so")
        if default_lib and (
            not os.path.exists(_LIB_PATH)
            or os.path.getmtime(_LIB_PATH)
            < os.path.getmtime(os.path.join(_NATIVE_DIR, "object_store.cc"))
        ):
            subprocess.run(
                ["make", "-s", "-C", _NATIVE_DIR],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.rt_store_open.restype = ctypes.c_void_p
        lib.rt_store_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int]
        lib.rt_store_close.argtypes = [ctypes.c_void_p]
        lib.rt_store_unlink.argtypes = [ctypes.c_char_p]
        lib.rt_store_base.restype = ctypes.c_void_p
        lib.rt_store_base.argtypes = [ctypes.c_void_p]
        lib.rt_store_create_object.restype = ctypes.c_int64
        lib.rt_store_create_object.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
        ]
        lib.rt_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_store_get.restype = ctypes.c_int64
        lib.rt_store_get.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rt_store_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_store_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_store_evict.restype = ctypes.c_uint64
        lib.rt_store_evict.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rt_store_stats.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64 * 4),
        ]
        _lib = lib
        return lib


RT_OK = 0
RT_ERR_EXISTS = -1
RT_ERR_FULL = -2
RT_ERR_NOT_FOUND = -3
RT_ERR_NOT_SEALED = -4
RT_ERR_IN_USE = -5
RT_ERR_STATE = -6


class ObjectStore:
    """Handle to a shared-memory object store segment."""

    def __init__(self, name: str, size: int = 0, create: bool = False):
        self._lib = _load_lib()
        self.name = name
        self._owner = create
        self._handle = self._lib.rt_store_open(
            name.encode(), ctypes.c_uint64(size), 1 if create else 0
        )
        if not self._handle:
            raise OSError(f"failed to open object store segment {name!r}")
        self._base = self._lib.rt_store_base(self._handle)
        self._closed = False
        self._unmapped = False
        self._lock = threading.Lock()
        if create:
            atexit.register(self.destroy)

    # -- lifecycle ------------------------------------------------------

    def close(self, unmap: bool = True):
        """Close the handle. With unmap=False the shared mapping (and the
        handle) stay valid for the process lifetime — required when
        zero-copy views (numpy arrays over store memory) may still be
        alive; munmap under them is a segfault. Late release() calls are
        still honored in that mode so shared refcounts don't leak."""
        with self._lock:
            if not self._closed:
                if unmap:
                    self._lib.rt_store_close(self._handle)
                    self._unmapped = True
                self._closed = True

    def destroy(self):
        self.close()
        if self._owner:
            self._lib.rt_store_unlink(self.name.encode())
            self._owner = False

    # -- object ops -----------------------------------------------------

    def _view(self, offset: int, size: int) -> memoryview:
        return memoryview(
            (ctypes.c_char * size).from_address(self._base + offset)
        ).cast("B")

    def create(self, object_id: ObjectID, size: int) -> memoryview:
        """Allocate a writable buffer; caller must seal() when done."""
        off = self._lib.rt_store_create_object(
            self._handle, object_id.binary(), ctypes.c_uint64(size)
        )
        if off == RT_ERR_EXISTS:
            raise ValueError(f"object {object_id} already exists")
        if off == RT_ERR_FULL:
            raise ObjectStoreFullError(
                f"cannot allocate {size} bytes in store {self.name}"
            )
        return self._view(off, size)

    def seal(self, object_id: ObjectID):
        rc = self._lib.rt_store_seal(self._handle, object_id.binary())
        if rc != RT_OK:
            raise ValueError(f"seal({object_id}) failed: {rc}")

    def get(self, object_id: ObjectID) -> Optional[memoryview]:
        """Pin and return a read view, or None if absent. Pair with release()."""
        size = ctypes.c_uint64()
        off = self._lib.rt_store_get(
            self._handle, object_id.binary(), ctypes.byref(size)
        )
        if off in (RT_ERR_NOT_FOUND, RT_ERR_NOT_SEALED):
            return None
        return self._view(off, size.value)

    def release(self, object_id: ObjectID):
        self._lib.rt_store_release(self._handle, object_id.binary())

    def contains(self, object_id: ObjectID) -> bool:
        return bool(self._lib.rt_store_contains(self._handle, object_id.binary()))

    def contains_raw(self, id_bytes: bytes) -> bool:
        return bool(self._lib.rt_store_contains(self._handle, id_bytes))

    def delete(self, object_id: ObjectID) -> bool:
        return self._lib.rt_store_delete(self._handle, object_id.binary()) == RT_OK

    def abort(self, object_id: ObjectID):
        self._lib.rt_store_abort(self._handle, object_id.binary())

    def evict(self, nbytes: int) -> int:
        return self._lib.rt_store_evict(self._handle, ctypes.c_uint64(nbytes))

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 4)()
        self._lib.rt_store_stats(self._handle, ctypes.byref(out))
        return {
            "used_bytes": out[0],
            "num_objects": out[1],
            "num_evictions": out[2],
            "heap_size": out[3],
        }

    # -- high-level helpers ---------------------------------------------

    def put_serialized(self, object_id: ObjectID, serialized) -> bool:
        """Write a SerializedObject directly into shared memory.

        Returns False if the object already exists (put is idempotent,
        matching plasma's ObjectExists handling).
        """
        try:
            buf = self.create(object_id, serialized.total_size)
        except ValueError:
            return False
        serialized.write_into(buf)
        del buf
        self.seal(object_id)
        self.release(object_id)
        return True

    def put_bytes(self, object_id: ObjectID, data: bytes) -> bool:
        try:
            buf = self.create(object_id, len(data))
        except ValueError:
            return False
        buf[: len(data)] = data
        del buf
        self.seal(object_id)
        self.release(object_id)
        return True
