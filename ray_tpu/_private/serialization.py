"""Object serialization with zero-copy buffer support.

TPU-native analog of python/ray/_private/serialization.py in the reference:
cloudpickle for arbitrary Python objects plus pickle protocol 5 out-of-band
buffers so large numpy / jax host arrays are written into (and read from)
the shared-memory object store without copies.

Wire layout of a serialized object:

    u32 magic | u32 pickle_len | u32 nbuffers |
    nbuffers * u64 buffer_len |
    pickle bytes | pad to 64 | buffer0 | pad to 64 | buffer1 | ...

Buffers are 64-byte aligned so numpy views over shared memory are
vector-load friendly on the host side before `jax.device_put`.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Tuple

import cloudpickle

_MAGIC = 0x52545031  # "RTP1" — pickle + out-of-band buffers
_MAGIC_XLANG = 0x52545831  # "RTX1" — msgpack payload (cross-language)
_ALIGN = 64


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


class SerializedObject:
    """A serialized value: metadata pickle plus out-of-band buffers."""

    __slots__ = ("pickled", "buffers")

    def __init__(self, pickled: bytes, buffers: List[pickle.PickleBuffer]):
        self.pickled = pickled
        self.buffers = buffers

    @property
    def total_size(self) -> int:
        size = 12 + 8 * len(self.buffers)
        size = _pad(size + len(self.pickled))
        for b in self.buffers:
            size = _pad(size + len(b.raw()))
        return size

    def write_into(self, dest: memoryview) -> int:
        """Write the framed object into `dest`; returns bytes written."""
        raws = [b.raw() for b in self.buffers]
        header = struct.pack(
            f"<III{len(raws)}Q",
            _MAGIC,
            len(self.pickled),
            len(raws),
            *[len(r) for r in raws],
        )
        off = len(header)
        dest[:off] = header
        dest[off : off + len(self.pickled)] = self.pickled
        off = _pad(off + len(self.pickled))
        for r in raws:
            dest[off : off + len(r)] = r
            off = _pad(off + len(r))
        return off

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_size)
        n = self.write_into(memoryview(out))
        return bytes(out[:n])


def serialize(value: Any) -> SerializedObject:
    buffers: List[pickle.PickleBuffer] = []

    def buffer_callback(buf: pickle.PickleBuffer):
        # Only take the out-of-band path for buffers big enough to matter;
        # small ones are cheaper inline in the pickle stream.
        if buf.raw().nbytes >= 1024:
            buffers.append(buf)
            return False  # out-of-band
        return True  # in-band

    pickled = cloudpickle.dumps(
        value, protocol=pickle.HIGHEST_PROTOCOL, buffer_callback=buffer_callback
    )
    return SerializedObject(pickled, buffers)


def deserialize(data: memoryview) -> Any:
    """Deserialize from a framed buffer.

    Out-of-band buffers are reconstructed as memoryviews into `data` —
    zero-copy when `data` maps shared memory. The caller is responsible for
    keeping the backing store pinned while the value is alive (the object
    store client pins via refcount, releasing on a weakref callback).
    """
    if len(data) < 4:
        raise ValueError("corrupt serialized object (too short)")
    (magic,) = struct.unpack_from("<I", data, 0)
    if magic == _MAGIC_XLANG:
        # Cross-language (RTX1) payloads can be tiny (5 bytes for None) —
        # decode before touching the larger RTP1 header. The memoryview
        # slice feeds msgpack without copying the payload.
        import msgpack

        return msgpack.unpackb(data[4:], raw=False, strict_map_key=False)
    if magic != _MAGIC:
        raise ValueError("corrupt serialized object (bad magic)")
    _, pickle_len, nbuf = struct.unpack_from("<III", data, 0)
    lens = struct.unpack_from(f"<{nbuf}Q", data, 12)
    off = 12 + 8 * nbuf
    pickled = bytes(data[off : off + pickle_len])
    off = _pad(off + pickle_len)
    bufs = []
    for ln in lens:
        bufs.append(data[off : off + ln])
        off = _pad(off + ln)
    return pickle.loads(pickled, buffers=bufs)


def serialize_xlang(value: Any) -> bytes:
    """Serialize as a cross-language (RTX1/msgpack) object.

    Any client that speaks msgpack — the C++ API in cpp/, or a remote
    driver in another language — can read these; `deserialize` handles
    them transparently on the Python side (the reference's cross-language
    serialization role, java/C++ <-> python object passing)."""
    import msgpack

    return struct.pack("<I", _MAGIC_XLANG) + msgpack.packb(
        value, use_bin_type=True
    )


def serialize_to_bytes(value: Any) -> bytes:
    return serialize(value).to_bytes()


def deserialize_from_bytes(data: bytes) -> Any:
    return deserialize(memoryview(data))
