"""Deterministic fault injection for chaos testing.

Reference analogs: ResourceKillerActor / WorkerKillerActor / RayletKiller
(python/ray/_private/test_utils.py:1396,1446,1527) — reshaped as a small
library the train/serve/data layers and their chaos suites share instead
of each test hand-rolling kill threads.

Everything here is gated on RT_CHAOS=1 (set by `enable()`), so a stray
import in production code can never inject a fault. Injection is
deterministic by construction: faults fire at a caller-chosen point
(`once()` markers on shared storage make "exactly once across restarts"
trivial), never on a timer.

Driver-side injections (drain, poll delay) live in process-local state;
worker-side helpers (`die`, `sever_dcn_peer`) execute inside the worker
that calls them — ship them there with `worker_group.execute*` or call
them from the training loop itself.

Injection table (all gated on RT_CHAOS=1):

  hook                      | fires in          | models
  --------------------------|-------------------|------------------------
  die()                     | calling worker    | host preemption
  sever_dcn_peer(rank)      | calling worker    | network partition
  kill_rank(group, rank)    | driver            | train-worker host death
  inject_drain(ranks)       | driver            | preemption notice
  delay_polls(s, n)         | driver            | saturated control plane
  delay_object_pulls(s, n)  | raylet (local)    | slow cross-node fetch
  delay_steps(s, n)         | calling process   | straggling train rank
  delay_prefills(s, n)      | replica process   | huge-prompt HOL blocker
  kill_replica(app, index)  | driver            | serve replica death
  delay_dispatch(s, n)      | handle process    | slow router dispatch
  drop_controller()         | driver            | serve controller crash
  delay_dcn_send(s, n)      | calling process   | DCN per-message latency
  cap_dcn_bandwidth(B/s)    | calling process   | DCN bandwidth ceiling
  preempt_node(node_id)     | driver (GCS RPC)  | node-scope chip reclaim
  reclaim_chips(n)          | driver (GCS RPC)  | partial chip reclaim (elastic shrink)
  lift_fence()              | driver (GCS RPC)  | claimant releases (elastic grow-back)
  kill_victim_mid_drain()   | driver            | victim dies while draining
  flush_prefix_cache()      | replica process   | prefix-cache cold start
  exhaust_kv_pages(frac)    | replica process   | KV page-pool pressure
  kill_replica_at(t, app)   | driver (sched)    | replica death at trace time t
  drop_controller_at(t)     | driver (sched)    | controller crash at trace time t
  anchor_schedule(off)      | driver (sched)    | pins t=0 for the *_at faults
  postmortem(reason)        | driver (GCS RPC)  | manual black-box dump trigger

Every hook journals a ``chaos.injected`` event at fire time (the
cluster black box, util/journal.py), so an assembled postmortem
timeline starts at the injection that provoked it — the causal chain
is reconstructable without cross-referencing the test source.

Schedule-anchored faults (`*_at`) fire at a fixed offset from an anchor
set by `anchor_schedule()` — the same t=0 a recorded loadgen trace
replays against, so a chaos scenario replays deterministically alongside
the traffic that provoked it.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Set

from ray_tpu.util import journal

logger = logging.getLogger("ray_tpu.chaos")

_ENV = "RT_CHAOS"

_lock = threading.Lock()
# rank -> pretend "this rank's node is draining" (consumed once, like a
# real preemption notice).
_injected_drain_ranks: Set[int] = set()
# Deterministic delay applied to the next executor polls (seconds, count).
_poll_delay_s: float = 0.0
_poll_delays_left: int = 0
# Deterministic delay applied to the next raylet object pulls
# (wait_object_local), modelling slow cross-node transfer.
_pull_delay_s: float = 0.0
_pull_delays_left: int = 0
# Deterministic delay applied to the next training steps (consumed by
# the flight recorder's StepProfiler), modelling a straggling rank.
_step_delay_s: float = 0.0
_step_delays_left: int = 0
_prefill_delay_s: float = 0.0
_prefill_delays_left: int = 0
# Deterministic delay applied to the next handle dispatches (consumed by
# DeploymentHandle.remote before the replica call), modelling a slow
# router so deadline-propagation tests can burn budget at a chosen hop.
_dispatch_delay_s: float = 0.0
_dispatch_delays_left: int = 0
# Deterministic per-message latency on the next DCN socket sends plus an
# optional bandwidth ceiling (consumed by dcn_group._Peer.send_bytes) —
# turns the loopback TCP of CPU tests into a modelable slow tier so the
# collective-algorithm benches measure deterministic cost, not scheduler
# noise.
_dcn_send_delay_s: float = 0.0
_dcn_send_delays_left: int = 0
_dcn_bandwidth_cap_bps: float = 0.0
# Paged-KV faults (consumed by ContinuousBatchingEngine's loop): a
# one-shot prefix-cache flush, and a PERSISTENT pool-pressure fraction
# (the engine holds that share of pages until it is set back to 0 —
# a memory squeeze, not an event). -1 = no injection.
_flush_prefix_pending: bool = False
_kv_exhaust_frac: float = -1.0
# Schedule-anchored fault windows: entries fire at anchor + entry["t"]
# on a daemon scheduler thread (started lazily, exits when the schedule
# drains or clear() empties it).
_sched_anchor: Optional[float] = None
_sched_faults: List[Dict] = []
_sched_thread_alive: bool = False


def enabled() -> bool:
    return os.environ.get(_ENV, "").lower() in ("1", "true", "yes")


def enable():
    """Turn fault injection on for this process AND its future children
    (worker processes inherit the environment)."""
    os.environ[_ENV] = "1"


def disable():
    os.environ.pop(_ENV, None)
    clear()


def clear():
    """Drop all pending driver-side injections."""
    global _poll_delay_s, _poll_delays_left
    global _pull_delay_s, _pull_delays_left
    global _step_delay_s, _step_delays_left
    global _prefill_delay_s, _prefill_delays_left
    global _dispatch_delay_s, _dispatch_delays_left
    global _dcn_send_delay_s, _dcn_send_delays_left, _dcn_bandwidth_cap_bps
    global _flush_prefix_pending, _kv_exhaust_frac
    global _sched_anchor
    with _lock:
        _injected_drain_ranks.clear()
        _sched_anchor = None
        # Emptying the list retires the scheduler thread at its next
        # tick (it exits when nothing is pending).
        _sched_faults.clear()
        _flush_prefix_pending = False
        _kv_exhaust_frac = -1.0
        _poll_delay_s = 0.0
        _poll_delays_left = 0
        _pull_delay_s = 0.0
        _pull_delays_left = 0
        _step_delay_s = 0.0
        _step_delays_left = 0
        _prefill_delay_s = 0.0
        _prefill_delays_left = 0
        _dispatch_delay_s = 0.0
        _dispatch_delays_left = 0
        _dcn_send_delay_s = 0.0
        _dcn_send_delays_left = 0
        _dcn_bandwidth_cap_bps = 0.0


def _require_enabled(what: str):
    if not enabled():
        raise RuntimeError(
            f"chaos.{what} called without RT_CHAOS=1 — call chaos.enable() "
            f"first (fault injection is refused in production)"
        )
    # Every armed injection leaves a journal fingerprint at fire time, so
    # a postmortem timeline opens with the fault that provoked it.
    journal.emit("chaos.injected", hook=what)


# -- cross-process / cross-attempt determinism ---------------------------
def once(marker_dir: str, key: str) -> bool:
    """True exactly once per (marker_dir, key), across processes and
    restart attempts — the standard guard so an injected fault fires on
    attempt 1 and never again. Atomic via O_CREAT|O_EXCL."""
    path = os.path.join(marker_dir, f".chaos_once_{key}")
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return False
    os.close(fd)
    return True


# -- worker-side faults --------------------------------------------------
def die(exit_code: int = 1):
    """Kill this process like a preempted host would: immediate, no
    cleanup handlers, no goodbye to the raylet (os._exit ~ SIGKILL)."""
    _require_enabled("die")
    os._exit(exit_code)


def sever_dcn_peer(peer_rank: int, group_name: str = "default"):
    """Cut this process's DCN sockets to/from `peer_rank` — the network
    analog of a host vanishing: the peer's next op on the link raises
    (closed) and ours times out instead of hanging."""
    _require_enabled("sever_dcn_peer")
    from ray_tpu.util.collective.collective import _manager

    group = _manager.get(group_name)
    for table in (group._accepted, group._outgoing):
        peer = table.pop(peer_rank, None)
        if peer is not None:
            try:
                peer.sock.close()
            except OSError:
                pass


# -- driver-side faults --------------------------------------------------
def kill_rank(worker_group, rank: int):
    """Hard-kill one rank's TrainWorker actor (preemption of its host)."""
    _require_enabled("kill_rank")
    import ray_tpu as rt

    rt.kill(worker_group.workers[rank])


def inject_drain(ranks: Iterable[int]):
    """Pretend the nodes hosting `ranks` received a preemption notice.
    Consumed by BackendExecutor.draining_ranks() exactly once (a real
    drain persists in the GCS node table; the injected one must not
    re-trigger after the gang restarts elsewhere)."""
    _require_enabled("inject_drain")
    with _lock:
        _injected_drain_ranks.update(int(r) for r in ranks)


def take_injected_drain_ranks() -> Set[int]:
    """Pop all injected drain ranks (empty when chaos is off)."""
    if not enabled():
        return set()
    with _lock:
        out = set(_injected_drain_ranks)
        _injected_drain_ranks.clear()
    return out


def delay_polls(seconds: float, count: int = 1):
    """Deterministically slow down the next `count` executor polls —
    models a saturated control plane without nondeterministic sleeps
    scattered through tests."""
    _require_enabled("delay_polls")
    global _poll_delay_s, _poll_delays_left
    with _lock:
        _poll_delay_s = float(seconds)
        _poll_delays_left = int(count)


def take_poll_delay() -> Optional[float]:
    """Pop one pending poll delay (None when chaos is off or exhausted)."""
    if not enabled():
        return None
    global _poll_delays_left
    with _lock:
        if _poll_delays_left <= 0:
            return None
        _poll_delays_left -= 1
        return _poll_delay_s


def delay_object_pulls(seconds: float, count: int = 1):
    """Deterministically slow down the next `count` object pulls
    (raylet wait_object_local) — models slow cross-node transfer, so
    feed-pipeline tests and benches see a realistic fetch-latency-bound
    regime without real multi-node network. Driver-process raylets only
    (cluster_utils nodes share this process's state)."""
    _require_enabled("delay_object_pulls")
    global _pull_delay_s, _pull_delays_left
    with _lock:
        _pull_delay_s = float(seconds)
        _pull_delays_left = int(count)


def take_pull_delay() -> Optional[float]:
    """Pop one pending object-pull delay (None when chaos is off or
    exhausted)."""
    if not enabled():
        return None
    global _pull_delays_left
    with _lock:
        if _pull_delays_left <= 0:
            return None
        _pull_delays_left -= 1
        return _pull_delay_s


def delay_steps(seconds: float, count: int = 1):
    """Deterministically slow down this process's next `count` training
    steps (consumed by flight_recorder.StepProfiler at step start) —
    models a straggling rank for skew-attribution tests without
    nondeterministic sleeps in the loop body. Process-local: call it
    from inside the rank you want to slow."""
    _require_enabled("delay_steps")
    global _step_delay_s, _step_delays_left
    with _lock:
        _step_delay_s = float(seconds)
        _step_delays_left = int(count)


def take_step_delay() -> Optional[float]:
    """Pop one pending step delay (None when chaos is off or exhausted).

    Runs once per training step, so the common no-injection case exits
    on a plain global read before touching os.environ or the lock."""
    global _step_delays_left
    if _step_delays_left <= 0 or not enabled():
        return None
    with _lock:
        if _step_delays_left <= 0:
            return None
        _step_delays_left -= 1
        return _step_delay_s


def delay_prefills(seconds: float, count: int = 1):
    """Deterministically stretch this process's next `count` engine
    prefill passes (consumed by ContinuousBatchingEngine at prefill
    start) — models a long-prompt head-of-line blocker for the serve
    observatory's HOL-attribution tests without needing a genuinely
    huge prompt. Process-local: call it inside the replica process."""
    _require_enabled("delay_prefills")
    global _prefill_delay_s, _prefill_delays_left
    with _lock:
        _prefill_delay_s = float(seconds)
        _prefill_delays_left = int(count)


def take_prefill_delay() -> Optional[float]:
    """Pop one pending prefill delay (None when chaos is off/exhausted).

    Runs once per prefill pass — never on the steady-state decode path —
    and the no-injection case exits on a plain global read."""
    global _prefill_delays_left
    if _prefill_delays_left <= 0 or not enabled():
        return None
    with _lock:
        if _prefill_delays_left <= 0:
            return None
        _prefill_delays_left -= 1
        return _prefill_delay_s


# -- serve-side faults ----------------------------------------------------
def kill_replica(app: str, index: int = 0):
    """Hard-kill one replica of a serve app (the serving analog of
    kill_rank): looks the current replica set up from the controller and
    SIGKILLs replica `index`. Deterministic: the caller picks which
    replica dies and when; the controller's health pass + the handles'
    redispatch path then have to recover. Returns the killed replica's
    actor id hex so tests can assert replacement."""
    _require_enabled("kill_replica")
    import ray_tpu as rt
    from ray_tpu._private.config import get_config
    from ray_tpu.serve.controller import CONTROLLER_NAME

    ctrl = rt.get_actor(CONTROLLER_NAME)
    info = rt.get(ctrl.get_replicas.remote(app),
                  timeout=get_config().serve_probe_timeout_s)
    replicas = info["replicas"]
    if not replicas:
        raise RuntimeError(f"chaos.kill_replica: app {app!r} has no replicas")
    victim = replicas[index % len(replicas)]
    journal.emit("chaos.kill_replica", app=app, index=int(index),
                 actor_id=victim._actor_id.hex())
    rt.kill(victim)
    return victim._actor_id.hex()


def delay_dispatch(seconds: float, count: int = 1):
    """Deterministically slow down this process's next `count` handle
    dispatches (consumed by DeploymentHandle.remote just before the
    replica call) — lets deadline-propagation tests burn a request's
    budget at the dispatch hop without nondeterministic sleeps."""
    _require_enabled("delay_dispatch")
    global _dispatch_delay_s, _dispatch_delays_left
    with _lock:
        _dispatch_delay_s = float(seconds)
        _dispatch_delays_left = int(count)


def take_dispatch_delay() -> Optional[float]:
    """Pop one pending dispatch delay (None when chaos is off/exhausted).
    Runs once per handle dispatch; the no-injection case exits on a
    plain global read before touching os.environ or the lock."""
    global _dispatch_delays_left
    if _dispatch_delays_left <= 0 or not enabled():
        return None
    with _lock:
        if _dispatch_delays_left <= 0:
            return None
        _dispatch_delays_left -= 1
        return _dispatch_delay_s


# -- DCN wire faults ------------------------------------------------------
def delay_dcn_send(seconds: float, count: int = 1):
    """Deterministically add `seconds` of latency to this process's next
    `count` DCN socket sends (consumed by the collective transport just
    before sendall) — models per-message DCN latency (the alpha term of
    the cost model) so algorithm-selection benches on loopback TCP
    measure a deterministic latency regime. Process-local: call it
    inside the rank whose sends should stall."""
    _require_enabled("delay_dcn_send")
    global _dcn_send_delay_s, _dcn_send_delays_left
    with _lock:
        _dcn_send_delay_s = float(seconds)
        _dcn_send_delays_left = int(count)


def take_dcn_send_delay() -> Optional[float]:
    """Pop one pending DCN send delay (None when chaos is off or
    exhausted). Runs on every DCN message, so the no-injection case
    exits on a plain global read before touching os.environ or the
    lock."""
    global _dcn_send_delays_left
    if _dcn_send_delays_left <= 0 or not enabled():
        return None
    with _lock:
        if _dcn_send_delays_left <= 0:
            return None
        _dcn_send_delays_left -= 1
        return _dcn_send_delay_s


def cap_dcn_bandwidth(bytes_per_s: float):
    """Impose a bandwidth ceiling on this process's DCN sends until
    cleared: each message sleeps nbytes/bytes_per_s before hitting the
    socket (the beta term of the cost model). Unlike the counted delays
    this persists until clear()/disable() — a slow tier, not an event.
    Pass 0 to lift the cap."""
    _require_enabled("cap_dcn_bandwidth")
    global _dcn_bandwidth_cap_bps
    if bytes_per_s < 0:
        raise ValueError("bandwidth cap must be >= 0")
    with _lock:
        _dcn_bandwidth_cap_bps = float(bytes_per_s)


def dcn_bandwidth_cap() -> Optional[float]:
    """The active DCN bandwidth cap in bytes/s (None when chaos is off
    or no cap is set). Fast path: plain global read first."""
    if not _dcn_bandwidth_cap_bps or not enabled():
        return None
    return _dcn_bandwidth_cap_bps


# -- preemption faults -----------------------------------------------------
def preempt_node(node_id: bytes):
    """Node-scope preemption (a spot/maintenance reclaim of one host):
    asks the GCS to cordon `node_id` and open a grace-then-hard-kill
    eviction record for every CREATED placement group holding a bundle
    there. Deterministic: the caller picks the node and the moment.
    Returns the list of victim placement-group ids (hex)."""
    _require_enabled("preempt_node")
    from ray_tpu._private import worker as worker_mod

    client = worker_mod.get_client()
    resp = client._run(
        client._gcs_call("preempt_node", {"node_id": node_id})
    )
    if not resp.get("ok"):
        raise RuntimeError(
            f"chaos.preempt_node: {resp.get('error', 'preempt_node failed')}"
        )
    return [v.hex() for v in resp.get("victims", [])]


def reclaim_chips(amount: float, resource: str = "TPU",
                  bundle_chips: Optional[float] = None,
                  priority: int = 1_000_000):
    """Partial chip reclamation (a serve spike claiming k < gang_size
    chips): runs the GCS's real reclamation pass under a synthetic
    top-priority claimant that needs `amount` of `resource`, split into
    bundles of `bundle_chips` each (default: one bundle of `amount`).
    The claimed victim bundles drain; an elastic gang sheds exactly
    those ranks and keeps training. The sentinel claimant never places,
    so the chips stay fenced until lift_fence(). Deterministic: fires
    the pass inline, no health-loop timing involved. Returns the victim
    list: [{"victim_pg_id", "partial", "bundle_indices"}, ...]."""
    _require_enabled("reclaim_chips")
    from ray_tpu._private import worker as worker_mod

    client = worker_mod.get_client()
    req = {"amount": float(amount), "resource": resource,
           "priority": int(priority)}
    if bundle_chips is not None:
        req["bundle_chips"] = float(bundle_chips)
    resp = client._run(client._gcs_call("chaos_reclaim_chips", req))
    if not resp.get("ok"):
        raise RuntimeError(
            f"chaos.reclaim_chips: {resp.get('error', 'reclaim failed')}"
        )
    return resp.get("victims", [])


def lift_fence():
    """Release every chaos reclamation claim (the synthetic claimant
    goes away): still-draining chaos evictions are cancelled, armed
    resize obligations flip to lifted — the grow-back signal elastic
    trainers poll — and the fences clear. Returns the number of
    obligations lifted."""
    _require_enabled("lift_fence")
    from ray_tpu._private import worker as worker_mod

    client = worker_mod.get_client()
    resp = client._run(client._gcs_call("chaos_lift_fence", {}))
    if not resp.get("ok"):
        raise RuntimeError("chaos.lift_fence failed")
    return int(resp.get("lifted", 0))


def kill_victim_mid_drain():
    """Kill one actor of a currently-draining preemption victim — the
    worst-case compound fault: the gang dies *while* it is gracefully
    checkpointing out. The hard-kill deadline and the trainer's crash
    path must still converge (no wedged placement groups). Returns the
    killed actor's id hex."""
    _require_enabled("kill_victim_mid_drain")
    from ray_tpu._private import worker as worker_mod

    client = worker_mod.get_client()
    resp = client._run(client._gcs_call("get_preemptions", {}))
    for rec in resp.get("preemptions", []):
        if rec.get("state") != "draining":
            continue
        for aid in rec.get("victim_actors", []):
            client._run(
                client._gcs_call(
                    "kill_actor", {"actor_id": aid, "no_restart": True}
                )
            )
            return aid.hex()
    raise RuntimeError(
        "chaos.kill_victim_mid_drain: no draining victim with live actors"
    )


def flush_prefix_cache():
    """Drop every resident prefix-cache entry in THIS process's serving
    engine(s) at their next loop tick — a deterministic cold-cache
    transition (rolling restart, cache invalidation) without restarting
    the replica. One-shot: consumed once. Process-local: call it inside
    the replica process (serve tests use worker_group.execute or a
    replica method)."""
    _require_enabled("flush_prefix_cache")
    global _flush_prefix_pending
    with _lock:
        _flush_prefix_pending = True


def take_flush_prefix_cache() -> bool:
    """Pop the pending prefix-cache flush (False when chaos is off or
    none pending). Runs every engine loop iteration, so the
    no-injection case exits on a plain global read."""
    global _flush_prefix_pending
    if not _flush_prefix_pending or not enabled():
        return False
    with _lock:
        if not _flush_prefix_pending:
            return False
        _flush_prefix_pending = False
        return True


def exhaust_kv_pages(frac: float):
    """Squeeze the paged-KV pool: the engine holds `frac` of its usable
    pages hostage (admissions then queue on pool pressure) until a
    later call sets the fraction back to 0.0. Unlike the counted delays
    this PERSISTS — it models a memory squeeze (fragmentation, a noisy
    co-tenant), not an event. Process-local, like flush_prefix_cache."""
    _require_enabled("exhaust_kv_pages")
    if not 0.0 <= frac <= 1.0:
        raise ValueError("exhaust_kv_pages frac must be in [0, 1]")
    global _kv_exhaust_frac
    with _lock:
        _kv_exhaust_frac = float(frac)


def kv_exhaust_frac() -> Optional[float]:
    """The active pool-pressure fraction (None when chaos is off or no
    squeeze is set). Runs every engine loop iteration: plain global
    read first."""
    if _kv_exhaust_frac < 0 or not enabled():
        return None
    return _kv_exhaust_frac


def drop_controller(restart: bool = True):
    """Crash the serve controller actor (SIGKILL-style). With
    restart=True (the default) the GCS replays the creation spec —
    max_restarts=-1 — and the restarted controller restores from its
    KV checkpoint; restart=False pins it dead so tests can exercise the
    handles-serve-from-cached-routes window. Returns the old actor's
    id hex."""
    _require_enabled("drop_controller")
    import ray_tpu as rt
    from ray_tpu.serve.controller import CONTROLLER_NAME

    ctrl = rt.get_actor(CONTROLLER_NAME)
    journal.emit("chaos.drop_controller", restart=bool(restart),
                 actor_id=ctrl._actor_id.hex())
    rt.kill(ctrl, no_restart=not restart)
    return ctrl._actor_id.hex()


def postmortem(reason: str = "chaos.postmortem") -> str:
    """Force a cluster-wide black-box dump NOW (bypasses the failure
    cooldown): every connected process freezes its journal ring into a
    bundle directory that `rt postmortem` can assemble. Deterministic
    capture point for chaos suites — inject a fault, let the cluster
    react, then snapshot exactly when the scenario says to. Returns the
    bundle directory path."""
    _require_enabled("postmortem")
    from ray_tpu._private import worker as worker_mod

    client = worker_mod.get_client()
    resp = client._run(
        client._gcs_call(
            "journal_trigger",
            {"reason": reason, "source": "chaos", "force": True},
        )
    )
    if not resp.get("triggered"):
        raise RuntimeError("chaos.postmortem: trigger suppressed")
    return resp["bundle"]


# -- schedule-anchored fault windows ---------------------------------------
def anchor_schedule(offset_s: float = 0.0) -> None:
    """Pin t=0 of the fault schedule to ``now - offset_s`` — the same
    origin a loadgen run (or trace replay) measures its arrival offsets
    from. Registered ``*_at(t)`` faults then fire at schedule-relative
    times, so a recorded chaos scenario replays deterministically
    alongside the recorded traffic. Re-anchoring moves t=0 for every
    not-yet-fired entry."""
    _require_enabled("anchor_schedule")
    global _sched_anchor
    with _lock:
        _sched_anchor = time.monotonic() - float(offset_s)
    _ensure_sched_thread()


def kill_replica_at(t: float, app: str, index: int = 0) -> None:
    """Schedule kill_replica(app, index) at schedule time ``t`` seconds
    (relative to the anchor_schedule origin). Registration is allowed
    before anchoring; the fault arms once the anchor exists."""
    _require_enabled("kill_replica_at")
    _schedule_fault("kill_replica", t, {"app": app, "index": int(index)})


def drop_controller_at(t: float, restart: bool = True) -> None:
    """Schedule drop_controller(restart) at schedule time ``t`` seconds
    (relative to the anchor_schedule origin)."""
    _require_enabled("drop_controller_at")
    _schedule_fault("drop_controller", t, {"restart": bool(restart)})


def scheduled_faults() -> List[Dict]:
    """JSON-safe copy of the fault schedule ({kind, t, kwargs, fired,
    result}) — recorded next to a loadgen trace so replays re-register
    the identical scenario."""
    with _lock:
        return [dict(e, kwargs=dict(e["kwargs"])) for e in _sched_faults]


def _schedule_fault(kind: str, t: float, kwargs: Dict) -> None:
    if t < 0:
        raise ValueError(f"chaos schedule time must be >= 0, got {t}")
    with _lock:
        _sched_faults.append({
            "kind": kind, "t": float(t), "kwargs": kwargs,
            "fired": False, "result": None,
        })
    _ensure_sched_thread()


def _ensure_sched_thread() -> None:
    global _sched_thread_alive
    with _lock:
        if _sched_thread_alive:
            return
        _sched_thread_alive = True
    threading.Thread(
        target=_sched_loop, name="rt-chaos-scheduler", daemon=True,
    ).start()


def _sched_loop() -> None:
    """Fire due faults every 20ms until the schedule drains (or clear()
    empties it). Exit and the alive flag flip happen under the SAME lock
    hold as the emptiness check, so a fault registered concurrently
    either keeps this thread alive or starts a fresh one — never
    stranded. Execution happens on this thread — the driver process,
    where kill_replica/drop_controller expect to run."""
    global _sched_thread_alive
    while True:
        due = []
        with _lock:
            pending = [e for e in _sched_faults if not e["fired"]]
            if not pending or not enabled():
                _sched_thread_alive = False
                return
            anchor = _sched_anchor
            if anchor is not None:
                now = time.monotonic() - anchor
                for e in pending:
                    if e["t"] <= now:
                        e["fired"] = True
                        due.append(e)
        for e in due:
            with _lock:
                if e not in _sched_faults:  # clear() raced the firing
                    continue
            try:
                journal.emit("chaos.scheduled_fire", fault=e["kind"],
                             t=e["t"], kwargs=dict(e["kwargs"]))
                if e["kind"] == "kill_replica":
                    e["result"] = kill_replica(**e["kwargs"])
                elif e["kind"] == "drop_controller":
                    e["result"] = drop_controller(**e["kwargs"])
            except Exception as err:  # noqa: BLE001 — a failed
                # injection (app already gone, controller mid-restart)
                # must not kill the scheduler or the run; the entry
                # records what happened for the trace.
                e["result"] = f"error: {err}"
                logger.warning(
                    "scheduled chaos fault %s(%s) at t=%.3f failed",
                    e["kind"], e["kwargs"], e["t"], exc_info=True,
                )
        time.sleep(0.02)
