"""Node bootstrap: starts the control-plane services for a host.

Analog of python/ray/_private/node.py:37 (Node) + services.py in the
reference (start_gcs_server services.py:1421, start_raylet :1485). Unlike
the reference — which execs separate gcs_server/raylet binaries — the head
services here run on an asyncio loop in a background thread of the driver
process by default (worker processes are always real subprocesses). A
`Cluster` harness can attach extra raylets to the same loop to simulate
multi-node topologies, mirroring python/ray/cluster_utils.py:108.
"""

from __future__ import annotations

import asyncio
import os
import threading
from typing import Dict, Optional

from ray_tpu._private.accelerators import get_all_accelerator_managers
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.ids import JobID
from ray_tpu._private.raylet import Raylet
from ray_tpu._private.worker import CoreClient


def resolve_resources(
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
) -> Dict[str, float]:
    """ResourceSpec.resolve analog (_private/resource_spec.py:169): CPU
    count, accelerator detection, and accelerator-specific extra resources
    (TPU pod gang resources enter here, reference tpu.py:335)."""
    out: Dict[str, float] = dict(resources or {})
    out["CPU"] = float(num_cpus if num_cpus is not None else (os.cpu_count() or 1))
    for name, mgr in get_all_accelerator_managers().items():
        if name in out:
            continue
        count = num_tpus if (name == "TPU" and num_tpus is not None) else None
        if count is None:
            count = mgr.get_current_node_num_accelerators()
        if count:
            out[name] = float(count)
            acc_type = mgr.get_current_node_accelerator_type()
            if acc_type:
                out.setdefault(acc_type, 1.0)
            for k, v in mgr.get_current_node_additional_resources().items():
                out.setdefault(k, v)
    out.setdefault("memory", 0.0)
    return out


class EventLoopThread:
    def __init__(self, name="ray_tpu-io"):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self._run, name=name, daemon=True)
        self.thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout=None):
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(timeout)

    def stop(self):
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(timeout=5)


class Node:
    """A head (or worker) node running in this process."""

    def __init__(
        self,
        head: bool = True,
        gcs_address: Optional[str] = None,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        resources: Optional[Dict[str, float]] = None,
        object_store_memory: Optional[int] = None,
        labels: Optional[Dict[str, str]] = None,
        loop_thread: Optional[EventLoopThread] = None,
    ):
        self.io = loop_thread or EventLoopThread()
        self._owns_loop = loop_thread is None
        self.gcs_server: Optional[GcsServer] = None
        if head:
            import os as _os

            # GCS fault tolerance: set RT_GCS_PERSIST_PATH to snapshot the
            # durable GCS tables (kv/jobs/actors/PGs/object dir) to disk so a
            # restarted GCS rejoins live raylets with its state intact.
            self.gcs_server = GcsServer(
                persist_path=_os.environ.get("RT_GCS_PERSIST_PATH") or None
            )
            self.gcs_port = self.io.run(self.gcs_server.start())
            self.gcs_host = "127.0.0.1"
        else:
            assert gcs_address is not None
            host, port = gcs_address.rsplit(":", 1)
            self.gcs_host, self.gcs_port = host, int(port)

        node_resources = resolve_resources(num_cpus, num_tpus, resources)
        self.raylet = Raylet(
            self.gcs_host,
            self.gcs_port,
            node_resources,
            labels=labels,
            object_store_memory=object_store_memory,
            is_head=head,
        )
        self.raylet_port = self.io.run(self.raylet.start())

    @property
    def gcs_address(self) -> str:
        return f"{self.gcs_host}:{self.gcs_port}"

    def make_client(self, job_id: Optional[JobID] = None, mode="driver") -> CoreClient:
        client = CoreClient(
            self.io.loop,
            (self.gcs_host, self.gcs_port),
            ("127.0.0.1", self.raylet_port),
            self.raylet.store_name,
            self.raylet.node_id.binary(),
            job_id or JobID.from_random(),
            mode=mode,
        )
        client.connect()
        return client

    def stop(self):
        try:
            self.io.run(self.raylet.stop(), timeout=10)
        except Exception:
            pass
        if self.gcs_server is not None:
            try:
                self.io.run(self.gcs_server.stop(), timeout=5)
            except Exception:
                pass
        if self._owns_loop:
            self.io.stop()
