"""Client runtime shared by drivers and workers.

Analog of the reference CoreWorker (src/ray/core_worker/core_worker.h:290)
plus the driver plumbing in python/ray/_private/worker.py: object refs,
task submission (SubmitTask core_worker.cc:1935), actor calls
(SubmitActorTask core_worker.cc:2241), get/put (core_worker.cc:1406/:1168),
and the in-process memory store for small/inline objects
(store_provider/memory_store/memory_store.h:43).

Threading model: all I/O runs on one asyncio loop (a background thread in
drivers, the main loop in workers); the public API is synchronous and posts
coroutines to that loop. User task code executes on executor threads and can
reenter the API (e.g. rt.get inside a task).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import itertools
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu._private import serialization as ser
from ray_tpu._private.config import get_config
from ray_tpu._private.function_manager import FunctionManager
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID, object_id_for_task
from ray_tpu._private.object_store import ObjectStore
from ray_tpu._private.protocol import (
    Connection,
    ConnectionLost,
    RpcError,
    connect,
    spawn,
)
from ray_tpu.exceptions import (
    ActorDiedError,
    ActorUnavailableError,
    GetTimeoutError,
    ObjectLostError,
    TaskError,
    WorkerCrashedError,
)

from ray_tpu.util import journal, lifecycle

# Thread-local flag: serializing task args => promote refs to the shared store.
_ser_ctx = threading.local()
_EMPTY_ARGS_PAYLOAD: Optional[bytes] = None

# Lazily-created client-side GCS RPC metrics (module-level so every
# CoreClient in the process shares one series set).
_gcs_rpc_metric_pair = None


def _gcs_rpc_metrics():
    global _gcs_rpc_metric_pair
    if _gcs_rpc_metric_pair is None:
        from ray_tpu.util import metrics as _metrics

        _gcs_rpc_metric_pair = (
            _metrics.get_or_create(
                _metrics.Counter, "gcs_rpc_client_calls_total",
                "Client-issued GCS RPCs, by method", tag_keys=("method",),
            ),
            _metrics.get_or_create(
                _metrics.Histogram, "gcs_rpc_client_seconds",
                "Client-observed GCS RPC round-trip latency, by method",
                boundaries=_metrics.LATENCY_BOUNDARIES,
                tag_keys=("method",),
            ),
        )
    return _gcs_rpc_metric_pair


class _InStoreSentinel:
    """Marks a completion future whose value lives in the shared store."""

    def __repr__(self):
        return "<in-store>"


_IN_STORE = _InStoreSentinel()


class ObjectRef:
    """A reference to a (possibly pending) remote object.

    Reference analog: ObjectRef in python/ray/includes/object_ref.pxi; the
    completion future mirrors the owner's TaskManager bookkeeping.
    """

    __slots__ = ("id", "_future", "__weakref__")

    def __init__(self, id: ObjectID, future: Optional[concurrent.futures.Future] = None):
        self.id = id
        self._future = future

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def future(self) -> concurrent.futures.Future:
        """A concurrent future resolving to the object's value."""
        fut = concurrent.futures.Future()

        def fill():
            try:
                fut.set_result(get_client().get([self], timeout=None)[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=fill, daemon=True).start()
        return fut

    def __reduce__(self):
        if getattr(_ser_ctx, "promote", False):
            client = _global_client
            if client is not None:
                client.promote_ref(self)
                promoted = getattr(_ser_ctx, "promoted", None)
                if promoted is not None:
                    promoted.append(self.id.binary())
        return (_ref_from_binary, (self.id.binary(),))

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"


class ObjectRefGenerator:
    """Iterator over a dynamic-generator task's item refs
    (num_returns="dynamic"; reference: ObjectRefGenerator /
    streaming_generator). Items STREAM: ref i becomes available as soon
    as the running task yields item i and stores it — iteration does not
    wait for task completion. Item oids derive deterministically from
    (task_id, index), so retries regenerate the same refs."""

    def __init__(self, task_id: "TaskID", future, client):
        self._task_id = task_id
        self._future = future  # resolves to ("__gen__", n) / raises
        self._client = client
        self._i = 0
        self._n: Optional[int] = None

    def _read_n(self):
        val = self._future.result(0)
        if isinstance(val, tuple) and val and val[0] == "__gen__":
            self._n = val[1]
        else:  # non-generator value under dynamic: single item
            self._n = 1

    def _adopt(self, oid: bytes) -> ObjectRef:
        ref = ObjectRef(ObjectID(oid))
        c = self._client
        c._in_store.add(oid)
        c._owned_store_oids.add(oid)
        c.known_refs[oid] = ref
        c._track_owned_ref(ref)
        return ref

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        c = self._client
        oid = object_id_for_task(self._task_id, self._i).binary()
        while True:
            if self._n is not None and self._i >= self._n:
                raise StopIteration
            # Item already visible? (local store, or known to the
            # directory after the producer's registration flush.)
            if c.store is not None and c.store.contains_raw(oid):
                break
            try:
                known = c._run(
                    c.gcs.call("object_location_get", {"object_id": oid}),
                    timeout=get_config().object_directory_rpc_timeout_s,
                )
                if known.get("nodes") or known.get("spilled"):
                    break
            except Exception:  # noqa: BLE001 — transient; retry below
                pass
            if self._future.done():
                if self._n is None:
                    self._read_n()  # raises the task's error if it failed
                    continue  # recheck i < n, then item visibility
                # Completed, i < n, but the item never appeared: the
                # store lost it; let get()'s recovery path deal with it.
                break
            time.sleep(0.01)
        self._i += 1
        return self._adopt(oid)

    def __repr__(self):
        return (f"ObjectRefGenerator(task={self._task_id.hex()[:12]}, "
                f"next={self._i})")


def _ref_from_binary(b: bytes) -> ObjectRef:
    client = _global_client
    if client is not None:
        existing = client.known_refs.get(b)
        if existing is not None:
            return existing
    return ObjectRef(ObjectID(b))


class ActorHandle:
    """Client-side handle to an actor (reference: python/ray/actor.py ActorHandle)."""

    def __init__(self, actor_id: ActorID, class_name: str, method_names: List[str],
                 max_task_retries: int = 0):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_names = list(method_names)
        self._max_task_retries = max_task_retries

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        # An empty method list means the handle was looked up before the
        # actor finished creation; defer validation to the receiving worker.
        if self._method_names and item not in self._method_names:
            raise AttributeError(
                f"actor {self._class_name} has no method {item!r}"
            )
        return ActorMethod(self, item)

    def _kill(self, no_restart: bool = True):
        get_client().kill_actor(self._actor_id, no_restart)

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id, self._class_name, self._method_names,
             self._max_task_retries),
        )

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"


class ActorMethod:
    def __init__(self, handle: ActorHandle, name: str, num_returns: int = 1,
                 max_task_retries: Optional[int] = None):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._max_task_retries = max_task_retries

    def options(self, num_returns: int = 1, max_task_retries: Optional[int] = None):
        return ActorMethod(self._handle, self._name, num_returns, max_task_retries)

    def remote(self, *args, **kwargs):
        retries = (
            self._max_task_retries
            if self._max_task_retries is not None
            else self._handle._max_task_retries
        )
        refs = get_client().submit_actor_call(
            self._handle._actor_id,
            self._name,
            args,
            kwargs,
            num_returns=self._num_returns,
            max_task_retries=retries,
        )
        return refs[0] if self._num_returns in (1, "dynamic") else refs

    def bind(self, *args, **kwargs):
        """Lazy DAG composition (reference: dag/class_node.py)."""
        from ray_tpu.dag.dag_node import ClassMethodNode

        return ClassMethodNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor methods cannot be called directly; use "
            f".{self._name}.remote(...)"
        )


class _Pin:
    """Keeps a store object pinned while a deserialized value is alive."""

    __slots__ = ("store", "oid")

    def __init__(self, store: ObjectStore, oid: ObjectID):
        self.store = store
        self.oid = oid

    def release(self):
        if self.store is not None:
            try:
                # Safe until the mapping is actually gone; refcounts live in
                # shared memory, so skipping would leak them cluster-wide.
                if not self.store._unmapped:
                    self.store.release(self.oid)
            except Exception:
                pass
            self.store = None


class CoreClient:
    """Synchronous facade over the asyncio control plane."""

    def __init__(
        self,
        loop: asyncio.AbstractEventLoop,
        gcs_addr: Tuple[str, int],
        raylet_addr: Tuple[str, int],
        store_name: str,
        node_id: bytes,
        job_id: JobID,
        mode: str = "driver",
    ):
        import os as _os

        self.loop = loop
        self.gcs_addr = gcs_addr
        self.raylet_addr = raylet_addr
        self.node_id = node_id
        self.job_id = job_id
        self.mode = mode
        self.client_id = _os.urandom(16)
        # store_name=None => remote (rt://) driver: no node-local shared
        # memory; puts/gets proxy through the raylet over TCP (the
        # reference's Ray Client role, util/client/worker.py:81).
        self.store = ObjectStore(store_name) if store_name else None
        # LRU-bounded cache of inline results (the in-process memory store,
        # memory_store.h:43). Values remain recoverable from a live ref's
        # completion future after eviction, so the bound is safe.
        from collections import OrderedDict

        self.memory_store: "OrderedDict[bytes, Any]" = OrderedDict()
        self.memory_store_max_entries = get_config().memory_store_max_entries
        self.known_refs: "weakref.WeakValueDictionary[bytes, ObjectRef]" = (
            weakref.WeakValueDictionary()
        )
        self.fn_manager = FunctionManager(self)
        self.gcs: Optional[Connection] = None
        self.raylet: Optional[Connection] = None
        self._actor_cache: Dict[bytes, dict] = {}
        self._actor_conns: Dict[Tuple[str, int], Connection] = {}
        self._actor_locks: Dict[bytes, asyncio.Lock] = {}
        self._actor_events: Dict[bytes, threading.Event] = {}
        self._pins: Dict[bytes, _Pin] = {}
        self._value_finalizers: list = []  # value-lifetime pins (see _read_store)
        self._in_store: set = set()  # oids known to live in shared store
        self._push_handlers = {}
        self._connected = False
        self.default_runtime_env = None  # job-level env from init()
        self._runtime_env_cache: Dict[str, Optional[dict]] = {}
        # Direct task transport: leased workers per scheduling class
        # (direct_task_transport.cc OnWorkerIdle — keep a granted worker
        # hot and push queued tasks without re-contacting the raylet).
        self._leases: Dict[tuple, dict] = {}
        self._lease_reaper: Optional[asyncio.Task] = None
        # Submit batching: bursts of .remote() calls cross the
        # thread->loop boundary once, not once per task.
        self._submit_buf: list = []
        self._submit_scheduled = False
        self._submit_lock = threading.Lock()
        # Owner-side lineage: store-kind return oid -> creating task spec,
        # for reconstruction when every copy is lost (TaskManager lineage +
        # ObjectRecoveryManager, object_recovery_manager.h:41).
        from collections import OrderedDict as _OD

        self.lineage: "_OD[bytes, dict]" = _OD()
        self.lineage_max_entries = get_config().lineage_max_entries
        # Owner-side reference GC (ReferenceCounter analog,
        # reference_count.h:61, simplified): when the last local ObjectRef
        # to an object THIS process owns dies — and no in-flight task
        # borrows it as an argument — the owner frees the cluster copies.
        # Borrowers (processes that deserialized the ref) never free.
        self._owned_store_oids: set = set()
        # Owned oids serialized out through task results: a borrower holds
        # them now, so local ref death must not free the store copy.
        self._escaped_oids: set = set()
        self._task_borrows: Dict[bytes, int] = {}
        self._free_dropped: set = set()   # dropped refs awaiting borrow==0
        self._free_queue: List[bytes] = []
        self._free_lock = threading.Lock()
        self._free_flusher = None
        # Batched async primary-copy registration: put() returns after the
        # store write; object_created notifications coalesce into one
        # raylet RPC per loop tick (the reference's plasma-notification
        # socket is asynchronous the same way).
        self._obj_created_buf: list = []
        self._obj_created_lock = threading.Lock()
        self._obj_created_scheduled = False
        # GCS-restart survival (client half): see _gcs_call.
        self._subscribed_channels: set = set()
        self._gcs_redial_lock = None
        # Client-side GCS RPC accounting (per-method count + wall sum):
        # cheap plain dicts read directly by benches/tests; the metric
        # registry mirrors them as gcs_rpc_client_* series.
        from collections import defaultdict as _dd

        self.gcs_rpc_counts: Dict[str, int] = _dd(int)
        self.gcs_rpc_time_s: Dict[str, float] = _dd(float)
        # Control-plane profiler (util/lifecycle): submit-side state for
        # sampled tasks — task_id -> {"t0", "t_buf", "phases", ...},
        # completed (popped + LIFECYCLE_SPAN emitted) in _complete_task;
        # return-oid -> task_id for driver-side get_wait stamps.
        self._lc_pending: Dict[bytes, dict] = {}
        self._lc_get_map: Dict[bytes, bytes] = {}
        # In-flight background pulls started by prefetch(): oid -> loop
        # task running _pull_object. get() joins an in-flight pull instead
        # of racing a second probe for the same object. Loop-side only.
        self._prefetch_pulls: Dict[bytes, asyncio.Task] = {}

    # -- bootstrap -------------------------------------------------------
    def connect(self):
        fut = asyncio.run_coroutine_threadsafe(self._connect(), self.loop)
        fut.result(timeout=get_config().rpc_connect_timeout_s * 3)
        self._connected = True

    async def _connect(self, raylet_conn: Optional[Connection] = None):
        # Name this process in journal dumps; weak so a more specific
        # label (replica/controller/proxy) set later is never clobbered,
        # and an in-process node's GCS never renames the driver.
        journal.set_process_label(self.mode or "proc", weak=True)
        self.gcs = await connect(*self.gcs_addr, push_handler=self._on_push)
        # Workers already hold a raylet connection (push channel); reuse it
        # rather than paying a second TCP connect on the boot path.
        if raylet_conn is not None:
            # Worker process: the conn belongs to worker_main, whose
            # push handler (run_task/create_actor) must stay installed;
            # it forwards lease_revoked here.
            self.raylet = raylet_conn
        else:
            # Driver: raylet-initiated notifications (drain-time lease
            # revocation) arrive as pushes on this connection.
            self.raylet = await connect(
                *self.raylet_addr, push_handler=self._on_raylet_push
            )
        # Control-plane profiler runtime toggle: adopt the cluster-wide
        # sampling rate (if one was set via `rt profile --on`) and follow
        # future changes over the profile_config broadcast channel —
        # drivers AND workers, so the sampled bit appears wherever tasks
        # are submitted from. Best-effort: profiling never gates connect.
        try:
            self._push_handlers.setdefault(
                "profile_config", []
            ).append(self._on_profile_config)
            self._subscribed_channels.add("profile_config")
            await self.gcs.call("subscribe", {"channel": "profile_config"})
            r = await self.gcs.call("get_profile_config", {})
            self._on_profile_config(r.get("profile_config") or {})
        except Exception:  # noqa: BLE001 — profiling is best-effort
            pass
        # Cluster black box: every connected process answers journal_dump
        # broadcasts by freezing its event ring into the named postmortem
        # bundle (util/journal.py). Best-effort, like profile_config.
        try:
            self._push_handlers.setdefault(
                "journal_dump", []
            ).append(journal.on_dump_trigger)
            self._subscribed_channels.add("journal_dump")
            await self.gcs.call("subscribe", {"channel": "journal_dump"})
        except Exception:  # noqa: BLE001 — the black box never gates connect
            pass

    @staticmethod
    def _on_profile_config(payload):
        rate = (payload or {}).get("task_trace_sample")
        if rate is not None:
            lifecycle.set_sample_rate(float(rate))

    async def _gcs_call(self, method, payload=None, timeout=None):
        """GCS call that survives a GCS restart: on a dead connection,
        redial once, replay channel subscriptions, and retry the call.

        Known limitation: if the GCS applied+persisted a non-idempotent
        write (register_actor, kv_put overwrite=False) and died before
        replying, the retry double-applies and may surface an
        'already exists' error for an operation that succeeded — the same
        at-least-once window every RPC-retry system has without
        idempotency tokens.
        """
        if method == "subscribe":
            self._subscribed_channels.add(payload["channel"])
        t0 = time.monotonic()
        try:
            try:
                return await self.gcs.call(method, payload, timeout=timeout)
            except ConnectionLost:
                await self._redial_gcs()
                return await self.gcs.call(method, payload, timeout=timeout)
        finally:
            # Per-method accounting, success or failure: "N GCS
            # round-trips per actor birth" must be a reported number.
            dur = time.monotonic() - t0
            self.gcs_rpc_counts[method] += 1
            self.gcs_rpc_time_s[method] += dur
            try:
                calls, lat = _gcs_rpc_metrics()
                tags = {"method": method}
                calls.inc(1.0, tags)
                lat.observe(dur, tags)
            except Exception:  # noqa: BLE001 — accounting must never break RPCs
                pass

    async def _redial_gcs(self):
        lock = self._gcs_redial_lock
        if lock is None:
            lock = self._gcs_redial_lock = asyncio.Lock()
        async with lock:
            if self.gcs is not None and not self.gcs._closed:
                return  # another caller already redialed
            deadline = time.monotonic() + 30.0
            while True:
                try:
                    gcs = await connect(
                        *self.gcs_addr, push_handler=self._on_push,
                        timeout=get_config().gcs_reconnect_dial_timeout_s
                    )
                    break
                except Exception:  # noqa: BLE001
                    if time.monotonic() > deadline:
                        raise ConnectionLost("GCS unreachable after restart")
                    await asyncio.sleep(get_config().gcs_reconnect_backoff_s)
            for ch in list(self._subscribed_channels):
                try:
                    await gcs.call("subscribe", {"channel": ch})
                except Exception:  # noqa: BLE001
                    pass
            self.gcs = gcs

    def _on_push(self, channel: str, payload):
        if channel.startswith("actor_update:"):
            aid = bytes.fromhex(channel.split(":", 1)[1])
            self._actor_cache[aid] = payload
            ev = self._actor_events.get(aid)
            if ev:
                ev.set()
        for handler in self._push_handlers.get(channel, ()):
            handler(payload)

    def subscribe_push(self, channel: str, handler):
        """Register a push handler + GCS subscription for a channel
        (client half of the pubsub long-poll replacement). Multiple
        handlers per channel fan out — a second subscriber must not evict
        the first."""
        self._push_handlers.setdefault(channel, []).append(handler)
        self._run(self._gcs_call("subscribe", {"channel": channel}))

    def publish(self, channel: str, payload=None):
        self._run(self._gcs_call("publish",
                                 {"channel": channel, "payload": payload}))

    def disconnect(self):
        # Quiesce the free flusher before teardown ("task destroyed but
        # pending" noise otherwise).
        self._connected = False
        flusher = self._free_flusher
        if flusher is not None and not flusher.done():
            try:
                self.loop.call_soon_threadsafe(flusher.cancel)
            except RuntimeError:
                pass
        # Decide unmap safety BEFORE releasing session pins: a session pin
        # means some non-weakrefable container of zero-copy views was
        # fetched, and we cannot know whether its arrays are still alive.
        self._live_views_at_disconnect = bool(self._pins) or any(
            f.alive for f in self._value_finalizers
        )
        for pin in self._pins.values():
            pin.release()
        self._pins.clear()

        async def _close():
            for t in list(self._prefetch_pulls.values()):
                t.cancel()
            self._prefetch_pulls.clear()
            if self._lease_reaper is not None:
                self._lease_reaper.cancel()
                self._lease_reaper = None
            try:
                await self._release_all_leases()
            except Exception:  # noqa: BLE001
                pass
            for c in list(self._actor_conns.values()):
                await c.close()
            if self.gcs:
                await self.gcs.close()
            if self.raylet:
                await self.raylet.close()

        try:
            asyncio.run_coroutine_threadsafe(_close(), self.loop).result(timeout=5)
        except Exception:
            pass
        # Leave the shared mapping in place if any fetched value might still
        # alias store memory — unmapping under a live numpy view is a
        # segfault. The mapping is reclaimed at process exit.
        if self.store is not None:
            self.store.close(unmap=not self._live_views_at_disconnect)
        self._connected = False

    def _run(self, coro, timeout=None):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    # -- reference GC -----------------------------------------------------
    def _track_owned_ref(self, ref: ObjectRef):
        """Fire the free protocol when this owned ref is garbage-collected."""
        weakref.finalize(ref, self._on_ref_dropped, ref.id.binary())

    def _on_ref_dropped(self, oid: bytes):
        # Runs from GC — any thread, possibly at interpreter shutdown.
        if not self._connected:
            return
        with self._free_lock:
            if self._task_borrows.get(oid, 0) > 0:
                self._free_dropped.add(oid)
                return
            self._free_queue.append(oid)
        try:
            self.loop.call_soon_threadsafe(self._ensure_free_flush)
        except RuntimeError:
            pass  # loop is shutting down; store is reclaimed with the node

    def _ensure_free_flush(self):
        if self._free_flusher is None or self._free_flusher.done():
            self._free_flusher = asyncio.ensure_future(self._flush_free())

    async def _flush_free(self):
        # Loop until the queue is drained: a ref dropped while the raylet
        # call below is in flight sees this task as not-done and schedules
        # nothing, so exiting with a non-empty queue would strand it.
        while True:
            await asyncio.sleep(get_config().free_flush_debounce_s)
            with self._free_lock:
                oids, self._free_queue = self._free_queue, []
            if not oids:
                return
            to_free = [
                o for o in oids
                if o in self._owned_store_oids and o not in self._escaped_oids
            ]
            for o in oids:
                self._owned_store_oids.discard(o)
                self.lineage.pop(o, None)
                self.memory_store.pop(o, None)
                self._in_store.discard(o)
            if not self._connected:
                return
            if to_free:
                try:
                    await self.raylet.call(
                        "free_objects", {"object_ids": to_free},
                        timeout=get_config().free_objects_timeout_s
                    )
                except Exception:  # noqa: BLE001 — eviction backstops
                    pass

    def _borrow_deps(self, spec: dict, deps: List[bytes]):
        """Pin deps for the task's lifetime so an argument whose driver ref
        dies mid-flight is not freed under the running task."""
        if not deps:
            return
        spec["deps_borrowed"] = list(deps)
        with self._free_lock:
            for dep in deps:
                self._task_borrows[dep] = self._task_borrows.get(dep, 0) + 1

    async def _release_ctor_borrows_when_live(self, actor_id: ActorID,
                                              ctor_spec: dict,
                                              max_restarts: int = 0):
        """Release actor-constructor arg pins once no creation replay can
        read them again. Restartable actors keep their pins until DEAD:
        the GCS replays the stored create_spec on every restart, and a
        replayed __init__ must still be able to resolve nested refs the
        driver has long dropped. State arrives via the actor_update push
        channel (_on_push keeps _actor_cache fresh) — this loop only reads
        the cache, no per-tick GCS RPCs."""
        aid = actor_id.binary()
        try:
            await self._gcs_call(
                "subscribe", {"channel": "actor_update:" + actor_id.hex()}
            )
        except Exception:  # noqa: BLE001 — cache polls still progress below
            pass
        try:
            first_rpc_done = False
            while self._connected:
                info = self._actor_cache.get(aid)
                if info is None and not first_rpc_done:
                    first_rpc_done = True
                    try:
                        info = (await self._gcs_call(
                            "get_actor", {"actor_id": aid}
                        ))["actor"]
                        if info is not None:
                            self._actor_cache[aid] = info
                    except Exception:  # noqa: BLE001
                        info = None
                state = (info or {}).get("state")
                if state == "DEAD":
                    break
                if state == "ALIVE" and max_restarts == 0:
                    break  # no replay possible: creation consumed the args
                await asyncio.sleep(1.0)
        finally:
            self._release_borrows(ctor_spec)

    def _release_borrows(self, spec: dict):
        deps = spec.pop("deps_borrowed", None)
        if not deps:
            return
        enqueued = False
        with self._free_lock:
            for dep in deps:
                n = self._task_borrows.get(dep, 0) - 1
                if n > 0:
                    self._task_borrows[dep] = n
                    continue
                self._task_borrows.pop(dep, None)
                if dep in self._free_dropped:
                    self._free_dropped.discard(dep)
                    self._free_queue.append(dep)
                    enqueued = True
        if enqueued:
            try:
                self.loop.call_soon_threadsafe(self._ensure_free_flush)
            except RuntimeError:
                pass

    # -- kv --------------------------------------------------------------
    def kv_put(self, key: bytes, value: bytes, ns: str = "", overwrite=True) -> bool:
        r = self._run(
            self._gcs_call(
                "kv_put", {"ns": ns, "key": key, "value": value, "overwrite": overwrite}
            ),
            timeout=get_config().gcs_op_timeout_s,
        )
        return r["added"]

    def kv_get(self, key: bytes, ns: str = "") -> Optional[bytes]:
        return self._run(self._gcs_call("kv_get", {"ns": ns, "key": key}),
                         timeout=get_config().gcs_op_timeout_s)["value"]

    def kv_del(self, key: bytes, ns: str = "") -> bool:
        return self._run(self._gcs_call("kv_del", {"ns": ns, "key": key}),
                         timeout=get_config().gcs_op_timeout_s)["deleted"]

    def kv_keys(self, prefix: bytes = b"", ns: str = "") -> List[bytes]:
        return self._run(self._gcs_call("kv_keys", {"ns": ns, "prefix": prefix}),
                         timeout=get_config().gcs_op_timeout_s)["keys"]

    # -- serialization helpers -------------------------------------------
    def serialize_args(self, args, kwargs) -> Tuple[bytes, List[bytes], List[bytes]]:
        """Serialize (args, kwargs); top-level refs become _ArgRef markers,
        nested refs are promoted to the shared store.

        Returns (payload, deps, borrow_oids): `deps` is what the raylet
        prefetches (top-level store args only — it must stay empty for
        plain tasks so they keep the direct-transport fast path);
        `borrow_oids` additionally includes refs nested inside containers,
        which the caller pins for the call's lifetime
        (reference_count.h nested-ref tracking — without the pin, the
        driver dropping its handle mid-flight frees the object under the
        running task's rt.get).

        Mirrors the reference's plasma-promotion of serialized ObjectRefs
        and inline substitution of resolved top-level args
        (transport/dependency_resolver.cc).
        """
        if not args and not kwargs:
            # The common trivial-call shape: one cached payload, no
            # cloudpickle work on the per-submit path.
            global _EMPTY_ARGS_PAYLOAD
            if _EMPTY_ARGS_PAYLOAD is None:
                _EMPTY_ARGS_PAYLOAD = ser.serialize_to_bytes(([], {}))
            return _EMPTY_ARGS_PAYLOAD, [], []
        deps: List[bytes] = []
        processed_args = []
        for a in args:
            if isinstance(a, ObjectRef):
                a = self._arg_for_ref(a, deps)
            processed_args.append(a)
        processed_kwargs = {}
        for k, v in kwargs.items():
            if isinstance(v, ObjectRef):
                v = self._arg_for_ref(v, deps)
            processed_kwargs[k] = v
        _ser_ctx.promote = True
        _ser_ctx.promoted = []
        try:
            payload = ser.serialize_to_bytes((processed_args, processed_kwargs))
        finally:
            _ser_ctx.promote = False
            promoted, _ser_ctx.promoted = _ser_ctx.promoted, []
        borrow_oids = list(deps)
        for oid in promoted:
            if oid not in borrow_oids:
                borrow_oids.append(oid)
        return payload, deps, borrow_oids

    def _arg_for_ref(self, ref: ObjectRef, deps: List[bytes]):
        oid = ref.id.binary()
        if oid in self.memory_store and oid not in self._in_store:
            return _InlineArg(self.memory_store[oid])
        # Wait for pending local task results so the dep is materialized.
        if ref._future is not None:
            value = ref._future.result()
            if value is not _IN_STORE and oid not in self._in_store:
                return _InlineArg(value)
        deps.append(oid)
        return _StoreArg(oid)

    def serialize_result(self, value):
        """Serialize a task/actor return value. ObjectRefs inside escape to
        a borrower: promote them to the shared store and exempt them from
        this owner's local-ref-drop free — the recipient holds a handle the
        owner can no longer see (reference_count.h borrower rule; without
        this, the owner's GC frees the copy under the borrower).

        Escaped objects are never auto-freed by this owner (the full
        borrower-count protocol the reference runs is future work); they
        stay spillable, so sustained pressure degrades them to disk rather
        than occupying shm, and they are reclaimed with the job."""
        _ser_ctx.promote = True
        _ser_ctx.promoted = []
        try:
            so = ser.serialize(value)
        finally:
            _ser_ctx.promote = False
            promoted, _ser_ctx.promoted = _ser_ctx.promoted, []
        for oid in promoted:
            self._escaped_oids.add(oid)
        return so

    def deserialize_args(self, payload: bytes):
        args, kwargs = ser.deserialize_from_bytes(payload)
        args = tuple(self._resolve_arg(a) for a in args)
        kwargs = {k: self._resolve_arg(v) for k, v in kwargs.items()}
        return args, kwargs

    def _resolve_arg(self, a):
        if isinstance(a, _InlineArg):
            return a.value
        if isinstance(a, _StoreArg):
            # Store pull under the executing worker's deserialize window:
            # the lifecycle profiler splits this wait out as arg_fetch
            # (thread-local accumulator, armed only for sampled tasks).
            t0 = time.monotonic()
            try:
                return self.get(
                    [ObjectRef(ObjectID(a.oid))],
                    timeout=get_config().arg_fetch_timeout_s,
                )[0]
            finally:
                lifecycle.add_arg_fetch(time.monotonic() - t0)
        return a

    def promote_ref(self, ref: ObjectRef):
        """Ensure a ref's value is resolvable from the shared store."""
        oid = ref.id.binary()
        if oid in self._in_store or (
            self.store is not None and self.store.contains_raw(oid)
        ):
            return
        value = None
        have_value = False
        if oid in self.memory_store:
            value = self.memory_store[oid]
            have_value = True
        elif ref._future is not None:
            value = ref._future.result()
            have_value = value is not _IN_STORE
        if have_value:
            self._put_to_store(ObjectID(oid), value)
        # else: remote object; the directory resolves it

    def put_serialized_with_spill(self, oid: ObjectID, so) -> bool:
        """Write to the shared store, asking the raylet to spill under
        pressure; registers + pins the primary copy via the raylet
        (object_created), never silently evictable."""
        from ray_tpu.exceptions import ObjectStoreFullError

        if self.store is None:  # remote driver: ship bytes to the raylet
            if not self._client_put_remote(oid, so):
                raise ObjectLostError(
                    f"remote put of {oid.hex()} was not stored"
                )
            return True

        wrote = False
        attempts = 8
        for attempt in range(attempts):
            try:
                wrote = self.store.put_serialized(oid, so)
                break
            except ObjectStoreFullError:
                if attempt == attempts - 1:
                    raise
                r = self._run(self.raylet.call("spill_objects", {}),
                              timeout=get_config().spill_rpc_timeout_s)
                if not r.get("spilled"):
                    # Nothing spillable right now — concurrent writers may
                    # finish (and become spillable) shortly.
                    time.sleep(get_config().spill_retry_backoff_s)
        if wrote:
            self._queue_object_created(oid.binary(), so.total_size)
        return wrote

    def _queue_object_created(self, oid: bytes, size: int):
        """Register + pin the sealed primary copy with the raylet — batched
        and asynchronous (any thread). The raylet pins and records the
        location in the GCS directory; readers that race the registration
        fall back to the directory's probe/wait path.

        Until the raylet's pin lands, the client holds its own store view:
        the store-side refcount keeps LRU eviction off the sole copy
        through the registration window (the old synchronous registration
        guaranteed this by blocking put())."""
        pinned = False
        if self.store is not None:
            try:
                pinned = self.store.get(ObjectID(oid)) is not None
            except Exception:  # noqa: BLE001 — registration still proceeds
                pinned = False
        with self._obj_created_lock:
            self._obj_created_buf.append(
                ({"object_id": oid, "size": size}, pinned)
            )
            need = not self._obj_created_scheduled
            if need:
                self._obj_created_scheduled = True
        if need:
            try:
                self.loop.call_soon_threadsafe(self._flush_object_created)
            except RuntimeError:
                pass  # loop shutting down; node reclaims the store

    def _flush_object_created(self):
        with self._obj_created_lock:
            buf, self._obj_created_buf = self._obj_created_buf, []
            self._obj_created_scheduled = False
        if buf and self._connected:
            spawn(self._send_objects_created(buf))

    async def _send_objects_created(self, buf):
        try:
            await self.raylet.call(
                "objects_created", {"objects": [e for e, _ in buf]},
                timeout=60,
            )
        except Exception:  # noqa: BLE001 — directory probes re-resolve
            pass
        finally:
            # Drop the client-side pins now that the raylet holds its own
            # (store.get refcounts require an explicit paired release).
            if self.store is not None:
                for e, pinned in buf:
                    if pinned:
                        try:
                            self.store.release(ObjectID(e["object_id"]))
                        except Exception:  # noqa: BLE001
                            pass

    def _put_to_store(self, oid: ObjectID, value) -> int:
        so = ser.serialize(value)
        self.put_serialized_with_spill(oid, so)
        self._in_store.add(oid.binary())
        return so.total_size

    # -- put / get / wait -------------------------------------------------
    def put(self, value) -> ObjectRef:
        oid = ObjectID.from_random()
        self._put_to_store(oid, value)
        ref = ObjectRef(oid)
        self.known_refs[oid.binary()] = ref
        self._owned_store_oids.add(oid.binary())
        self._track_owned_ref(ref)
        return ref

    def get(self, refs: List[ObjectRef], timeout: Optional[float]):
        lc_t0 = time.monotonic() if self._lc_get_map else None
        deadline = None if timeout is None else time.monotonic() + timeout
        out: List[Any] = [None] * len(refs)
        remote: List[Tuple[int, ObjectRef]] = []
        for i, ref in enumerate(refs):
            hit, value = self._resolve_local(ref, deadline)
            if hit:
                out[i] = value
            else:
                remote.append((i, ref))
        if remote:
            # One round of concurrent pulls: every remote ref probes in
            # parallel on the event loop under the shared deadline, instead
            # of N sequential blocking pulls. Per-ref lost-object detection
            # and lineage reconstruction live in _pull_object unchanged.
            results = self._run(
                self._pull_many([ref.id.binary() for _, ref in remote],
                                deadline)
            )
            for (i, ref), res in zip(remote, results):
                if isinstance(res, BaseException):
                    raise res  # first failing ref in list order
                out[i] = self._read_store(ObjectID(ref.id.binary()))
        if lc_t0 is not None:
            self._lc_note_get_wait(refs, time.monotonic() - lc_t0)
        return out

    def prefetch(self, refs: List[ObjectRef]) -> int:
        """Start background pulls for refs not yet local; never blocks.

        Each pull is a fire-and-forget event-loop task, deduplicated per
        object; a later get() joins the in-flight pull instead of racing a
        second probe. Failures are advisory — get() re-resolves the ref and
        surfaces errors with full reconstruction semantics. Returns the
        number of pulls started.
        """
        if not self._connected or self.store is None:
            return 0
        started: List[bytes] = []
        for ref in refs:
            oid = ref.id.binary()
            f = ref._future
            if f is not None:
                if not f.done():
                    continue  # still executing locally; nothing to pull yet
                try:
                    if f.result() is not _IN_STORE:
                        continue  # inline value — no store copy to pull
                except BaseException:
                    continue  # errored/cancelled; get() will surface it
            if oid in self.memory_store:
                continue
            if self.store.contains_raw(oid):
                continue
            started.append(oid)
        if started:
            self.loop.call_soon_threadsafe(self._start_prefetch_pulls, started)
        return len(started)

    def _start_prefetch_pulls(self, oids: List[bytes]) -> None:
        if not self._connected:
            return
        for oid in oids:
            existing = self._prefetch_pulls.get(oid)
            if existing is not None and not existing.done():
                continue
            self._prefetch_pulls[oid] = spawn(self._prefetch_pull(oid))

    async def _prefetch_pull(self, oid: bytes) -> None:
        # Bounded deadline: an advisory pull for a never-produced object
        # must not park a loop task forever (blocking-get semantics belong
        # to get(), which re-issues its own pull).
        deadline = time.monotonic() + get_config().prefetch_pull_timeout_s
        try:
            await self._pull_object(oid, deadline)
        except Exception:  # noqa: BLE001 — advisory; get() re-surfaces
            pass
        finally:
            self._prefetch_pulls.pop(oid, None)

    def _memory_store_put(self, oid: bytes, value):
        ms = self.memory_store
        ms[oid] = value
        ms.move_to_end(oid)
        while len(ms) > self.memory_store_max_entries:
            ms.popitem(last=False)

    def _resolve_local(self, ref: ObjectRef, deadline) -> Tuple[bool, Any]:
        """Resolve a ref from its completion future / memory store / local
        shm store without touching the network. Returns (hit, value)."""
        oid = ref.id.binary()
        if ref._future is not None:
            remaining = None if deadline is None else max(0, deadline - time.monotonic())
            try:
                completed = ref._future.result(remaining)
            except concurrent.futures.TimeoutError:
                raise GetTimeoutError(f"get() timed out waiting for {ref}")
            if completed is not _IN_STORE and oid not in self.memory_store:
                # Inline result evicted from the LRU cache; the completion
                # future still holds it.
                return True, completed
        if oid in self.memory_store:
            return True, self.memory_store[oid]
        if self.store is not None and self.store.contains_raw(oid):
            return True, self._read_store(ObjectID(oid))
        return False, None

    async def _pull_many(self, oids: List[bytes], deadline):
        return await asyncio.gather(
            *(self._pull_or_join(oid, deadline) for oid in oids),
            return_exceptions=True,
        )

    async def _pull_or_join(self, oid: bytes, deadline) -> None:
        task = self._prefetch_pulls.get(oid)
        if task is not None and not task.done():
            remaining = (
                None if deadline is None
                else max(0.05, deadline - time.monotonic())
            )
            try:
                await asyncio.wait_for(asyncio.shield(task), remaining)
            except asyncio.TimeoutError:
                raise GetTimeoutError(
                    f"get() timed out waiting for "
                    f"ObjectRef({ObjectID(oid).hex()})"
                )
            except Exception:  # noqa: BLE001 — advisory; re-pull below
                pass
        if self.store is not None and self.store.contains_raw(oid):
            return
        await self._pull_object(oid, deadline)

    async def _pull_object(self, oid: bytes, deadline) -> None:
        """Pull one remote object into the local store (event-loop side).

        Ask our raylet to pull it locally. Probes are short so a vanished
        object is detected well before the caller's deadline; with lineage
        the creating task re-executes
        (ObjectRecoveryManager::RecoverObject), otherwise the object is
        declared lost after a grace probe.
        """
        recon_left = get_config().task_max_retries
        last_err: Optional[Exception] = None
        while True:
            remaining = (
                60.0 if deadline is None else max(0.1, deadline - time.monotonic())
            )
            probe = min(get_config().get_probe_interval_s, remaining * 0.4)
            try:
                await self.raylet.call(
                    "wait_object_local",
                    {"object_id": oid, "timeout": probe},
                    timeout=probe + 5,
                )
                return
            except Exception as e:  # noqa: BLE001
                last_err = e
                if deadline is not None and time.monotonic() >= deadline:
                    raise GetTimeoutError(
                        f"get() timed out waiting for "
                        f"ObjectRef({ObjectID(oid).hex()})"
                    )
                # A probe timeout can just mean a slow transfer. Consult the
                # object directory first: re-executing the (side-effectful)
                # creating task while a copy still exists would duplicate it.
                # "Known with zero copies" means every replica (memory +
                # spill) is gone — lost. Unknown means possibly not yet
                # produced: keep waiting (blocking get semantics).
                try:
                    loc = await self._gcs_call(
                        "object_location_get", {"object_id": oid},
                        timeout=10,
                    )
                except Exception:
                    continue
                lost = (
                    loc.get("known")
                    and not loc.get("nodes")
                    and not loc.get("spilled")
                )
                if not lost:
                    continue  # copy exists or not yet produced: keep pulling
                spec = self.lineage.get(oid)
                if spec is None:
                    break  # registered once, all copies lost, no lineage
                # Re-execute the creating task (bounded attempts).
                if recon_left <= 0:
                    break
                recon_left -= 1
                result = await asyncio.wait_for(
                    self.raylet.call("submit_task", dict(spec), timeout=None),
                    None if deadline is None else remaining,
                )
                if result.get("status") != "ok":
                    break
                continue
        raise ObjectLostError(
            f"object {ObjectID(oid).hex()} could not be retrieved: {last_err}"
        ) from None

    def _client_put_remote(self, oid: ObjectID, so) -> bool:
        """Ship a put to the raylet's store over TCP. Small objects go in
        one frame; large ones stream in transfer-sized chunks so neither
        side buffers (or stalls its event loop on) one giant message."""
        data = so.to_bytes()
        chunk = get_config().object_transfer_chunk_size
        if len(data) <= chunk:
            r = self._run(
                self.raylet.call(
                    "client_put", {"object_id": oid.binary(), "data": data},
                    timeout=get_config().remote_client_op_timeout_s,
                )
            )
            return bool(r.get("ok"))
        r = self._run(
            self.raylet.call(
                "client_create",
                {"object_id": oid.binary(), "size": len(data)},
                timeout=get_config().remote_client_op_timeout_s,
            )
        )
        if not r.get("ok"):
            raise ObjectLostError(f"remote put failed: {r.get('error')}")
        if r.get("exists"):
            return True
        view = memoryview(data)
        for off in range(0, len(data), chunk):
            r = self._run(
                self.raylet.call(
                    "client_put_chunk",
                    {"object_id": oid.binary(), "offset": off,
                     "data": bytes(view[off:off + chunk])},
                    timeout=get_config().remote_client_op_timeout_s,
                )
            )
            if not r.get("ok"):
                raise ObjectLostError(f"remote put failed: {r.get('error')}")
        r = self._run(
            self.raylet.call(
                "client_seal",
                {"object_id": oid.binary(), "size": len(data)},
                timeout=get_config().remote_client_op_timeout_s,
            )
        )
        return bool(r.get("ok"))

    def _read_remote(self, oid: ObjectID):
        """Remote (rt://) driver: stream the object out of the raylet's
        store over TCP in transfer-sized chunks.

        The chunk stream holds no pin on the raylet side, so a concurrent
        spill can evict the object mid-stream; each retry re-runs
        client_get_info, whose _ensure_local restores spilled copies."""
        from ray_tpu._private.protocol import RpcError

        last_err = None
        for _attempt in range(3):
            try:
                info = self._run(
                    self.raylet.call(
                        "client_get_info", {"object_id": oid.binary()},
                        timeout=get_config().remote_client_op_timeout_s,
                    )
                )
                if not info.get("ok"):
                    raise ObjectLostError(
                        f"object {oid.hex()}: {info.get('error')}"
                    )
                size = info["size"]
                chunk = get_config().object_transfer_chunk_size
                parts = []
                off = 0
                while off < size:
                    n = min(chunk, size - off)
                    r = self._run(
                        self.raylet.call(
                            "fetch_chunk",
                            {"object_id": oid.binary(), "offset": off,
                             "size": n},
                            timeout=get_config().remote_client_op_timeout_s,
                        )
                    )
                    parts.append(r["data"])
                    off += n
                value = ser.deserialize(memoryview(b"".join(parts)))
                self._in_store.add(oid.binary())
                return value
            except RpcError as e:  # spilled/evicted mid-stream: retry
                last_err = e
        raise ObjectLostError(
            f"remote fetch of {oid.hex()} failed: {last_err}"
        ) from None

    def _read_store(self, oid: ObjectID):
        if self.store is None:
            return self._read_remote(oid)
        view = self.store.get(oid)
        if view is None:
            raise ObjectLostError(f"object {oid.hex()} missing from local store")
        value = ser.deserialize(view)
        # The store-side refcount from get() is the pin protecting the
        # zero-copy buffers under `value`. Tie its release to the value's
        # lifetime where possible so dropped results become spillable;
        # otherwise hold a session pin (released at disconnect).
        pin = _Pin(self.store, oid)
        try:
            fin = weakref.finalize(value, pin.release)
            self._value_finalizers.append(fin)
            if len(self._value_finalizers) > 256:
                self._value_finalizers = [
                    f for f in self._value_finalizers if f.alive
                ]
        except TypeError:  # not weakref-able (tuple/dict/primitive)
            old = self._pins.get(oid.binary())
            if old is not None:
                pin.release()  # keep a single session pin per object
            else:
                self._pins[oid.binary()] = pin
        self._in_store.add(oid.binary())
        return value

    def wait(self, refs: List[ObjectRef], num_returns: int, timeout: Optional[float],
             fetch_local: bool = True):
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[ObjectRef] = []
        pending = list(refs)
        while True:
            still = []
            for ref in pending:
                oid = ref.id.binary()
                done = (
                    (ref._future is not None and ref._future.done())
                    or oid in self.memory_store
                    or (self.store is not None
                        and self.store.contains_raw(oid))
                )
                if not done and ref._future is None:
                    # Check the cluster directory for remote completion; a
                    # spilled-only object is ready (restorable on get).
                    loc = self._run(
                        self._gcs_call("object_location_get", {"object_id": oid})
                    )
                    done = bool(loc["nodes"]) or bool(loc.get("spilled"))
                (ready if done else still).append(ref)
            pending = still
            if len(ready) >= num_returns or not pending:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(0.005)
        return ready[:num_returns], ready[num_returns:] + pending

    # -- task submission ---------------------------------------------------
    def _resolve_runtime_env(self, renv) -> Optional[dict]:
        """Resolve + cache a runtime env (upload working_dir/py_modules once
        per content); falls back to the job-level env from init()."""
        if renv is None:
            renv = self.default_runtime_env
        if not renv:
            return None
        if "hash" in renv:  # already resolved (job-inherited env)
            return dict(renv)
        import json as _json

        from ray_tpu.runtime_env import prepare_runtime_env

        cache_key = _json.dumps(dict(renv), sort_keys=True, default=str)
        hit = self._runtime_env_cache.get(cache_key)
        if hit is None:
            hit = prepare_runtime_env(renv, self)
            self._runtime_env_cache[cache_key] = hit
        return hit

    def submit_task(
        self,
        fn,
        args,
        kwargs,
        name: str = "",
        num_returns: int = 1,
        resources: Optional[Dict[str, float]] = None,
        scheduling=None,
        max_retries: Optional[int] = None,
        runtime_env=None,
        max_calls: Optional[int] = None,
        priority: int = 0,
    ) -> List[ObjectRef]:
        cfg = get_config()
        # Control-plane profiler head sampling: one module-attr check per
        # task when off; a sampled task carries the bit in its spec and
        # every hop stamps phase marks (util/lifecycle).
        lc_sampled = lifecycle.enabled and lifecycle.sample()
        if lc_sampled:
            _lc_t0, _lc_ts0 = time.monotonic(), time.time()
        fn_key = self.fn_manager.export(fn)
        payload, deps, borrow_oids = self.serialize_args(args, kwargs)
        if lc_sampled:
            _lc_ser = time.monotonic() - _lc_t0
        task_id = TaskID.from_random()
        resolved_env = self._resolve_runtime_env(runtime_env)
        spec = {
            "task_id": task_id.binary(),
            "job_id": self.job_id.binary(),
            "name": name,
            "fn_key": fn_key,
            "args": payload,
            "deps": deps,
            "num_returns": num_returns,
            "resources": resources if resources is not None else {"CPU": 1.0},
            "scheduling": scheduling,
            "runtime_env": resolved_env,
            "runtime_env_hash": resolved_env["hash"] if resolved_env else None,
        }
        if priority:
            # Priority class: orders raylet dispatch and makes the demand
            # eligible to reclaim chips from lower-priority gangs.
            spec["priority"] = int(priority)
        if max_calls:
            # Worker retires after this many executions of the function
            # (reference: @ray.remote(max_calls=N), remote_function.py —
            # the leak mitigation for tasks wrapping leaky native code).
            spec["max_calls"] = int(max_calls)
        retries = cfg.task_max_retries if max_retries is None else max_retries
        # The raylet's OOM policy prefers killing retriable tasks
        # (worker_killing_policy.cc retriable-FIFO). max_retries=-1 means
        # infinite retries — very much retriable.
        spec["retriable"] = retries != 0
        from ray_tpu.util import tracing

        trace_ctx = tracing.inject()
        if trace_ctx:
            spec["trace_ctx"] = trace_ctx
        if num_returns == "dynamic":
            # Streaming generator task: ONE future carries completion +
            # item count; item refs materialize through the
            # ObjectRefGenerator as the task yields.
            fut = concurrent.futures.Future()
            refs = [ObjectRefGenerator(task_id, fut, self)]
            futures = [fut]
        else:
            refs = []
            futures = []
            for i in range(num_returns):
                oid = object_id_for_task(task_id, i)
                fut = concurrent.futures.Future()
                ref = ObjectRef(oid, fut)
                self.known_refs[oid.binary()] = ref
                self._track_owned_ref(ref)
                refs.append(ref)
                futures.append(fut)
        self._borrow_deps(spec, borrow_oids)
        if lc_sampled:
            spec["sampled"] = True
            self._lc_track(task_id.binary(), name, _lc_t0, _lc_ts0,
                           _lc_ser, refs)
        with self._submit_lock:
            self._submit_buf.append((spec, futures, retries))
            need_schedule = not self._submit_scheduled
            if need_schedule:
                self._submit_scheduled = True
        if need_schedule:
            self.loop.call_soon_threadsafe(self._drain_submits)
        return refs

    # -- control-plane profiler (submit side) ---------------------------
    def _lc_track(self, task_id, name, t0, ts0, serialize_s, refs):
        """Register a sampled submission: phase accumulator keyed by task
        id (finished in _complete_task) + return-oid map for get_wait."""
        self._lc_pending[task_id] = {
            "t0": t0,
            "t_buf": time.monotonic(),
            "name": name,
            "phases": {"serialize": [ts0, serialize_s]},
        }
        for ref in refs:
            if isinstance(ref, ObjectRef):
                self._lc_get_map[ref.id.binary()] = task_id
        # Bound both maps: tasks whose completion we miss (client-side
        # crash paths) and refs never passed to get() must not leak.
        for m in (self._lc_pending, self._lc_get_map):
            while len(m) > 16384:
                m.pop(next(iter(m)), None)

    def _lc_emit(self, ev):
        """Queue one LIFECYCLE_SPAN event on the shared profiling buffer
        (bounded-delay batched flush to the GCS)."""
        from ray_tpu.util import profiling

        with profiling._lock:
            profiling._buffer.append(ev)
        profiling.request_flush()

    def _lc_close_submit_buffer(self, spec):
        """Close a sampled task's submit_buffer phase: .remote() → the
        task reaching its sender coroutine (burst-buffer wait + drain
        routing + the event-loop hop into the sender), so the client-side
        phases tile the submit window with no unattributed gap."""
        pend = self._lc_pending.get(spec["task_id"])
        if pend is not None and "t_buf" in pend:
            dur = max(0.0, time.monotonic() - pend.pop("t_buf"))
            pend["phases"]["submit_buffer"] = [time.time() - dur, dur]  # rtlint: disable=RT011 — deliberate wall anchor: [start_wall, dur] stitches this phase onto cross-process timelines

    def _lc_stamp_rpc_wait(self, task_id, t0_m):
        """Close a sampled task's rpc_wait mark: the submit RPC's full
        round-trip, stamped only on single-spec frames (a batch frame's
        wall spans its siblings' execution, so per-task attribution
        would lie). The stitcher subtracts the remote-attributed phases
        to derive the ``transport`` (wire + event-loop) residual."""
        pend = self._lc_pending.get(task_id)
        if pend is not None:
            dur = max(0.0, time.monotonic() - t0_m)
            pend["phases"]["rpc_wait"] = [time.time() - dur, dur]  # rtlint: disable=RT011 — deliberate wall anchor for cross-process phase stitching

    def _lc_complete(self, spec):
        """_complete_task: emit the client-hop LIFECYCLE_SPAN carrying
        the submit-side phases and the authoritative e2e wall."""
        pend = self._lc_pending.pop(spec["task_id"], None)
        if pend is None:
            return
        self._lc_emit(lifecycle.event(
            spec["task_id"], pend["name"], self.job_id.binary(),
            self.node_id, "client", pend["phases"],
            e2e_s=max(0.0, time.monotonic() - pend["t0"]),
        ))

    def _lc_note_get_wait(self, refs, dur_s):
        """get(): attribute one blocking-get wall to each sampled task
        whose return ref was fetched (overlaps remote phases; kept out
        of the phase sum — see lifecycle.SUM_PHASES)."""
        now = time.time()
        for ref in refs:
            tid = self._lc_get_map.pop(ref.id.binary(), None)
            if tid is None:
                continue
            self._lc_emit(lifecycle.event(
                tid, "", self.job_id.binary(), self.node_id, "client",
                {"get_wait": [now - dur_s, dur_s]},
            ))

    def _drain_submits(self):
        """Runs on the loop: route a burst of queued submissions.

        Direct-eligible tasks sharing a lease key and pipelined calls to
        the same actor are grouped into batch frames — one RPC (and one
        worker-side executor hop) covers the whole run instead of one per
        task, which is where the per-op interpreter cost lives on the
        10k-tasks/s path."""
        with self._submit_lock:
            buf, self._submit_buf = self._submit_buf, []
            self._submit_scheduled = False
        direct_groups: Dict[tuple, list] = {}
        actor_groups: Dict[bytes, list] = {}
        for item in buf:
            if item[0] == "actor":
                _, actor_id, request, spec, futures, retries = item
                actor_groups.setdefault(actor_id.binary(), []).append(
                    (actor_id, request, spec, futures, retries)
                )
            elif self._direct_eligible(item[0]):
                key = self._lease_key(item[0])
                direct_groups.setdefault(key, []).append(item)
            else:
                spawn(self._submit_with_retries(*item))
        for group in direct_groups.values():
            spawn(self._submit_direct_group(group))
        for calls in actor_groups.values():
            if len(calls) == 1:
                spawn(self._actor_call_with_retries(*calls[0]))
            else:
                spawn(self._actor_call_group(calls))

    @staticmethod
    def _direct_eligible(spec) -> bool:
        """Direct transport handles the plain case: no object deps (the
        raylet owns dependency fetching), default scheduling, single
        return. Everything else takes the classic submit path."""
        return (
            not spec.get("deps")
            and spec.get("scheduling") is None
            and spec.get("num_returns", 1) in (1, "dynamic")
        )

    async def _submit_direct(self, spec, futures, retries):
        return await self._submit_direct_group([(spec, futures, retries)])

    async def _submit_direct_group(self, items):
        """Submit a burst of same-lease-key tasks as batch frames.

        Chunks spread across the lease pool (least-outstanding first, the
        pool growing while chunks stack up) so a big burst still fans out
        over every leased worker; each chunk costs one RPC and one
        worker-side executor hop regardless of size."""
        # Only plain CPU shapes may share a lease (pipelining depth — see
        # _lease_for): a batch of resource-bearing tasks on one worker
        # would serialize a gang the raylet should spread across hosts.
        cpu_only = all(
            k == "CPU" for k in (items[0][0].get("resources") or {})
        )
        batch_max = get_config().direct_submit_batch_max if cpu_only else 1
        i = 0
        while i < len(items):
            chunk = items[i:i + batch_max]
            i += batch_max
            entry = None
            lc_t = time.monotonic() if self._lc_pending else None
            if lc_t is not None:
                for _spec, _f, _r in chunk:
                    self._lc_close_submit_buffer(_spec)
            try:
                entry = await self._lease_for(chunk[0][0])
            except Exception:  # noqa: BLE001 — lease loss must never lose a task
                entry = None
            if lc_t is not None:
                # Lease acquisition (usually a pool hit, ~0; a raylet
                # round-trip when the pool grows) charged to every
                # sampled task in the chunk that shared it.
                dur = time.monotonic() - lc_t
                wall = time.time() - dur  # rtlint: disable=RT011 — deliberate wall anchor for cross-process phase stitching
                for _spec, _f, _r in chunk:
                    pend = self._lc_pending.get(_spec["task_id"])
                    if pend is not None:
                        pend["phases"]["lease"] = [wall, dur]
            if entry is None:
                for spec, futures, retries in chunk:
                    spawn(self._submit_with_retries(spec, futures, retries))
                continue
            # Count the chunk against the worker NOW (not inside the spawned
            # sender): _lease_for often returns without yielding, so the next
            # loop iteration must already see this load or every chunk in the
            # burst lands on the same worker.
            entry["outstanding"] += len(chunk)
            entry["last_used"] = time.monotonic()
            # rpc_wait anchors here (not inside the spawned sender) so the
            # event-loop hop into the sender coroutine is attributed too.
            spawn(self._send_direct_batch(entry, chunk, time.monotonic()))

    async def _send_direct_batch(self, entry, chunk, rpc_t0=None):
        try:
            if len(chunk) == 1:
                spec0 = chunk[0][0]
                rpc_t = (
                    (rpc_t0 or time.monotonic())
                    if spec0.get("sampled") and self._lc_pending else None
                )
                results = [await entry["conn"].call(
                    "run_task_direct", spec0, timeout=None)]
                if rpc_t is not None:
                    self._lc_stamp_rpc_wait(spec0["task_id"], rpc_t)
            else:
                resp = await entry["conn"].call(
                    "run_tasks_batch",
                    {"specs": [c[0] for c in chunk]},
                    timeout=None,
                )
                results = resp["results"]
        except (ConnectionLost, RpcError):
            # Leased worker died mid-batch. Any task may have executed
            # before the reply was lost, so max_retries=0 (at-most-once)
            # must NOT re-run it — same contract as the classic path.
            for spec, futures, retries in chunk:
                if retries == 0:
                    self._complete_task(
                        spec,
                        {"status": "worker_crashed",
                         "error": "leased worker connection lost"},
                        futures,
                    )
                else:
                    remaining = retries if retries < 0 else retries - 1
                    spawn(self._submit_with_retries(spec, futures, remaining))
            return
        finally:
            entry["outstanding"] -= len(chunk)
            entry["last_used"] = time.monotonic()
        for (spec, futures, retries), result in zip(chunk, results):
            if result.get("status") == "worker_crashed" and result.get(
                "not_executed"
            ):
                # The worker refused before running (retiring under
                # max_calls): safe to resubmit even at max_retries=0 —
                # nothing executed.
                spawn(self._submit_with_retries(spec, futures, retries))
                continue
            self._complete_task(spec, result, futures)

    @staticmethod
    def _lease_key(spec) -> tuple:
        return (
            spec.get("runtime_env_hash"),
            tuple(sorted((spec.get("resources") or {}).items())),
        )

    async def _lease_for(self, spec):
        key = self._lease_key(spec)
        pool = self._leases.setdefault(
            key, {"workers": [], "acquiring": False}
        )
        live = [w for w in pool["workers"] if not w["conn"]._closed]
        pool["workers"] = live
        best = min(live, key=lambda w: w["outstanding"], default=None)
        # Pipelining DEPTH (queueing a second task behind a running one on
        # the same leased worker) is only sound for plain CPU shapes. A
        # resource-bearing task (TPU gangs, custom resources) queued deep
        # on a held worker would serialize on one node while the raylet
        # could have spilled it to idle capacity elsewhere — the reference
        # keeps leases 1:1 with running tasks for exactly this reason
        # (direct_task_transport.cc). So: non-CPU shapes take an idle
        # lease or fall back to the raylet's scheduler.
        cpu_only = all(
            k == "CPU" for k in (spec.get("resources") or {})
        )
        if not cpu_only and best is not None and best["outstanding"] > 0:
            best = None
        # Grow while tasks are stacking up (up to the node's CPU-ish cap);
        # single-flight so a burst requests one lease at a time.
        cfg = get_config()
        if (
            (best is None
             or best["outstanding"] >= cfg.direct_lease_grow_outstanding)
            and len(live) < cfg.direct_lease_max_workers
            and not pool["acquiring"]
        ):
            pool["acquiring"] = True
            try:
                resp = await self.raylet.call(
                    "lease_worker",
                    {
                        "resources": spec.get("resources") or {},
                        "runtime_env_hash": spec.get("runtime_env_hash"),
                        "runtime_env": spec.get("runtime_env"),
                    },
                    timeout=cfg.lease_rpc_timeout_s,
                )
                if resp.get("status") == "ok":
                    try:
                        conn = await connect(resp["host"], resp["port"])
                    except Exception:
                        # Granted but unreachable: return it or the
                        # raylet's resources leak until our conn dies.
                        await self.raylet.call(
                            "release_lease",
                            {"worker_id": resp["worker_id"]},
                            timeout=cfg.lease_rpc_timeout_s,
                        )
                        raise
                    w = {
                        "conn": conn,
                        "worker_id": resp["worker_id"],
                        "outstanding": 0,
                        "last_used": time.monotonic(),
                        "key": key,
                    }
                    pool["workers"].append(w)
                    if best is None:
                        best = w
            except Exception:  # noqa: BLE001 — lease is opportunistic
                pass
            finally:
                pool["acquiring"] = False
        if best is not None and self._lease_reaper is None:
            self._lease_reaper = spawn(self._reap_leases_loop())
        return best

    async def _reap_leases_loop(self):
        """Return idle leases so the raylet can schedule other work."""
        try:
            while self._connected:
                await asyncio.sleep(get_config().lease_reap_interval_s)
                now = time.monotonic()
                for pool in self._leases.values():
                    # Partition synchronously FIRST: once an idle worker
                    # leaves pool["workers"], _lease_for can no longer
                    # hand it to a new task — only then is it safe to
                    # await the release RPC (an await here with the
                    # worker still visible let a fresh direct task race
                    # the connection close).
                    keep, to_release = [], []
                    for w in pool["workers"]:
                        if w["conn"]._closed:
                            continue
                        if (w["outstanding"] == 0 and now - w["last_used"]
                                > get_config().direct_lease_idle_release_s):
                            to_release.append(w)
                        else:
                            keep.append(w)
                    pool["workers"] = keep
                    for w in to_release:
                        await self._release_lease(w)
        except asyncio.CancelledError:
            pass

    def _on_raylet_push(self, channel: str, payload):
        if channel == "lease_revoked":
            wid = (payload or {}).get("worker_id")
            for pool in self._leases.values():
                for w in list(pool["workers"]):
                    if w["worker_id"] == wid:
                        # Out of the pool first so no new task can pick
                        # it; in-flight calls on it finish normally.
                        pool["workers"].remove(w)
                        spawn(self._return_revoked_lease(w))

    async def _return_revoked_lease(self, w):
        """A draining raylet revoked this lease: the worker is already
        out of the pool (no new tasks route to it); wait out its
        outstanding direct calls, then hand it back so the node can
        empty. Resubmissions go through the raylet submit path, which
        spills off the draining node."""
        deadline = time.monotonic() + 60.0
        while (w["outstanding"] > 0 and not w["conn"]._closed
               and time.monotonic() < deadline):
            await asyncio.sleep(0.05)
        await self._release_lease(w)

    async def _release_lease(self, w):
        try:
            await self.raylet.call(
                "release_lease", {"worker_id": w["worker_id"]},
                timeout=get_config().lease_rpc_timeout_s
            )
        except Exception:  # noqa: BLE001
            pass
        try:
            await w["conn"].close()
        except Exception:  # noqa: BLE001
            pass

    async def _release_all_leases(self):
        for pool in self._leases.values():
            for w in pool["workers"]:
                await self._release_lease(w)
            pool["workers"] = []

    async def _submit_with_retries(self, spec, futures, retries):
        attempt = 0
        refusals = 0
        if spec.get("sampled") and self._lc_pending:
            self._lc_close_submit_buffer(spec)
        while True:
            rpc_t = (
                time.monotonic()
                if spec.get("sampled") and self._lc_pending else None
            )
            try:
                result = await self.raylet.call("submit_task", spec, timeout=None)
            except ConnectionLost:
                result = {"status": "worker_crashed", "error": "raylet connection lost"}
            if rpc_t is not None:
                self._lc_stamp_rpc_wait(spec["task_id"], rpc_t)
            status = result.get("status")
            if result.get("not_executed") and refusals < 100:
                # Refused before running (a worker retiring under
                # max_calls): resubmission is free — nothing executed —
                # so it does not consume a retry (separate counter; the
                # cap only bounds a pathological refuse-forever loop).
                refusals += 1
                await asyncio.sleep(min(0.05 * refusals, 0.5))
                continue
            # max_retries=-1 = retry worker crashes forever (reference
            # semantics; data tasks are idempotent and use it).
            if status == "worker_crashed" and (
                retries < 0 or attempt < retries
            ):
                attempt += 1
                await asyncio.sleep(min(0.1 * attempt, 1.0))
                continue
            self._complete_task(spec, result, futures)
            return

    def _complete_task(self, spec, result, futures):
        self._release_borrows(spec)
        if spec.get("sampled") and self._lc_pending:
            self._lc_complete(spec)
        status = result.get("status")
        if status == "ok" and result.get("generator"):
            # Dynamic-generator task: items already live in the store
            # under (task_id, i) oids; the future resolves to the count.
            futures[0].set_result(("__gen__", result["num_items"]))
            return
        if status == "ok":
            for i, entry in enumerate(result["returns"]):
                oid = object_id_for_task(TaskID(spec["task_id"]), i).binary()
                if entry["kind"] == "inline":
                    try:
                        value = ser.deserialize_from_bytes(entry["data"])
                    except Exception as e:  # noqa: BLE001
                        futures[i].set_exception(
                            TaskError(type(e).__name__, f"result deserialization failed: {e}")
                        )
                        continue
                    self._memory_store_put(oid, value)
                    futures[i].set_result(value)
                else:  # in the shared store
                    self._in_store.add(oid)
                    self._owned_store_oids.add(oid)
                    self.lineage[oid] = spec
                    while len(self.lineage) > self.lineage_max_entries:
                        self.lineage.popitem(last=False)
                    futures[i].set_result(_IN_STORE)
                    if oid not in self.known_refs:
                        # The caller dropped the ref before completion: the
                        # finalizer already fired, so free the result now.
                        with self._free_lock:
                            self._free_queue.append(oid)
                        self._ensure_free_flush()
        elif status == "error":
            err = _rebuild_task_error(result)
            for f in futures:
                if not f.done():
                    f.set_exception(err)
        else:
            err = WorkerCrashedError(result.get("error", "worker crashed"))
            for f in futures:
                if not f.done():
                    f.set_exception(err)

    # -- actors ------------------------------------------------------------
    def create_actor(
        self,
        cls,
        args,
        kwargs,
        name: Optional[str] = None,
        namespace: str = "",
        resources: Optional[Dict[str, float]] = None,
        max_restarts: int = 0,
        max_task_retries: int = 0,
        max_concurrency: int = 1,
        scheduling=None,
        detached: bool = False,
        runtime_env=None,
        priority: int = 0,
    ) -> ActorHandle:
        cls_key = self.fn_manager.export(cls)
        payload, deps, borrow_oids = self.serialize_args(args, kwargs)
        actor_id = ActorID.from_random()
        # Constructor args (top-level AND nested refs) stay pinned until
        # the actor leaves PENDING/RESTARTING — creation may start long
        # after the driver dropped its handles.
        ctor_spec = {"task_id": actor_id.binary()}
        self._borrow_deps(ctor_spec, borrow_oids)
        if borrow_oids:
            asyncio.run_coroutine_threadsafe(
                self._release_ctor_borrows_when_live(
                    actor_id, ctor_spec, max_restarts
                ),
                self.loop,
            )
        resolved_env = self._resolve_runtime_env(runtime_env)
        create_spec = {
            "actor_id": actor_id.binary(),
            "cls_key": cls_key,
            "args": payload,
            "deps": deps,
            "max_concurrency": max_concurrency,
            "runtime_env": resolved_env,
        }
        reg_payload = {
            "actor_id": actor_id.binary(),
            "name": name,
            "namespace": namespace,
            "class_name": getattr(cls, "__name__", str(cls)),
            "job_id": self.job_id.binary(),
            "resources": resources if resources is not None else {"CPU": 1.0},
            "max_restarts": max_restarts,
            "create_spec": create_spec,
            "detached": detached,
            "scheduling": scheduling,
            "priority": int(priority),
            "subscribe": True,  # bundle the actor_update sub
        }
        if name:
            # Named actors keep the synchronous duplicate-name check
            # (reference: .remote() raises ValueError on a taken name).
            resp = self._run(self._gcs_call("register_actor", reg_payload))
            if not resp.get("ok"):
                raise ValueError(resp.get("error", "actor registration failed"))
        else:
            # Unnamed: registration pipelines — the handle returns
            # immediately and a burst of creations overlaps GCS
            # scheduling/forking with the driver's loop (reference: actor
            # creation is asynchronous, gcs_actor_manager.cc). Failures
            # surface as DEAD on the first call.
            async def _register():
                try:
                    resp = await self._gcs_call("register_actor", reg_payload)
                    err = None if resp.get("ok") else resp.get(
                        "error", "actor registration failed")
                except Exception as e:  # noqa: BLE001
                    err = f"{type(e).__name__}: {e}"
                if err is not None:
                    self._actor_cache[actor_id.binary()] = {
                        "actor_id": actor_id.binary(),
                        "state": "DEAD",
                        "address": None, "port": None, "node_id": None,
                        "name": None, "namespace": namespace,
                        "class_name": reg_payload["class_name"],
                        "death_cause": err, "restarts_used": 0,
                        "methods": [],
                    }
                    ev = self._actor_events.get(actor_id.binary())
                    if ev is not None:
                        ev.set()

            asyncio.run_coroutine_threadsafe(_register(), self.loop)
        self._subscribed_channels.add("actor_update:" + actor_id.hex())
        method_names = [
            m
            for m in dir(cls)
            if callable(getattr(cls, m, None)) and not m.startswith("__")
        ]
        return ActorHandle(
            actor_id,
            getattr(cls, "__name__", str(cls)),
            method_names,
            max_task_retries,
        )

    def _actor_info(self, actor_id: ActorID, wait_alive_timeout: float = 30.0) -> dict:
        aid = actor_id.binary()
        info = self._actor_cache.get(aid)
        if info is None or info["state"] not in ("ALIVE", "DEAD"):
            info = self._run(self._gcs_call("get_actor", {"actor_id": aid}))["actor"]
            if info is not None:
                self._actor_cache[aid] = info
        if info is None:
            # Pipelined (unnamed) registration may still be in flight:
            # poll briefly before declaring the actor unknown.
            reg_deadline = time.monotonic() + get_config().actor_register_wait_s
            while info is None and time.monotonic() < reg_deadline:
                time.sleep(0.02)
                info = self._actor_cache.get(aid) or self._run(
                    self._gcs_call("get_actor", {"actor_id": aid})
                )["actor"]
            if info is not None:
                self._actor_cache[aid] = info
        if info is None:
            raise ActorDiedError(f"unknown actor {actor_id.hex()}")
        deadline = time.monotonic() + wait_alive_timeout
        while info["state"] in ("PENDING", "RESTARTING"):
            ev = self._actor_events.setdefault(aid, threading.Event())
            ev.clear()
            self._run(
                self._gcs_call("subscribe", {"channel": "actor_update:" + actor_id.hex()})
            )
            info = self._run(self._gcs_call("get_actor", {"actor_id": aid}))["actor"]
            self._actor_cache[aid] = info
            if info["state"] not in ("PENDING", "RESTARTING"):
                break
            if not ev.wait(timeout=max(0.05, deadline - time.monotonic())):
                if time.monotonic() >= deadline:
                    raise ActorUnavailableError(
                        f"actor {actor_id.hex()} not ready after {wait_alive_timeout}s"
                    )
            info = self._actor_cache.get(aid) or info
        if info["state"] == "DEAD":
            raise ActorDiedError(
                f"actor {actor_id.hex()} is dead: {info.get('death_cause')}"
            )
        return info

    def actor_raw_call(self, actor_id, method: str, payload,
                       timeout: float = 30.0):
        """Low-level RPC to the worker hosting an actor (compiled-DAG
        control: dag_start/dag_stop)."""
        if isinstance(actor_id, (bytes, bytearray)):
            actor_id = ActorID(actor_id)
        info = self._actor_info(actor_id)
        conn = self._actor_conn(info)
        return self._run(conn.call(method, payload, timeout=None), timeout=timeout)

    def _actor_conn(self, info) -> Connection:
        key = (info["address"], info["port"])
        conn = self._actor_conns.get(key)
        if conn is None or conn._closed:
            conn = self._run(connect_coro(self.loop, info["address"], info["port"]))
            self._actor_conns[key] = conn
        return conn

    def submit_actor_call(
        self,
        actor_id: ActorID,
        method: str,
        args,
        kwargs,
        num_returns: int = 1,
        max_task_retries: int = 0,
    ) -> List[ObjectRef]:
        lc_sampled = lifecycle.enabled and lifecycle.sample()
        if lc_sampled:
            _lc_t0, _lc_ts0 = time.monotonic(), time.time()
        payload, deps, borrow_oids = self.serialize_args(args, kwargs)
        if lc_sampled:
            _lc_ser = time.monotonic() - _lc_t0
        task_id = TaskID.from_random()
        request = {
            "actor_id": actor_id.binary(),
            "task_id": task_id.binary(),
            "method": method,
            "args": payload,
            "deps": deps,
            "caller": self.client_id,
            "num_returns": num_returns,
        }
        if lc_sampled:
            request["sampled"] = True
        from ray_tpu.util import tracing

        trace_ctx = tracing.inject()
        if trace_ctx:
            request["trace_ctx"] = trace_ctx
        if num_returns == "dynamic":
            # Streaming generator actor method (same contract as dynamic
            # tasks: items store under (task_id, i) as yielded).
            fut = concurrent.futures.Future()
            refs, futures = [ObjectRefGenerator(task_id, fut, self)], [fut]
        else:
            refs, futures = [], []
            for i in range(num_returns):
                oid = object_id_for_task(task_id, i)
                fut = concurrent.futures.Future()
                ref = ObjectRef(oid, fut)
                self.known_refs[oid.binary()] = ref
                self._track_owned_ref(ref)
                refs.append(ref)
                futures.append(fut)
        spec = {"task_id": task_id.binary()}
        self._borrow_deps(spec, borrow_oids)
        if lc_sampled:
            spec["sampled"] = True
            self._lc_track(task_id.binary(), f"{method}()", _lc_t0,
                           _lc_ts0, _lc_ser, refs)
        # Same burst batching as plain tasks: one thread->loop crossing
        # per burst of .remote() calls, not one per call.
        with self._submit_lock:
            self._submit_buf.append(
                ("actor", actor_id, request, spec, futures, max_task_retries)
            )
            need_schedule = not self._submit_scheduled
            if need_schedule:
                self._submit_scheduled = True
        if need_schedule:
            self.loop.call_soon_threadsafe(self._drain_submits)
        return refs

    async def _actor_conn_for_call(self, actor_id) -> Connection:
        """Resolve the connection to an actor's worker. Cached-ALIVE is the
        hot path and stays on the loop; only the blocking wait-for-ALIVE
        resolution hops to a thread."""
        info = self._actor_cache.get(actor_id.binary())
        if info is None or info["state"] != "ALIVE":
            info = await asyncio.get_event_loop().run_in_executor(
                None, self._actor_info, actor_id
            )
        key = (info["address"], info["port"])
        conn = self._actor_conns.get(key)
        if conn is None or conn._closed:
            conn = await connect(info["address"], info["port"])
            self._actor_conns[key] = conn
        return conn

    @staticmethod
    def _conn_actor_seqs(conn, actor_id_b: bytes):
        # Counters live on the Connection object itself: their lifetime is
        # exactly the connection's, so a restarted actor (new connection)
        # always restarts seq at 0 and a recycled id() can never resurrect
        # a stale counter.
        seqs = getattr(conn, "_rt_actor_seq", None)
        if seqs is None:
            seqs = conn._rt_actor_seq = {}
        return seqs.setdefault(actor_id_b, itertools.count())

    async def _actor_call_group(self, calls):
        """Send a burst of pipelined calls to one actor as batch frames:
        seqs are assigned contiguously under the actor lock, the receiver
        executes the run in order with one executor hop per batch."""
        batch_max = get_config().actor_call_batch_max
        actor_id = calls[0][0]
        lock = self._actor_locks.setdefault(actor_id.binary(), asyncio.Lock())
        i = 0
        while i < len(calls):
            chunk = calls[i:i + batch_max]
            i += batch_max
            if self._lc_pending:
                for _, _, _spec, _, _ in chunk:
                    self._lc_close_submit_buffer(_spec)
            try:
                async with lock:
                    conn = await self._actor_conn_for_call(actor_id)
                    counter = self._conn_actor_seqs(conn, actor_id.binary())
                    for _, request, _, _, _ in chunk:
                        request["seq"] = next(counter)
                    call_task = asyncio.ensure_future(conn.call(
                        "actor_call_batch",
                        {"calls": [c[1] for c in chunk]},
                        timeout=None,
                    ))
                resp = await call_task
            except (ConnectionLost, OSError):
                # Actor may be restarting: fall back to the per-call retry
                # machinery, which re-resolves the actor and burns one
                # attempt for the loss we just observed. retries==0 calls
                # may already have executed — at-most-once forbids a resend.
                self._actor_cache.pop(actor_id.binary(), None)
                err = ActorUnavailableError(
                    f"actor {actor_id.hex()} connection lost"
                )
                for aid, request, spec, futures, retries in chunk:
                    if retries == 0:
                        self._release_borrows(spec)
                        for f in futures:
                            if not f.done():
                                f.set_exception(err)
                        continue
                    request.pop("seq", None)
                    spawn(self._actor_call_with_retries(
                        aid, request, spec, futures,
                        retries - 1 if retries > 0 else retries))
                continue
            except (ActorDiedError, ActorUnavailableError) as e:
                for _, _, spec, futures, _ in chunk:
                    self._release_borrows(spec)
                    for f in futures:
                        if not f.done():
                            f.set_exception(e)
                continue
            except BaseException as e:  # noqa: BLE001
                for _, _, spec, futures, _ in chunk:
                    self._release_borrows(spec)
                    for f in futures:
                        if not f.done():
                            f.set_exception(e)
                continue
            for (_, _, spec, futures, _), result in zip(chunk, resp["results"]):
                self._complete_task(spec, result, futures)

    async def _actor_call_with_retries(self, actor_id, request, spec, futures, retries):
        """Send an ordered actor call, retrying across restarts.

        Sequence numbers are assigned at *send* time under a per-actor lock
        and keyed by the connection instance, so a restarted actor (fresh
        receiver queue) sees a fresh sequence starting at 0 — the client
        side of the reference's SequentialActorSubmitQueue contract.
        """
        attempt = 0
        lock = self._actor_locks.setdefault(actor_id.binary(), asyncio.Lock())
        if request.get("sampled") and self._lc_pending:
            self._lc_close_submit_buffer(spec)
        while True:
            rpc_t = (
                time.monotonic()
                if request.get("sampled") and self._lc_pending else None
            )
            try:
                async with lock:
                    conn = await self._actor_conn_for_call(actor_id)
                    counter = self._conn_actor_seqs(conn, actor_id.binary())
                    request["seq"] = next(counter)
                    # Start the call inside the lock so the write order on
                    # the connection matches seq order; await outside.
                    call_task = asyncio.ensure_future(
                        conn.call("actor_call", request, timeout=None)
                    )
                result = await call_task
                if rpc_t is not None:
                    self._lc_stamp_rpc_wait(request["task_id"], rpc_t)
            except (ConnectionLost, OSError):
                self._actor_cache.pop(actor_id.binary(), None)
                if attempt < retries:
                    attempt += 1
                    cfg = get_config()
                    await asyncio.sleep(min(
                        cfg.actor_retry_backoff_s * attempt,
                        cfg.actor_retry_backoff_max_s,
                    ))
                    continue
                self._release_borrows(spec)
                err = ActorUnavailableError(
                    f"actor {actor_id.hex()} connection lost"
                )
                for f in futures:
                    if not f.done():
                        f.set_exception(err)
                return
            except (ActorDiedError, ActorUnavailableError) as e:
                self._release_borrows(spec)
                for f in futures:
                    if not f.done():
                        f.set_exception(e)
                return
            except BaseException as e:  # noqa: BLE001
                self._release_borrows(spec)
                for f in futures:
                    if not f.done():
                        f.set_exception(e)
                return
            self._complete_task(spec, result, futures)
            return

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self._run(
            self._gcs_call(
                "kill_actor",
                {"actor_id": actor_id.binary(), "no_restart": no_restart},
            )
        )

    def get_actor_by_name(self, name: str, namespace: str = "") -> ActorHandle:
        info = self._run(
            self._gcs_call("get_named_actor", {"name": name, "namespace": namespace})
        )["actor"]
        if info is None or info["state"] == "DEAD":
            raise ValueError(f"no live actor named {name!r}")
        aid = ActorID(info["actor_id"])
        self._actor_cache[aid.binary()] = info
        self._run(self._gcs_call("subscribe", {"channel": "actor_update:" + aid.hex()}))
        # Method names ride the GCS actor record (reported by the hosting
        # worker at actor_ready).
        return ActorHandle(aid, info["class_name"], info.get("methods") or [])

    # -- cluster introspection --------------------------------------------
    def nodes(self) -> List[dict]:
        return self._run(self._gcs_call("get_nodes", {}))["nodes"]

    def cluster_resources(self) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for n in self.nodes():
            if n["state"] != "ALIVE":
                continue
            for k, v in n["resources_total"].items():
                total[k] = total.get(k, 0) + v
        return total

    def available_resources(self) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for n in self.nodes():
            if n["state"] != "ALIVE":
                continue
            for k, v in n["resources_available"].items():
                total[k] = total.get(k, 0) + v
        return total


class _InlineArg:
    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value

    def __reduce__(self):
        return (_InlineArg, (self.value,))


class _StoreArg:
    __slots__ = ("oid",)

    def __init__(self, oid: bytes):
        self.oid = oid

    def __reduce__(self):
        return (_StoreArg, (self.oid,))


def _rebuild_task_error(result) -> TaskError:
    cause = None
    if result.get("data"):
        try:
            cause = cloudpickle.loads(result["data"])
        except Exception:  # noqa: BLE001
            cause = None
    # Raylet-originated errors carry a plain "error" string rather than a
    # worker traceback — surface it instead of an empty message.
    return TaskError(
        result.get("cls", "Exception"),
        result.get("tb") or result.get("error", ""),
        cause,
    )


async def connect_coro(loop, host, port):
    return await connect(host, port)


def make_task_error(exc: BaseException) -> dict:
    import traceback

    try:
        data = cloudpickle.dumps(exc)
    except Exception:  # noqa: BLE001
        data = None
    return {
        "status": "error",
        "cls": type(exc).__name__,
        "tb": "".join(traceback.format_exception(type(exc), exc, exc.__traceback__)),
        "data": data,
    }


# ---------------------------------------------------------------------------
# Global state (reference: python/ray/_private/worker.py global_worker)
# ---------------------------------------------------------------------------

_global_client: Optional[CoreClient] = None
_global_node = None  # the in-process Node when this process started the cluster
_mode: Optional[str] = None  # "driver" | "worker" | "local"
_local_state = None


def get_client() -> CoreClient:
    if _global_client is None:
        raise RuntimeError("ray_tpu is not initialized; call ray_tpu.init() first")
    return _global_client


def get_client_or_none() -> Optional[CoreClient]:
    return _global_client


def set_client(client: Optional[CoreClient], mode: Optional[str], node=None):
    global _global_client, _mode, _global_node
    _global_client = client
    _mode = mode
    _global_node = node


def is_initialized() -> bool:
    return _global_client is not None or _mode == "local"


def get_mode() -> Optional[str]:
    return _mode
