"""DQN: deep Q-learning with replay and a target network.

Analog of the reference's DQN (rllib/algorithms/dqn/) on the new-API
shape: TransitionEnvRunner actors collect epsilon-greedy transitions into
a ReplayBuffer, the LearnerGroup applies Huber TD updates against targets
computed from a periodically-synced target network, and fresh weights
broadcast back to the runners (Algorithm.training_step flow,
algorithm.py:1582).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu as rt
from ray_tpu.rl.algorithms.algorithm import AlgorithmBase, ConfigEvalMixin
from ray_tpu.rl.core.learner_group import LearnerGroup
from ray_tpu.rl.core.rl_module import (
    C51QNetworkModule,
    ConvModuleSpec,
    ConvQNetworkModule,
    DuelingQNetworkModule,
    NoisyQNetworkModule,
    QNetworkModule,
    RLModuleSpec,
    factorized_noise_np,
    filters_for,
)
from ray_tpu.rl.env_runner import TransitionEnvRunner
from ray_tpu.rl.replay import PrioritizedReplayBuffer, ReplayBuffer


def _huber_td(q, batch):
    q_sa = jnp.take_along_axis(
        q, batch["actions"][:, None].astype(jnp.int32), axis=-1
    )[:, 0]
    td = q_sa - batch["targets"]
    huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2, jnp.abs(td) - 0.5)
    if "weights" in batch:
        loss = (batch["weights"] * huber).mean()
    else:
        loss = huber.mean()
    return loss, {
        "total_loss": loss,
        "q_mean": q_sa.mean(),
        "td_abs_mean": jnp.abs(td).mean(),
    }


def dqn_loss(params, module, batch):
    """Huber TD loss against precomputed targets (target-network Q-values
    are computed driver-side so the learner stays a pure
    params+batch -> grads function). With prioritized replay the batch
    carries importance-sampling ``weights`` applied per sample."""
    q = module.forward(params, batch["obs"])["q_values"]
    return _huber_td(q, batch)


def noisy_dqn_loss(params, module, batch):
    """NoisyNet variant: the batch carries one factorized noise draw
    (eps_in/eps_out), so sigma trains through the same pure
    params+batch plumbing (Fortunato et al. 2017)."""
    q = module.forward(
        params, batch["obs"], noise=(batch["eps_in"], batch["eps_out"])
    )["q_values"]
    return _huber_td(q, batch)


def c51_loss(params, module, batch):
    """Categorical cross-entropy against the driver-projected target
    distribution (Bellemare et al. 2017; reference: num_atoms>1 DQN)."""
    logits = module.forward(params, batch["obs"])["q_logits"]
    la = jnp.take_along_axis(
        logits,
        batch["actions"][:, None, None].astype(jnp.int32).repeat(
            logits.shape[-1], axis=-1
        ),
        axis=1,
    )[:, 0]
    logp = jax.nn.log_softmax(la, axis=-1)
    ce = -(batch["target_probs"] * logp).sum(-1)
    if "weights" in batch:
        loss = (batch["weights"] * ce).mean()
    else:
        loss = ce.mean()
    return loss, {"total_loss": loss, "ce_mean": ce.mean()}


def categorical_projection(next_probs: np.ndarray, support: np.ndarray,
                           rewards: np.ndarray, discounts: np.ndarray,
                           dones: np.ndarray) -> np.ndarray:
    """Project the bootstrapped distribution r + disc*(1-d)*z onto the
    fixed support (the C51 projection step, computed driver-side so the
    learner loss stays a pure params+batch function)."""
    v_min, v_max = float(support[0]), float(support[-1])
    dz = (v_max - v_min) / (len(support) - 1)
    B, N = next_probs.shape
    tz = np.clip(
        rewards[:, None]
        + discounts[:, None] * (1.0 - dones[:, None]) * support[None],
        v_min, v_max,
    )
    b = (tz - v_min) / dz
    # Clamp: float rounding can push b past N-1 when tz clips to v_max
    # (e.g. (v_max - v_min)/dz = 94.000000001 -> ceil = 95).
    lo = np.clip(np.floor(b).astype(np.int64), 0, N - 1)
    hi = np.clip(np.ceil(b).astype(np.int64), 0, N - 1)
    # When b lands exactly on an atom (lo == hi) give it the full mass.
    frac_hi = b - lo
    frac_lo = np.where(lo == hi, 1.0, 1.0 - frac_hi)
    out = np.zeros_like(next_probs)
    rows = np.repeat(np.arange(B), N)
    np.add.at(out, (rows, lo.ravel()), (next_probs * frac_lo).ravel())
    np.add.at(out, (rows, hi.ravel()), (next_probs * frac_hi).ravel())
    return out.astype(np.float32)


@dataclass
class DQNConfig(ConfigEvalMixin):
    """Builder-style config (reference: DQNConfig)."""

    env_creator: Optional[Callable] = None
    obs_dim: int = 4
    # Image observations: obs_shape=(H, W, C) -> conv torso Q-network
    # (reference: pixel DQN via catalog conv_filters).
    obs_shape: Optional[tuple] = None
    conv_filters: Optional[tuple] = None
    num_actions: int = 2
    hidden: tuple = (64, 64)
    num_env_runners: int = 2
    rollout_length: int = 100
    connectors_factory: Optional[Callable] = None
    num_learners: int = 1
    lr: float = 1e-3
    gamma: float = 0.99
    buffer_capacity: int = 50_000
    learning_starts: int = 500
    train_batch_size: int = 64
    updates_per_iteration: int = 32
    target_update_freq: int = 2  # iterations between target syncs
    epsilon_start: float = 1.0
    epsilon_end: float = 0.05
    epsilon_decay_iters: int = 20
    seed: int = 0
    # Rainbow-style extensions (reference: DQNConfig double_q / dueling /
    # n_step / replay_buffer_config prioritized fields).
    double_q: bool = True
    dueling: bool = False
    n_step: int = 1
    prioritized_replay: bool = False
    per_alpha: float = 0.6
    per_beta_start: float = 0.4
    per_beta_iters: int = 50  # iterations to anneal beta -> 1.0
    # C51 distributional head (reference: DQNConfig.num_atoms/v_min/v_max).
    distributional: bool = False
    num_atoms: int = 51
    v_min: float = -10.0
    v_max: float = 10.0
    # NoisyNet exploration (reference: DQNConfig.noisy): learned
    # parametric noise on the head replaces epsilon-greedy.
    noisy: bool = False

    def environment(self, env_creator=None, obs_dim=None, num_actions=None,
                    obs_shape=None, conv_filters=None):
        if env_creator is not None:
            self.env_creator = env_creator
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        if obs_shape is not None:
            self.obs_shape = tuple(obs_shape)
        if conv_filters is not None:
            self.conv_filters = tuple(conv_filters)
        return self

    def env_runners(self, num_env_runners=None, rollout_length=None,
                    connectors_factory=None):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if rollout_length is not None:
            self.rollout_length = rollout_length
        if connectors_factory is not None:
            self.connectors_factory = connectors_factory
        return self

    def training(self, lr=None, gamma=None, train_batch_size=None,
                 updates_per_iteration=None, target_update_freq=None,
                 buffer_capacity=None, learning_starts=None,
                 num_learners=None, double_q=None, dueling=None, n_step=None,
                 prioritized_replay=None, per_alpha=None,
                 per_beta_start=None, per_beta_iters=None,
                 distributional=None, num_atoms=None, v_min=None,
                 v_max=None, noisy=None):
        for name, val in (
            ("lr", lr), ("gamma", gamma),
            ("train_batch_size", train_batch_size),
            ("updates_per_iteration", updates_per_iteration),
            ("target_update_freq", target_update_freq),
            ("buffer_capacity", buffer_capacity),
            ("learning_starts", learning_starts),
            ("num_learners", num_learners),
            ("double_q", double_q), ("dueling", dueling), ("n_step", n_step),
            ("prioritized_replay", prioritized_replay),
            ("per_alpha", per_alpha), ("per_beta_start", per_beta_start),
            ("per_beta_iters", per_beta_iters),
            ("distributional", distributional), ("num_atoms", num_atoms),
            ("v_min", v_min), ("v_max", v_max), ("noisy", noisy),
        ):
            if val is not None:
                setattr(self, name, val)
        return self

    def exploration(self, epsilon_start=None, epsilon_end=None,
                    epsilon_decay_iters=None):
        for name, val in (
            ("epsilon_start", epsilon_start),
            ("epsilon_end", epsilon_end),
            ("epsilon_decay_iters", epsilon_decay_iters),
        ):
            if val is not None:
                setattr(self, name, val)
        return self

    def build(self) -> "DQN":
        return DQN(self)


class DQN(AlgorithmBase):
    """The algorithm object (reference: Algorithm; train() = one iteration)."""

    def __init__(self, config: DQNConfig):
        assert config.env_creator is not None, "config.environment(...) first"
        self.config = config
        spec = RLModuleSpec(config.obs_dim, config.num_actions, config.hidden)
        if sum((config.distributional, config.dueling, config.noisy)) > 1:
            raise ValueError(
                "distributional / dueling / noisy heads are not composed; "
                "pick one head structure"
            )
        if config.obs_shape is not None:
            if config.distributional or config.dueling or config.noisy:
                raise ValueError(
                    "image observations use the conv Q-network; "
                    "distributional/dueling/noisy heads are MLP-only here"
                )
            conv_spec = ConvModuleSpec(
                config.obs_shape, config.num_actions,
                conv_filters=filters_for(config.obs_shape,
                                         config.conv_filters),
                hidden=config.hidden[-1:] or (64,),
            )
            module_factory = self._module_factory = (  # noqa: E731
                lambda: ConvQNetworkModule(conv_spec)
            )
            loss = dqn_loss
        elif config.distributional:
            if config.num_atoms < 2:
                raise ValueError("distributional DQN needs num_atoms >= 2")
            module_factory = self._module_factory = (  # noqa: E731
                lambda: C51QNetworkModule(
                    spec, config.num_atoms, config.v_min, config.v_max
                )
            )
            loss = c51_loss
        elif config.noisy:
            module_factory = self._module_factory = (  # noqa: E731
                lambda: NoisyQNetworkModule(spec)
            )
            loss = noisy_dqn_loss
        else:
            cls = DuelingQNetworkModule if config.dueling else QNetworkModule
            module_factory = self._module_factory = lambda: cls(spec)  # noqa: E731
            loss = dqn_loss
        self.module = module_factory()

        self.learner_group = LearnerGroup(
            module_factory,
            loss,
            num_learners=config.num_learners,
            seed=config.seed,
            lr=config.lr,
        )
        self.buffer = self._make_buffer()
        self.env_runners = [
            TransitionEnvRunner.options(num_cpus=0.5).remote(
                config.env_creator,
                module_factory,
                seed=config.seed + 1 + i,
                rollout_length=config.rollout_length,
                gamma=config.gamma,
                n_step=config.n_step,
                connectors=(
                    config.connectors_factory()
                    if config.connectors_factory else None
                ),
            )
            for i in range(config.num_env_runners)
        ]
        # Driver-side copies: target net + the online params used for
        # double-DQN argmax and PER priority refresh (synced once per
        # iteration — the same one-iteration staleness the reference's
        # async variants accept).
        self.target_params = self.learner_group.get_weights()
        self._online_params = self.target_params
        self._fwd = jax.jit(lambda p, obs: self.module.forward(p, obs))
        self._target_q = lambda p, obs: self._fwd(p, obs)["q_values"]
        self._np_rng = np.random.default_rng(config.seed + 31)
        self._iteration = 0
        self._broadcast_weights()

    def _broadcast_weights(self, weights=None):
        if weights is None:
            weights = self.learner_group.get_weights()
        rt.get([r.set_weights.remote(weights) for r in self.env_runners],
               timeout=300)

    def _checkpoint_extra_state(self):
        return {
            "target_params": jax.device_get(self.target_params),
            "online_params": jax.device_get(self._online_params),
        }

    def _restore_extra_state(self, extra):
        if "target_params" in extra:
            self.target_params = extra["target_params"]
        if "online_params" in extra:
            self._online_params = extra["online_params"]

    def _epsilon(self) -> float:
        cfg = self.config
        if cfg.noisy:
            return 0.0  # exploration is the head's learned noise
        frac = min(1.0, self._iteration / max(cfg.epsilon_decay_iters, 1))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def _per_beta(self) -> float:
        cfg = self.config
        frac = min(1.0, self._iteration / max(cfg.per_beta_iters, 1))
        return cfg.per_beta_start + frac * (1.0 - cfg.per_beta_start)

    # -- replay interface (overridden by APEX's sharded replay actors) ----
    def _make_buffer(self):
        config = self.config
        buffer_cls = (
            PrioritizedReplayBuffer if config.prioritized_replay
            else ReplayBuffer
        )
        buffer_kwargs = dict(seed=config.seed, store_discounts=True)
        if config.prioritized_replay:
            buffer_kwargs["alpha"] = config.per_alpha
        return buffer_cls(
            config.buffer_capacity,
            config.obs_shape if config.obs_shape is not None
            else config.obs_dim,
            **buffer_kwargs,
        )

    def _collect(self, eps: float):
        rollouts = rt.get(
            [r.sample.remote(eps) for r in self.env_runners], timeout=600
        )
        for b in rollouts:
            self.buffer.add_batch(b)

    def _buffer_size(self) -> int:
        return len(self.buffer)

    def _sample_minibatch(self, beta: float):
        if self.config.prioritized_replay:
            return self.buffer.sample(self.config.train_batch_size, beta=beta)
        return self.buffer.sample(self.config.train_batch_size)

    def _update_priorities(self, mb, td_abs: np.ndarray):
        self.buffer.update_priorities(mb["indices"], td_abs)

    def _episode_stats(self):
        return rt.get(
            [r.episode_stats.remote() for r in self.env_runners], timeout=300
        )

    def _report_epsilon(self, eps: float):
        """What the 'epsilon' result key reports (APEX overrides: its
        runners keep a fixed exploration ladder, not this schedule)."""
        return eps

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        eps = self._epsilon()
        # 1. parallel epsilon-greedy collection into the replay buffer
        self._collect(eps)
        metrics: Dict[str, float] = {}
        # 2. TD updates once the buffer warms up
        if self._buffer_size() >= cfg.learning_starts:
            beta = self._per_beta()
            # Hard target sync BEFORE the update loop, from the pre-loop
            # online snapshot; _online_params then refreshes from the
            # learner mid-loop so the double-DQN argmax net trains away
            # from the frozen target instead of mirroring it all
            # iteration.
            if self._iteration % cfg.target_update_freq == 0:
                self.target_params = self._online_params
            refresh = max(1, cfg.updates_per_iteration // 4)
            for u in range(cfg.updates_per_iteration):
                if u and u % refresh == 0 and (
                    cfg.double_q or cfg.prioritized_replay
                ):
                    self._online_params = self.learner_group.get_weights()
                mb = self._sample_minibatch(beta)
                if mb is None:  # sharded replay still warming up
                    continue
                B = len(mb["obs"])
                out_t = self._fwd(self.target_params, mb["next_obs"])
                next_q_t = np.asarray(out_t["q_values"])
                # One fused online-net forward serves both the double-DQN
                # argmax (next_obs half) and the PER priority refresh
                # (obs half).
                if cfg.double_q or cfg.prioritized_replay:
                    q_on = np.asarray(self._target_q(
                        self._online_params,
                        np.concatenate([mb["obs"], mb["next_obs"]]),
                    ))
                    q_on_obs, q_on_next = q_on[:B], q_on[B:]
                if cfg.double_q:
                    # Double DQN: online net picks the action, target net
                    # evaluates it (van Hasselt 2016).
                    a_star = q_on_next.argmax(axis=-1)
                else:
                    a_star = next_q_t.argmax(axis=-1)
                if cfg.distributional:
                    # C51: project the bootstrapped distribution of the
                    # chosen next action onto the fixed support.
                    next_probs = np.asarray(out_t["q_probs"])[
                        np.arange(B), a_star
                    ]
                    target_probs = categorical_projection(
                        next_probs, np.asarray(self.module.support),
                        mb["rewards"], mb["discounts"], mb["dones"],
                    )
                    targets = (
                        target_probs * np.asarray(self.module.support)
                    ).sum(-1)  # scalar expectations, for PER priorities
                    batch = {
                        "obs": mb["obs"],
                        "actions": mb["actions"],
                        "target_probs": target_probs,
                    }
                else:
                    next_val = np.take_along_axis(
                        next_q_t, a_star[:, None], axis=-1
                    )[:, 0]
                    targets = mb["rewards"] + mb["discounts"] * (
                        1.0 - mb["dones"]
                    ) * next_val
                    batch = {
                        "obs": mb["obs"],
                        "actions": mb["actions"],
                        "targets": targets.astype(np.float32),
                    }
                if cfg.noisy:
                    # One fresh factorized draw per update: sigma trains
                    # against real noise, actions decorrelate per batch.
                    width = self._online_params["mu_w"].shape[0]
                    batch["eps_in"], batch["eps_out"] = factorized_noise_np(
                        self._np_rng, width, cfg.num_actions
                    )
                if cfg.prioritized_replay:
                    batch["weights"] = mb["weights"]
                    q_sa = np.take_along_axis(
                        q_on_obs,
                        mb["actions"][:, None].astype(np.int64), axis=-1,
                    )[:, 0]
                    self._update_priorities(mb, np.abs(q_sa - targets))
                metrics = self.learner_group.update_from_batch(batch)
            # 3. runner weight broadcast (the fetch also refreshes the
            # online snapshot for the next iteration's sync).
            weights = self.learner_group.get_weights()
            self._online_params = weights
            self._broadcast_weights(weights)
        self._iteration += 1
        stats = self._episode_stats()
        returns = [s["mean_return"] for s in stats if s["episodes"] > 0]
        return self._finish_iteration({
            "training_iteration": self._iteration,
            "episode_return_mean": float(np.mean(returns)) if returns else 0.0,
            "episodes_total": sum(s["episodes"] for s in stats),
            "epsilon": self._report_epsilon(eps),
            "buffer_size": self._buffer_size(),
            **{f"learner/{k}": v for k, v in metrics.items()},
        })

    def stop(self):
        self.stop_eval_runners()
        self.learner_group.shutdown()
        for r in self.env_runners:
            try:
                rt.kill(r)
            except Exception:
                pass
