"""SAC: soft actor-critic for continuous control.

Reference analog: rllib/algorithms/sac (SACConfig/SAC + sac_learner's
three-part update). The whole update — twin-critic TD loss against soft
targets, reparameterized actor loss, automatic entropy temperature, and
polyak target sync — is ONE jitted function over a state pytree, so on a
TPU learner actor it compiles to a single device program per step (the
reference splits it across torch optimizers and host-side polyak copies).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu as rt
from ray_tpu.rl.algorithms.algorithm import AlgorithmBase, ConfigEvalMixin
from ray_tpu.rl.core.rl_module import (
    ContinuousModuleSpec,
    ContinuousPolicyModule,
)
from ray_tpu.rl.env_runner import ContinuousTransitionRunner
from ray_tpu.rl.replay import ReplayBuffer


def make_sac_update(module: ContinuousPolicyModule, pi_tx, q_tx, alpha_tx,
                    gamma: float, tau: float, target_entropy: float):
    """Builds the jitted SAC update: state pytree in, state pytree out."""

    def update(state, batch, rng):
        params, target = state["params"], state["target"]
        log_alpha = state["log_alpha"]
        alpha = jnp.exp(log_alpha)
        k_next, k_pi = jax.random.split(rng)

        # -- twin critic loss against the soft target ---------------------
        next_a, next_logp = module.sample_with_logp(
            params, batch["next_obs"], k_next
        )
        tq1, tq2 = module.q_values(
            {**params, "q1": target["q1"], "q2": target["q2"]},
            batch["next_obs"], next_a,
        )
        soft_next = jnp.minimum(tq1, tq2) - alpha * next_logp
        td_target = jax.lax.stop_gradient(
            batch["rewards"] + gamma * (1.0 - batch["dones"]) * soft_next
        )

        def q_loss_fn(qp):
            q1, q2 = module.q_values(
                {**params, "q1": qp["q1"], "q2": qp["q2"]},
                batch["obs"], batch["actions"],
            )
            return ((q1 - td_target) ** 2).mean() + (
                (q2 - td_target) ** 2
            ).mean()

        qp = {"q1": params["q1"], "q2": params["q2"]}
        q_loss, q_grads = jax.value_and_grad(q_loss_fn)(qp)
        q_updates, q_opt = q_tx.update(q_grads, state["q_opt"], qp)
        qp = optax.apply_updates(qp, q_updates)

        # -- actor loss (reparameterized, against the UPDATED critics) ----
        def pi_loss_fn(pi_params):
            a, logp = module.sample_with_logp(
                {**params, "pi": pi_params}, batch["obs"], k_pi
            )
            q1, q2 = module.q_values(
                {**params, **qp}, batch["obs"], a
            )
            return (alpha * logp - jnp.minimum(q1, q2)).mean(), logp

        (pi_loss, logp), pi_grads = jax.value_and_grad(
            pi_loss_fn, has_aux=True
        )(params["pi"])
        pi_updates, pi_opt = pi_tx.update(pi_grads, state["pi_opt"],
                                          params["pi"])
        pi_params = optax.apply_updates(params["pi"], pi_updates)

        # -- automatic temperature ---------------------------------------
        def alpha_loss_fn(la):
            return -(
                jnp.exp(la)
                * jax.lax.stop_gradient(logp + target_entropy)
            ).mean()

        alpha_loss, a_grad = jax.value_and_grad(alpha_loss_fn)(log_alpha)
        a_update, alpha_opt = alpha_tx.update(
            a_grad, state["alpha_opt"], log_alpha
        )
        log_alpha = optax.apply_updates(log_alpha, a_update)

        # -- polyak target sync ------------------------------------------
        new_target = jax.tree.map(
            lambda t, o: (1.0 - tau) * t + tau * o,
            target, {"q1": qp["q1"], "q2": qp["q2"]},
        )
        new_state = {
            "params": {"pi": pi_params, **qp},
            "target": new_target,
            "log_alpha": log_alpha,
            "pi_opt": pi_opt,
            "q_opt": q_opt,
            "alpha_opt": alpha_opt,
        }
        metrics = {
            "q_loss": q_loss,
            "actor_loss": pi_loss,
            "alpha_loss": alpha_loss,
            "alpha": jnp.exp(log_alpha),
            "entropy": -logp.mean(),
        }
        return new_state, metrics

    return jax.jit(update)


@dataclass
class SACConfig(ConfigEvalMixin):
    """Builder-style config (reference: SACConfig)."""

    env_creator: Optional[Callable] = None
    obs_dim: int = 3
    action_dim: int = 1
    action_low: float = -1.0
    action_high: float = 1.0
    hidden: tuple = (64, 64)
    num_env_runners: int = 1
    rollout_length: int = 200
    buffer_capacity: int = 100_000
    warmup_steps: int = 1_000
    batch_size: int = 128
    updates_per_iteration: int = 200
    lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.005
    target_entropy: Optional[float] = None  # default: -action_dim
    seed: int = 0

    def environment(self, env_creator=None, obs_dim=None, action_dim=None,
                    action_low=None, action_high=None):
        for k, v in (("env_creator", env_creator), ("obs_dim", obs_dim),
                     ("action_dim", action_dim),
                     ("action_low", action_low),
                     ("action_high", action_high)):
            if v is not None:
                setattr(self, k, v)
        return self

    def env_runners(self, num_env_runners=None, rollout_length=None):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if rollout_length is not None:
            self.rollout_length = rollout_length
        return self

    def training(self, lr=None, gamma=None, tau=None, batch_size=None,
                 updates_per_iteration=None, warmup_steps=None,
                 buffer_capacity=None, target_entropy=None):
        for k, v in (("lr", lr), ("gamma", gamma), ("tau", tau),
                     ("batch_size", batch_size),
                     ("updates_per_iteration", updates_per_iteration),
                     ("warmup_steps", warmup_steps),
                     ("buffer_capacity", buffer_capacity),
                     ("target_entropy", target_entropy)):
            if v is not None:
                setattr(self, k, v)
        return self

    def build(self) -> "SAC":
        return SAC(self)


class SAC(AlgorithmBase):
    """Off-policy actor-critic loop: collect -> replay -> jitted updates."""

    def __init__(self, config: SACConfig):
        assert config.env_creator is not None, "config.environment(...) first"
        self.config = config
        spec = ContinuousModuleSpec(
            config.obs_dim, config.action_dim,
            config.action_low, config.action_high, config.hidden,
        )
        self.module = ContinuousPolicyModule(spec)
        module_factory = self._module_factory = lambda s=spec: ContinuousPolicyModule(s)  # noqa: E731

        params = self.module.init(jax.random.PRNGKey(config.seed))
        pi_tx = optax.adam(config.lr)
        q_tx = optax.adam(config.lr)
        alpha_tx = optax.adam(config.lr)
        qp = {"q1": params["q1"], "q2": params["q2"]}
        self.state = {
            "params": params,
            "target": jax.tree.map(lambda x: x, qp),
            "log_alpha": jnp.asarray(0.0),
            "pi_opt": pi_tx.init(params["pi"]),
            "q_opt": q_tx.init(qp),
            "alpha_opt": alpha_tx.init(jnp.asarray(0.0)),
        }
        tgt_ent = (
            config.target_entropy
            if config.target_entropy is not None
            else -float(config.action_dim)
        )
        self._update = make_sac_update(
            self.module, pi_tx, q_tx, alpha_tx,
            config.gamma, config.tau, tgt_ent,
        )
        self.buffer = ReplayBuffer(
            config.buffer_capacity, config.obs_dim, seed=config.seed,
            action_dim=config.action_dim,
        )
        self.env_runners = [
            ContinuousTransitionRunner.options(num_cpus=0.5).remote(
                config.env_creator, module_factory,
                seed=config.seed + 1 + i,
                rollout_length=config.rollout_length,
            )
            for i in range(config.num_env_runners)
        ]
        self._rng = jax.random.PRNGKey(config.seed + 99)
        self._steps_sampled = 0
        self._iteration = 0
        self._broadcast()

    def _broadcast(self):
        weights = jax.device_get(self.state["params"])
        rt.get([r.set_weights.remote(weights) for r in self.env_runners],
               timeout=300)

    # AlgorithmBase state hooks: the whole SAC update state (params,
    # targets, temperature, all three optimizers) is one pytree.
    def _get_learner_state(self):
        return jax.device_get(self.state)

    def _set_learner_state(self, state):
        self.state = jax.tree.map(jnp.asarray, state)

    def _current_weights(self):
        return jax.device_get(self.state["params"])

    def _checkpoint_extra_state(self):
        return {"steps_sampled": self._steps_sampled}

    def _restore_extra_state(self, extra):
        self._steps_sampled = extra.get("steps_sampled", self._steps_sampled)

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        warm = self._steps_sampled < cfg.warmup_steps
        rollouts = rt.get(
            [r.sample.remote(random_actions=warm) for r in self.env_runners],
            timeout=600,
        )
        for b in rollouts:
            self.buffer.add_batch(b)
            self._steps_sampled += len(b["obs"])
        metrics: Dict[str, Any] = {}
        if self._steps_sampled >= cfg.warmup_steps:
            m = None
            for _ in range(cfg.updates_per_iteration):
                batch = {
                    k: jnp.asarray(v)
                    for k, v in self.buffer.sample(cfg.batch_size).items()
                }
                self._rng, key = jax.random.split(self._rng)
                self.state, m = self._update(self.state, batch, key)
            if m is not None:
                metrics = {k: float(v) for k, v in m.items()}
            self._broadcast()
        self._iteration += 1
        stats = rt.get(
            [r.episode_stats.remote() for r in self.env_runners], timeout=300
        )
        returns = [s["mean_return"] for s in stats if s["episodes"] > 0]
        return self._finish_iteration({
            "training_iteration": self._iteration,
            "episode_return_mean": float(np.mean(returns)) if returns else 0.0,
            "episodes_total": sum(s["episodes"] for s in stats),
            "steps_sampled": self._steps_sampled,
            **{f"learner/{k}": v for k, v in metrics.items()},
        })

    def stop(self):
        self.stop_eval_runners()
        for r in self.env_runners:
            try:
                rt.kill(r)
            except Exception:  # noqa: BLE001
                pass
