"""PPO: proximal policy optimization on the new learner stack.

Analog of the reference's PPO (rllib/algorithms/ppo/ppo.py on the new API
stack: Algorithm.training_step samples via env runners, updates via the
LearnerGroup, then broadcasts weights — algorithm.py:1582 flow). Config
uses the builder pattern of AlgorithmConfig (algorithm_config.py:121).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu as rt
from ray_tpu.rl.algorithms.algorithm import AlgorithmBase, ConfigEvalMixin
from ray_tpu.rl.core.learner_group import LearnerGroup
from ray_tpu.rl.core.rl_module import (
    ConvModuleSpec,
    ConvPolicyModule,
    DiscretePolicyModule,
    RLModuleSpec,
    filters_for,
)
from ray_tpu.rl.env_runner import EnvRunner, compute_gae


def clipped_surrogate(out, batch, clip: float = 0.2, vf_coef: float = 0.5,
                      ent_coef: float = 0.01):
    """The PPO clipped-surrogate body shared by the MLP/conv and
    recurrent variants: callers only differ in how `out` (action_logits
    + value) was computed. Works on any leading shape — logits
    [..., A], actions/logp/advantages/returns [...]."""
    logp_all = jax.nn.log_softmax(out["action_logits"])
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    ratio = jnp.exp(logp - batch["logp"])
    adv = batch["advantages"]
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    surr = jnp.minimum(
        ratio * adv, jnp.clip(ratio, 1 - clip, 1 + clip) * adv
    )
    policy_loss = -surr.mean()
    value_loss = ((out["value"] - batch["returns"]) ** 2).mean()
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
    loss = policy_loss + vf_coef * value_loss - ent_coef * entropy
    return loss, {
        "total_loss": loss,
        "policy_loss": policy_loss,
        "vf_loss": value_loss,
        "entropy": entropy,
        "kl": (batch["logp"] - logp).mean(),
    }


def ppo_loss(params, module, batch):
    """Clipped-surrogate PPO loss (standard formulation)."""
    return clipped_surrogate(module.forward(params, batch["obs"]), batch)


def a2c_loss(params, module, batch):
    """Vanilla advantage actor-critic loss (reference:
    rllib/algorithms/a2c/ — synchronous A2C): plain policy gradient on
    normalized GAE advantages, no ratio clipping (the batch is exactly
    on-policy: a single pass over fresh rollouts)."""
    out = module.forward(params, batch["obs"])
    logp_all = jax.nn.log_softmax(out["action_logits"])
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][:, None].astype(jnp.int32), axis=-1
    )[:, 0]
    adv = batch["advantages"]
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    policy_loss = -(logp * adv).mean()
    value_loss = ((out["value"] - batch["returns"]) ** 2).mean()
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
    loss = policy_loss + 0.5 * value_loss - 0.01 * entropy
    return loss, {
        "total_loss": loss,
        "policy_loss": policy_loss,
        "vf_loss": value_loss,
        "entropy": entropy,
    }


@dataclass
class PPOConfig(ConfigEvalMixin):
    """Builder-style config (reference: AlgorithmConfig/PPOConfig)."""

    # The surrogate loss the learner optimizes; A2CConfig swaps in
    # a2c_loss (the DDPG-over-TD3 preset pattern).
    loss_fn: Callable = None  # resolved to ppo_loss in build()

    env_creator: Optional[Callable] = None
    obs_dim: int = 4
    # Image observations: set obs_shape=(H, W, C) and the policy gets a
    # conv torso (the catalog's conv_filters path, reference
    # rllib/models/catalog.py:105; filters auto-sized by resolution
    # unless given explicitly).
    obs_shape: Optional[tuple] = None
    conv_filters: Optional[tuple] = None
    num_actions: int = 2
    hidden: tuple = (64, 64)
    num_env_runners: int = 2
    rollout_length: int = 200
    connectors_factory: Optional[Callable] = None
    num_learners: int = 1
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    num_epochs: int = 4
    minibatch_size: int = 128
    seed: int = 0

    def environment(self, env_creator=None, obs_dim=None, num_actions=None,
                    obs_shape=None, conv_filters=None):
        if env_creator is not None:
            self.env_creator = env_creator
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        if obs_shape is not None:
            self.obs_shape = tuple(obs_shape)
        if conv_filters is not None:
            self.conv_filters = tuple(conv_filters)
        return self

    def env_runners(self, num_env_runners=None, rollout_length=None,
                    connectors_factory=None):
        """connectors_factory: zero-arg callable returning a fresh
        ConnectorPipeline — each runner gets its own instance (stateful
        connectors keep per-runner statistics)."""
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if rollout_length is not None:
            self.rollout_length = rollout_length
        if connectors_factory is not None:
            self.connectors_factory = connectors_factory
        return self

    def training(self, lr=None, num_epochs=None, minibatch_size=None,
                 gamma=None, lambda_=None, num_learners=None):
        for name, val in (
            ("lr", lr), ("num_epochs", num_epochs),
            ("minibatch_size", minibatch_size), ("gamma", gamma),
            ("lambda_", lambda_), ("num_learners", num_learners),
        ):
            if val is not None:
                setattr(self, name, val)
        return self

    def build(self) -> "PPO":
        return PPO(self)


class PPO(AlgorithmBase):
    """The algorithm object (reference: Algorithm, a Tune Trainable —
    train() returns one iteration's metrics)."""

    def __init__(self, config: PPOConfig):
        assert config.env_creator is not None, "config.environment(...) first"
        self.config = config
        if config.obs_shape is not None:
            spec = ConvModuleSpec(
                config.obs_shape, config.num_actions,
                conv_filters=filters_for(config.obs_shape,
                                         config.conv_filters),
                hidden=config.hidden[-1:] or (64,),
            )
            module_factory = self._module_factory = (  # noqa: E731
                lambda: ConvPolicyModule(spec)
            )
        else:
            spec = RLModuleSpec(config.obs_dim, config.num_actions,
                                config.hidden)
            module_factory = self._module_factory = (  # noqa: E731
                lambda: DiscretePolicyModule(spec)
            )

        import optax

        self.learner_group = LearnerGroup(
            module_factory,
            config.loss_fn or ppo_loss,
            num_learners=config.num_learners,
            seed=config.seed,
            lr=config.lr,
        )
        self.env_runners = [
            EnvRunner.options(num_cpus=0.5).remote(
                config.env_creator,
                module_factory,
                seed=config.seed + 1 + i,
                rollout_length=config.rollout_length,
                connectors=(
                    config.connectors_factory()
                    if config.connectors_factory else None
                ),
                gamma=config.gamma,
            )
            for i in range(config.num_env_runners)
        ]
        self._iteration = 0
        self._broadcast_weights()

    def _broadcast_weights(self):
        weights = self.learner_group.get_weights()
        rt.get([r.set_weights.remote(weights) for r in self.env_runners],
               timeout=300)

    def train(self) -> Dict[str, Any]:
        """One training iteration (reference: Algorithm.step :795 /
        training_step :1582)."""
        cfg = self.config
        # 1. parallel rollout collection
        rollouts = rt.get(
            [r.sample.remote() for r in self.env_runners], timeout=600
        )
        processed = [compute_gae(b, cfg.gamma, cfg.lambda_) for b in rollouts]
        batch = {
            k: np.concatenate([p[k] for p in processed])
            for k in ("obs", "actions", "logp", "values", "advantages", "returns")
        }
        # 2. minibatch SGD epochs on the learner group
        from ray_tpu.rl.core.learner import minibatch_epochs

        metrics: Dict[str, float] = minibatch_epochs(
            self.learner_group.update_from_batch, batch,
            cfg.num_epochs, cfg.minibatch_size,
            np.random.default_rng(cfg.seed + self._iteration),
        )
        # 3. broadcast new weights to env runners
        self._broadcast_weights()
        self._iteration += 1
        stats = rt.get(
            [r.episode_stats.remote() for r in self.env_runners], timeout=300
        )
        returns = [s["mean_return"] for s in stats if s["episodes"] > 0]
        return self._finish_iteration({
            "training_iteration": self._iteration,
            "episode_return_mean": float(np.mean(returns)) if returns else 0.0,
            "episodes_total": sum(s["episodes"] for s in stats),
            **{f"learner/{k}": v for k, v in metrics.items()},
        })

    def stop(self):
        self.stop_eval_runners()
        self.learner_group.shutdown()
        for r in self.env_runners:
            try:
                rt.kill(r)
            except Exception:
                pass


@dataclass
class A2CConfig(PPOConfig):
    """Synchronous advantage actor-critic (reference:
    rllib/algorithms/a2c/): the PPO machinery — parallel env runners,
    GAE, learner group — driven by the unclipped policy-gradient loss
    for exactly one pass over each fresh on-policy batch."""

    num_epochs: int = 1  # on-policy: a single pass per batch

    def __post_init__(self):
        if self.loss_fn is None:
            self.loss_fn = a2c_loss
