"""IMPALA: asynchronous actor-learner training with V-trace correction.

Analog of the reference's IMPALA (rllib/algorithms/impala/): env-runner
actors sample continuously and the learner consumes rollouts as they
arrive — no synchronization barrier — so sample collection and SGD
overlap. Because harvested rollouts were collected under slightly stale
weights, the update applies V-trace truncated importance sampling
(Espeholt et al. 2018) to stay unbiased. TPU-native twist: the whole
V-trace computation (reverse scan included) lives inside the jitted loss,
so the learner update is one compiled program per [B, T] rollout batch.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu as rt
from ray_tpu.rl.algorithms.algorithm import AlgorithmBase, ConfigEvalMixin
from ray_tpu.rl.core.learner_group import LearnerGroup
from ray_tpu.rl.core.rl_module import DiscretePolicyModule, RLModuleSpec
from ray_tpu.rl.env_runner import EnvRunner


def vtrace(
    behavior_logp: jax.Array,
    target_logp: jax.Array,
    rewards: jax.Array,
    values: jax.Array,
    bootstrap_value: jax.Array,
    dones: jax.Array,
    gamma: float = 0.99,
    clip_rho: float = 1.0,
    clip_c: float = 1.0,
):
    """V-trace targets and policy-gradient advantages for one [T] rollout.

    vs_t - V_t = delta_t + gamma * nonterminal_t * c_t * (vs_{t+1} - V_{t+1})
    with delta_t = rho_t * (r_t + gamma * nonterminal_t * V_{t+1} - V_t),
    rho/c the clipped importance ratios. Computed with a reverse lax.scan
    so it stays inside jit (no Python loop over time).
    """
    log_ratio = target_logp - behavior_logp
    rho = jnp.minimum(jnp.exp(log_ratio), clip_rho)
    c = jnp.minimum(jnp.exp(log_ratio), clip_c)
    nonterminal = 1.0 - dones
    values_next = jnp.concatenate([values[1:], bootstrap_value[None]])
    deltas = rho * (rewards + gamma * nonterminal * values_next - values)

    def step(acc, xs):
        delta, disc, c_t = xs
        acc = delta + disc * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        step, 0.0, (deltas, gamma * nonterminal, c), reverse=True
    )
    vs = values + vs_minus_v
    vs_next = jnp.concatenate([vs[1:], bootstrap_value[None]])
    pg_adv = rho * (rewards + gamma * nonterminal * vs_next - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


def impala_loss(params, module, batch, gamma: float = 0.99,
                vf_coeff: float = 0.5, entropy_coeff: float = 0.01):
    """V-trace actor-critic loss over a [B, T] batch of rollouts."""
    B, T = batch["actions"].shape
    obs = batch["obs"].reshape(B * T, -1)
    out = module.forward(params, obs)
    logp_all = jax.nn.log_softmax(out["action_logits"]).reshape(B, T, -1)
    values = out["value"].reshape(B, T)
    target_logp = jnp.take_along_axis(
        logp_all, batch["actions"][..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    bootstrap = module.forward(params, batch["last_obs"])["value"]

    vs, pg_adv = jax.vmap(
        lambda bl, tl, r, v, bv, d: vtrace(bl, tl, r, v, bv, d, gamma=gamma)
    )(batch["logp"], target_logp, batch["rewards"], values, bootstrap,
      batch["dones"])

    policy_loss = -(pg_adv * target_logp).mean()
    value_loss = ((values - vs) ** 2).mean()
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
    loss = policy_loss + vf_coeff * value_loss - entropy_coeff * entropy
    return loss, {
        "total_loss": loss,
        "policy_loss": policy_loss,
        "vf_loss": value_loss,
        "entropy": entropy,
        "mean_rho": jnp.exp(target_logp - batch["logp"]).mean(),
    }


@dataclass
class IMPALAConfig(ConfigEvalMixin):
    """Builder-style config (reference: IMPALAConfig)."""

    env_creator: Optional[Callable] = None
    obs_dim: int = 4
    num_actions: int = 2
    hidden: tuple = (64, 64)
    num_env_runners: int = 2
    rollout_length: int = 64
    connectors_factory: Optional[Callable] = None
    lr: float = 5e-4
    gamma: float = 0.99
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    updates_per_iteration: int = 8
    rollouts_per_update: int = 2
    seed: int = 0

    def environment(self, env_creator=None, obs_dim=None, num_actions=None):
        if env_creator is not None:
            self.env_creator = env_creator
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        return self

    def env_runners(self, num_env_runners=None, rollout_length=None,
                    connectors_factory=None):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if rollout_length is not None:
            self.rollout_length = rollout_length
        if connectors_factory is not None:
            self.connectors_factory = connectors_factory
        return self

    def training(self, lr=None, gamma=None, updates_per_iteration=None,
                 rollouts_per_update=None, vf_coeff=None, entropy_coeff=None):
        for name, val in (
            ("lr", lr), ("gamma", gamma),
            ("updates_per_iteration", updates_per_iteration),
            ("rollouts_per_update", rollouts_per_update),
            ("vf_coeff", vf_coeff), ("entropy_coeff", entropy_coeff),
        ):
            if val is not None:
                setattr(self, name, val)
        return self

    def build(self) -> "IMPALA":
        return IMPALA(self)


class IMPALA(AlgorithmBase):
    """Async actor-learner loop.

    Unlike PPO's barrier (collect all -> update -> broadcast), sample
    futures stay in flight across updates: each update harvests whichever
    rollouts finished first (rt.wait), applies a V-trace-corrected SGD
    step, then refreshes only the harvested runners' weights and
    resubmits them. Runners that are mid-rollout are never stalled —
    that's the IMPALA throughput property.
    """

    def __init__(self, config: IMPALAConfig):
        assert config.env_creator is not None, "config.environment(...) first"
        self.config = config
        spec = RLModuleSpec(config.obs_dim, config.num_actions, config.hidden)
        module_factory = self._module_factory = lambda: DiscretePolicyModule(spec)  # noqa: E731

        loss = lambda p, m, b: impala_loss(  # noqa: E731
            p, m, b, gamma=config.gamma, vf_coeff=config.vf_coeff,
            entropy_coeff=config.entropy_coeff,
        )
        self.learner_group = LearnerGroup(
            module_factory, loss, num_learners=1, seed=config.seed,
            lr=config.lr,
        )
        self.env_runners = [
            EnvRunner.options(num_cpus=0.5).remote(
                config.env_creator,
                module_factory,
                seed=config.seed + 1 + i,
                rollout_length=config.rollout_length,
                connectors=(
                    config.connectors_factory()
                    if config.connectors_factory else None
                ),
                gamma=config.gamma,
            )
            for i in range(config.num_env_runners)
        ]
        weights = self.learner_group.get_weights()
        rt.get([r.set_weights.remote(weights) for r in self.env_runners],
               timeout=300)
        # Kick off the standing sample pipeline.
        self._pending: Dict[Any, Any] = {
            r.sample.remote(): r for r in self.env_runners
        }
        self._iteration = 0

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        metrics: Dict[str, float] = {}
        for _ in range(cfg.updates_per_iteration):
            want = min(cfg.rollouts_per_update, len(self._pending))
            ready, _ = rt.wait(
                list(self._pending), num_returns=want, timeout=300
            )
            if not ready:
                continue
            rollouts = rt.get(ready, timeout=300)
            runners = [self._pending.pop(ref) for ref in ready]
            batch = {
                k: np.stack([b[k] for b in rollouts])
                for k in ("obs", "actions", "logp", "rewards", "dones",
                          "last_obs")
            }
            metrics = self.learner_group.update_from_batch(batch)
            # Refresh only the harvested runners, then put them back to work.
            weights = self.learner_group.get_weights()
            # The harvested runners are idle here, so awaiting the weight
            # push is cheap (in-memory swap) and surfaces a dead runner
            # now instead of leaking the error with the dropped ref.
            rt.get([r.set_weights.remote(weights) for r in runners],
                   timeout=300)
            for r in runners:
                self._pending[r.sample.remote()] = r
        self._iteration += 1
        stats = rt.get(
            [r.episode_stats.remote() for r in self.env_runners], timeout=300
        )
        returns = [s["mean_return"] for s in stats if s["episodes"] > 0]
        return self._finish_iteration({
            "training_iteration": self._iteration,
            "episode_return_mean": float(np.mean(returns)) if returns else 0.0,
            "episodes_total": sum(s["episodes"] for s in stats),
            **{f"learner/{k}": v for k, v in metrics.items()},
        })

    def pending_rollouts(self, num: int = 1, timeout: float = 120.0):
        """Harvest up to `num` completed rollouts from the standing
        sample pipeline without consuming them for training — e.g. to
        export experience to an offline dataset. Harvested runners are
        resubmitted so the pipeline keeps flowing."""
        ready, _ = rt.wait(
            list(self._pending), num_returns=min(num, len(self._pending)),
            timeout=timeout,
        )
        rollouts = rt.get(ready, timeout=timeout)
        for ref in ready:
            runner = self._pending.pop(ref)
            self._pending[runner.sample.remote()] = runner
        return rollouts

    def stop(self):
        self.stop_eval_runners()
        self.learner_group.shutdown()
        for r in self.env_runners:
            try:
                rt.kill(r)
            except Exception:
                pass
