"""TD3: twin-delayed deep deterministic policy gradient.

Analog of the reference's TD3 (rllib/algorithms/td3 — Fujimoto et al.
2018; the reference reaches it through its DDPG family). TPU framing
mirrors this repo's SAC: the WHOLE update — twin-critic TD step with
target-policy smoothing, the delayed deterministic policy step, and
polyak target sync — is ONE jitted function over a state pytree, so an
iteration's `updates_per_iteration` steps run as compiled device work.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu as rt
from ray_tpu.rl.algorithms.algorithm import AlgorithmBase, ConfigEvalMixin
from ray_tpu.rl.core.rl_module import (
    ContinuousModuleSpec,
    init_mlp,
    mlp_forward,
)
from ray_tpu.rl.env_runner import ContinuousTransitionRunner
from ray_tpu.rl.replay import ReplayBuffer


class DeterministicPolicyModule:
    """tanh-deterministic actor + twin Q towers (the DDPG/TD3 module
    shape). Exploration noise is added by the runner-side sampler;
    actions live normalized in [-1, 1] internally."""

    def __init__(self, spec: ContinuousModuleSpec,
                 explore_sigma: float = 0.1):
        self.spec = spec
        self.explore_sigma = explore_sigma

    def init(self, rng: jax.Array) -> Dict:
        kp, k1, k2 = jax.random.split(rng, 3)
        sizes = [self.spec.obs_dim, *self.spec.hidden, self.spec.action_dim]
        qin = self.spec.obs_dim + self.spec.action_dim
        qsizes = [qin, *self.spec.hidden, 1]
        return {
            "pi": init_mlp(kp, sizes),
            "q1": init_mlp(k1, qsizes),
            "q2": init_mlp(k2, qsizes),
        }

    def scale_action(self, a_norm: jax.Array) -> jax.Array:
        lo, hi = self.spec.action_low, self.spec.action_high
        return a_norm * (hi - lo) / 2.0 + (hi + lo) / 2.0

    def pi(self, params: Dict, obs: jax.Array) -> jax.Array:
        return jnp.tanh(mlp_forward(params["pi"], obs))

    def q_values(self, params: Dict, obs: jax.Array, a_norm: jax.Array):
        x = jnp.concatenate([obs, a_norm], axis=-1)
        return (mlp_forward(params["q1"], x)[..., 0],
                mlp_forward(params["q2"], x)[..., 0])

    def deterministic_action(self, params: Dict, obs: jax.Array):
        return self.scale_action(self.pi(params, obs))

    def sample_with_logp(self, params: Dict, obs: jax.Array,
                         rng: jax.Array):
        """Behavior policy: pi(s) + N(0, sigma), clipped to [-1, 1].
        (Deterministic policy: logp is a placeholder so the runner's
        interface matches the SAC module's.)"""
        a = self.pi(params, obs)
        noise = self.explore_sigma * jax.random.normal(rng, a.shape)
        a_norm = jnp.clip(a + noise, -1.0, 1.0)
        return a_norm, jnp.zeros(a_norm.shape[:-1])

    def sample_action(self, params: Dict, obs: jax.Array, rng: jax.Array):
        a_norm, logp = self.sample_with_logp(params, obs, rng)
        return self.scale_action(a_norm), logp, jnp.zeros(a_norm.shape[:-1])


def make_td3_update(module: DeterministicPolicyModule,
                    pi_tx, q_tx, gamma: float, tau: float,
                    target_noise: float, noise_clip: float,
                    policy_delay: int):
    """One TD3 gradient step as a pure function of (state, batch, rng)."""

    def q_loss_fn(qp, params, target, batch, rng):
        noise = jnp.clip(
            target_noise * jax.random.normal(
                rng, batch["actions"].shape
            ),
            -noise_clip, noise_clip,
        )
        next_a = jnp.clip(
            module.pi({"pi": target["pi"]}, batch["next_obs"]) + noise,
            -1.0, 1.0,
        )
        tq1, tq2 = module.q_values(
            {**params, "q1": target["q1"], "q2": target["q2"]},
            batch["next_obs"], next_a,
        )
        td_target = jax.lax.stop_gradient(
            batch["rewards"]
            + gamma * (1.0 - batch["dones"]) * jnp.minimum(tq1, tq2)
        )
        q1, q2 = module.q_values({**params, **qp}, batch["obs"],
                                 batch["actions"])
        return ((q1 - td_target) ** 2).mean() + ((q2 - td_target) ** 2).mean()

    def pi_loss_fn(pp, params, batch):
        a = module.pi({"pi": pp}, batch["obs"])
        q1, _ = module.q_values(params, batch["obs"], a)
        return -q1.mean()

    def update(state, batch, rng):
        params, target = state["params"], state["target"]
        qp = {"q1": params["q1"], "q2": params["q2"]}
        q_loss, q_grads = jax.value_and_grad(q_loss_fn)(
            qp, params, target, batch, rng
        )
        q_updates, q_opt = q_tx.update(q_grads, state["q_opt"], qp)
        qp = optax.apply_updates(qp, q_updates)
        params = {**params, **qp}

        def do_policy(_):
            pi_loss, pi_grads = jax.value_and_grad(pi_loss_fn)(
                params["pi"], params, batch
            )
            pi_updates, pi_opt = pi_tx.update(
                pi_grads, state["pi_opt"], params["pi"]
            )
            new_pi = optax.apply_updates(params["pi"], pi_updates)
            # Polyak targets move only on policy steps (TD3's delay).
            new_target = jax.tree.map(
                lambda t, o: (1.0 - tau) * t + tau * o,
                state["target"],
                {"pi": new_pi, "q1": params["q1"], "q2": params["q2"]},
            )
            return new_pi, pi_opt, new_target, pi_loss

        def skip_policy(_):
            return (params["pi"], state["pi_opt"], state["target"],
                    jnp.asarray(0.0))

        step = state["step"]
        new_pi, pi_opt, new_target, pi_loss = jax.lax.cond(
            step % policy_delay == 0, do_policy, skip_policy, None
        )
        new_state = {
            "params": {**params, "pi": new_pi},
            "target": new_target,
            "pi_opt": pi_opt,
            "q_opt": q_opt,
            "step": step + 1,
        }
        metrics = {"q_loss": q_loss, "pi_loss": pi_loss,
                   "mean_q": module.q_values(
                       params, batch["obs"], batch["actions"])[0].mean()}
        return new_state, metrics

    return jax.jit(update)


@dataclass
class TD3Config(ConfigEvalMixin):
    env_creator: Optional[Any] = None
    obs_dim: int = 3
    action_dim: int = 1
    action_low: float = -1.0
    action_high: float = 1.0
    hidden: tuple = (64, 64)
    num_env_runners: int = 1
    rollout_length: int = 200
    lr: float = 1e-3
    gamma: float = 0.99
    tau: float = 0.005
    batch_size: int = 128
    updates_per_iteration: int = 200
    warmup_steps: int = 500
    buffer_capacity: int = 100_000
    explore_sigma: float = 0.1
    target_noise: float = 0.2
    noise_clip: float = 0.5
    policy_delay: int = 2
    seed: int = 0

    def environment(self, env_creator=None, obs_dim=None, action_dim=None,
                    action_low=None, action_high=None):
        for name, val in (("env_creator", env_creator),
                          ("obs_dim", obs_dim), ("action_dim", action_dim),
                          ("action_low", action_low),
                          ("action_high", action_high)):
            if val is not None:
                setattr(self, name, val)
        return self

    def env_runners(self, num_env_runners=None, rollout_length=None):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if rollout_length is not None:
            self.rollout_length = rollout_length
        return self

    def training(self, lr=None, gamma=None, tau=None, batch_size=None,
                 updates_per_iteration=None, warmup_steps=None,
                 buffer_capacity=None, explore_sigma=None,
                 target_noise=None, noise_clip=None, policy_delay=None):
        for name, val in (
            ("lr", lr), ("gamma", gamma), ("tau", tau),
            ("batch_size", batch_size),
            ("updates_per_iteration", updates_per_iteration),
            ("warmup_steps", warmup_steps),
            ("buffer_capacity", buffer_capacity),
            ("explore_sigma", explore_sigma),
            ("target_noise", target_noise), ("noise_clip", noise_clip),
            ("policy_delay", policy_delay),
        ):
            if val is not None:
                setattr(self, name, val)
        return self

    def build(self) -> "TD3":
        return TD3(self)


class TD3(AlgorithmBase):
    """Off-policy deterministic actor-critic loop: collect -> replay ->
    jitted twin-delayed updates."""

    def __init__(self, config: TD3Config):
        assert config.env_creator is not None, "config.environment(...) first"
        self.config = config
        spec = ContinuousModuleSpec(
            config.obs_dim, config.action_dim,
            config.action_low, config.action_high, config.hidden,
        )
        self.module = DeterministicPolicyModule(spec, config.explore_sigma)
        module_factory = self._module_factory = (
            lambda s=spec, sg=config.explore_sigma:
            DeterministicPolicyModule(s, sg)
        )
        params = self.module.init(jax.random.PRNGKey(config.seed))
        pi_tx = optax.adam(config.lr)
        q_tx = optax.adam(config.lr)
        qp = {"q1": params["q1"], "q2": params["q2"]}
        self.state = {
            "params": params,
            "target": jax.tree.map(
                lambda x: x, {"pi": params["pi"], **qp}
            ),
            "pi_opt": pi_tx.init(params["pi"]),
            "q_opt": q_tx.init(qp),
            "step": jnp.asarray(0, dtype=jnp.int32),
        }
        self._update = make_td3_update(
            self.module, pi_tx, q_tx, config.gamma, config.tau,
            config.target_noise, config.noise_clip, config.policy_delay,
        )
        self.buffer = ReplayBuffer(
            config.buffer_capacity, config.obs_dim, seed=config.seed,
            action_dim=config.action_dim,
        )
        self.env_runners = [
            ContinuousTransitionRunner.options(num_cpus=0.5).remote(
                config.env_creator, module_factory,
                seed=config.seed + 1 + i,
                rollout_length=config.rollout_length,
            )
            for i in range(config.num_env_runners)
        ]
        self._rng = jax.random.PRNGKey(config.seed + 77)
        self._steps_sampled = 0
        self._iteration = 0
        self._broadcast()

    def _broadcast(self):
        weights = jax.device_get(self.state["params"])
        rt.get([r.set_weights.remote(weights) for r in self.env_runners],
               timeout=300)

    # AlgorithmBase state hooks (the SAC pattern: whole state, one pytree)
    def _get_learner_state(self):
        return jax.device_get(self.state)

    def _set_learner_state(self, state):
        self.state = jax.tree.map(jnp.asarray, state)

    def _current_weights(self):
        return jax.device_get(self.state["params"])

    def _checkpoint_extra_state(self):
        return {"steps_sampled": self._steps_sampled}

    def _restore_extra_state(self, extra):
        self._steps_sampled = extra.get("steps_sampled", self._steps_sampled)

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        warm = self._steps_sampled < cfg.warmup_steps
        rollouts = rt.get(
            [r.sample.remote(random_actions=warm) for r in self.env_runners],
            timeout=600,
        )
        for b in rollouts:
            self.buffer.add_batch(b)
            self._steps_sampled += len(b["obs"])
        metrics: Dict[str, Any] = {}
        if self._steps_sampled >= cfg.warmup_steps:
            m = None
            for _ in range(cfg.updates_per_iteration):
                batch = {
                    k: jnp.asarray(v)
                    for k, v in self.buffer.sample(cfg.batch_size).items()
                }
                self._rng, key = jax.random.split(self._rng)
                self.state, m = self._update(self.state, batch, key)
            if m is not None:
                metrics = {k: float(v) for k, v in m.items()}
            self._broadcast()
        self._iteration += 1
        stats = rt.get(
            [r.episode_stats.remote() for r in self.env_runners], timeout=300
        )
        returns = [s["mean_return"] for s in stats if s["episodes"] > 0]
        return self._finish_iteration({
            "training_iteration": self._iteration,
            "episode_return_mean": float(np.mean(returns)) if returns else 0.0,
            "episodes_total": sum(s["episodes"] for s in stats),
            "steps_sampled": self._steps_sampled,
            **{f"learner/{k}": v for k, v in metrics.items()},
        })

    def stop(self):
        self.stop_eval_runners()
        for r in self.env_runners:
            try:
                rt.kill(r)
            except Exception:  # noqa: BLE001
                pass


@dataclass
class DDPGConfig(TD3Config):
    """DDPG as the degenerate TD3 (reference: rllib's DDPG, which TD3
    historically extended): no policy delay, no target-policy smoothing.
    The twin critic stays (strictly helps; set nothing to recover the
    classic single-critic behavior is intentionally not offered — the
    minimum over twins only reduces overestimation)."""

    policy_delay: int = 1
    target_noise: float = 0.0
    noise_clip: float = 0.0
