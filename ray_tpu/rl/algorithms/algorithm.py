"""Shared Algorithm machinery: periodic evaluation + save/restore.

Analog of the reference's Algorithm.evaluate flow
(rllib/algorithms/algorithm.py:795: dedicated evaluation workers with a
separate env/config, eval metrics under results["evaluation"]) and
Algorithm.save/restore (checkpointable_state: module weights + optimizer
state + counters). Every algorithm class mixes this in; configs gain the
`.evaluation(...)` builder.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Dict, Optional

import numpy as np

import ray_tpu as rt
from ray_tpu.rl.env_runner import _EnvRunnerBase


@rt.remote
class EvalEnvRunner(_EnvRunnerBase):
    """Dedicated evaluation runner: whole episodes under the CURRENT
    weights, optionally greedy (explore=False), never feeding training
    (reference: evaluation/worker_set.py:82 eval WorkerSet)."""

    def run_episodes(self, num_episodes: int, explore: bool = False,
                     max_steps_per_episode: int = 10_000) -> Dict[str, Any]:
        import jax

        assert self.params is not None, "set_weights first"
        stateful = hasattr(self.module, "initial_state")
        if self._sample is None:
            self._sample = jax.jit(self.module.sample_action)
        greedy = None
        if not explore:
            # Cached like _sample: a fresh jit wrapper per eval round
            # would recompile every evaluation.
            if getattr(self, "_greedy", None) is None:
                self._greedy = jax.jit(self._greedy_action)
            greedy = self._greedy
        returns, lengths = [], []
        for _ in range(num_episodes):
            obs, _ = self.env.reset()
            self._set_obs(obs)
            state = self.module.initial_state(1) if stateful else None
            total, steps = 0.0, 0
            while steps < max_steps_per_episode:
                obs_c = self._obs_conn
                if explore:
                    self.rng, key = jax.random.split(self.rng)
                    args = [self.params, obs_c[None], key]
                    if stateful:
                        args.append(state)
                    if self._eval_epsilon() is not None:
                        # Value modules explore epsilon-greedily; 0.05
                        # is the conventional eval epsilon (without it
                        # explore mode would silently equal greedy).
                        args.append(self._eval_epsilon())
                    out = self._sample(*args)
                    # Normalize: policy modules return (a, logp, v[,
                    # state]); value modules (a[, state]) or a bare
                    # action array.
                    if not isinstance(out, (tuple, list)):
                        action = out
                    else:
                        action = out[0]
                        if stateful:
                            state = out[-1]
                elif stateful:
                    action, state = greedy(self.params, obs_c[None], state)
                else:
                    action = greedy(self.params, obs_c[None])
                action = np.asarray(action)[0]
                if action.ndim == 0 and np.issubdtype(action.dtype, np.integer):
                    action = int(action)
                nxt, reward, terminated, truncated, _ = self.env.step(action)
                total += float(reward)
                steps += 1
                if terminated or truncated:
                    break
                self._set_obs(nxt)
            returns.append(total)
            lengths.append(steps)
        return {"returns": returns, "lengths": lengths}

    def _eval_epsilon(self):
        """0.05 for modules whose sample_action takes an epsilon (the
        value-based family), None for policy modules."""
        import inspect

        if not hasattr(self, "_eval_eps_cached"):
            try:
                params = inspect.signature(
                    self.module.sample_action
                ).parameters
                self._eval_eps_cached = 0.05 if "epsilon" in params else None
            except (TypeError, ValueError):
                self._eval_eps_cached = None
        return self._eval_eps_cached

    def _greedy_action(self, params, obs, state=None):
        import jax.numpy as jnp

        if state is not None:  # stateful module: thread the GRU state
            out, state = self.module.forward_step(params, obs, state)
            logits = out.get("action_logits")
            if logits is None:  # recurrent Q module
                logits = out["q_values"]
            return jnp.argmax(logits, axis=-1), state
        if hasattr(self.module, "deterministic_action"):
            return self.module.deterministic_action(params, obs)
        out = self.module.forward(params, obs)
        logits = out.get("action_logits")
        if logits is None:
            logits = out["q_values"]
        return jnp.argmax(logits, axis=-1)


class ConfigEvalMixin:
    """`.evaluation(...)` builder shared by every AlgorithmConfig
    (reference: algorithm_config.py evaluation())."""

    evaluation_interval: Optional[int] = None  # iterations between evals
    evaluation_num_env_runners: int = 1
    evaluation_duration: int = 5               # episodes per eval
    evaluation_explore: bool = False
    evaluation_env_creator: Optional[Callable] = None

    def evaluation(self, evaluation_interval=None,
                   evaluation_num_env_runners=None,
                   evaluation_duration=None,
                   evaluation_explore=None,
                   evaluation_env_creator=None):
        for name, val in (
            ("evaluation_interval", evaluation_interval),
            ("evaluation_num_env_runners", evaluation_num_env_runners),
            ("evaluation_duration", evaluation_duration),
            ("evaluation_explore", evaluation_explore),
            ("evaluation_env_creator", evaluation_env_creator),
        ):
            if val is not None:
                setattr(self, name, val)
        return self

    def as_trainable(self, stop_iters: int = 10):
        """Tune adapter (reference: Algorithm IS a Tune Trainable,
        rllib/algorithms/algorithm.py:191): Tuner(config.as_trainable(),
        param_space={"lr": ...}) tunes this algorithm's fields."""
        return config_as_trainable(self, stop_iters)


class AlgorithmBase:
    """Mixin over concrete algorithms (which own `config`,
    `learner_group`, `_iteration`, `_broadcast_weights`)."""

    _eval_runners: Optional[list] = None

    # -- evaluation ------------------------------------------------------
    def _ensure_eval_runners(self):
        if self._eval_runners is not None:
            return
        cfg = self.config
        env_creator = (getattr(cfg, "evaluation_env_creator", None)
                       or cfg.env_creator)
        self._eval_runners = [
            EvalEnvRunner.options(num_cpus=0.25).remote(
                env_creator,
                self._module_factory,
                seed=getattr(cfg, "seed", 0) + 10_000 + i,
                connectors=(cfg.connectors_factory()
                            if getattr(cfg, "connectors_factory", None)
                            else None),
                gamma=getattr(cfg, "gamma", 0.99),
            )
            for i in range(max(1, getattr(cfg, "evaluation_num_env_runners", 1)))
        ]

    # Overridable state hooks (SAC keeps its whole update state in one
    # pytree instead of a LearnerGroup).
    def _get_learner_state(self):
        return self.learner_group.get_state()

    def _set_learner_state(self, state):
        self.learner_group.set_state(state)

    def _current_weights(self):
        return self.learner_group.get_weights()

    def evaluate(self) -> Dict[str, Any]:
        """Run evaluation_duration episodes on the dedicated runners under
        the current learner weights (reference: algorithm.py:795)."""
        self._ensure_eval_runners()
        cfg = self.config
        weights = self._current_weights()
        rt.get([r.set_weights.remote(weights) for r in self._eval_runners],
               timeout=300)
        total = max(1, getattr(cfg, "evaluation_duration", 5))
        n_runners = len(self._eval_runners)
        per = [total // n_runners + (1 if i < total % n_runners else 0)
               for i in range(n_runners)]
        outs = rt.get(
            [
                r.run_episodes.remote(
                    n, explore=getattr(cfg, "evaluation_explore", False)
                )
                for r, n in zip(self._eval_runners, per) if n > 0
            ],
            timeout=1200,
        )
        returns = [x for o in outs for x in o["returns"]]
        lengths = [x for o in outs for x in o["lengths"]]
        return {
            "episode_return_mean": float(np.mean(returns)),
            "episode_return_min": float(np.min(returns)),
            "episode_return_max": float(np.max(returns)),
            "episode_len_mean": float(np.mean(lengths)),
            "episodes_this_eval": len(returns),
        }

    def _finish_iteration(self, result: Dict[str, Any]) -> Dict[str, Any]:
        """Attach periodic evaluation to one train() result."""
        interval = getattr(self.config, "evaluation_interval", None)
        if interval and self._iteration % interval == 0:
            result["evaluation"] = self.evaluate()
        return result

    def _gather_runner_states(self):
        try:
            return rt.get(
                [r.get_runner_state.remote() for r in self.env_runners],
                timeout=300,
            )
        except Exception:  # noqa: BLE001 — runner flavor without state
            return None

    # -- checkpointing ---------------------------------------------------
    def save(self, checkpoint_dir: str) -> str:
        """Persist weights + optimizer state + counters (reference:
        Algorithm.save_checkpoint)."""
        os.makedirs(checkpoint_dir, exist_ok=True)
        state = {
            "learner_state": self._get_learner_state(),
            "iteration": self._iteration,
            "algorithm": type(self).__name__,
            "extra": self._checkpoint_extra_state(),
            # Env-runner sampling state (RNG/env/connectors) makes the
            # restored run continue the SAME trajectory stream. Runner
            # flavors without state support (vectorized) are skipped.
            "runner_states": self._gather_runner_states(),
        }
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "wb") as f:
            pickle.dump(state, f)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str):
        """Resume mid-train: learner params + optimizer state + iteration
        counter, then weight broadcast to the env runners."""
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "rb") as f:
            state = pickle.load(f)
        self._set_learner_state(state["learner_state"])
        self._iteration = state["iteration"]
        self._restore_extra_state(state.get("extra") or {})
        runner_states = state.get("runner_states") or []
        if len(runner_states) == len(self.env_runners):
            try:
                rt.get(
                    [
                        r.set_runner_state.remote(st)
                        for r, st in zip(self.env_runners, runner_states)
                    ],
                    timeout=300,
                )
            except Exception:  # noqa: BLE001 — runner flavor without state
                pass
        # Resync every env runner to the restored weights.
        weights = self._current_weights()
        rt.get(
            [r.set_weights.remote(weights) for r in self.env_runners],
            timeout=300,
        )

    def _checkpoint_extra_state(self) -> Dict[str, Any]:
        """Algorithm-specific additions (e.g. target-network params)."""
        return {}

    def _restore_extra_state(self, extra: Dict[str, Any]) -> None:
        pass

    def stop_eval_runners(self):
        for r in self._eval_runners or []:
            try:
                rt.kill(r)
            except Exception:  # noqa: BLE001
                pass
        self._eval_runners = None


def config_as_trainable(config, stop_iters: int = 10):
    """Tune adapter (reference: Algorithm IS a Tune Trainable,
    rllib/algorithms/algorithm.py:191 — Tuner(PPO, param_space=...)).

    Returns a function trainable: each trial deep-copies `config`,
    applies its sampled params (dataclass fields / non-callable config
    attributes only — builder METHODS are rejected), builds the
    algorithm, runs train() iterations reporting each result WITH an
    Algorithm.save checkpoint — so trial restarts, Tuner.restore, and
    PBT exploit resume from learned state instead of iteration 0 — and
    always stops the algorithm's actors.
    Use: Tuner(config.as_trainable(), param_space={"lr": ...}).
    """
    import copy
    import dataclasses

    def trainable(trial_config):
        import os
        import tempfile

        from ray_tpu import tune as _tune
        from ray_tpu.train.checkpoint import Checkpoint

        cfg = copy.deepcopy(config)
        field_names = (
            {f.name for f in dataclasses.fields(cfg)}
            if dataclasses.is_dataclass(cfg) else set()
        )
        for key, value in trial_config.items():
            settable = key in field_names or (
                hasattr(cfg, key) and not callable(getattr(cfg, key))
            )
            if not settable:
                raise ValueError(
                    f"param_space key {key!r} is not a config field of "
                    f"{type(cfg).__name__}"
                )
            setattr(cfg, key, value)
        algo = cfg.build()
        try:
            ckpt = _tune.get_checkpoint()
            if ckpt is not None:
                algo.restore(ckpt.path)
            while algo._iteration < stop_iters:
                result = algo.train()
                d = tempfile.mkdtemp(prefix="rl_trial_ckpt_")
                algo.save(d)
                _tune.report(result, checkpoint=Checkpoint.from_directory(d))
        finally:
            algo.stop()

    # tune.with_resources pins per-trial resources on the CONFIG copy
    # (the as_trainable dispatch branch); carry them onto the closure
    # the way trainer.as_trainable does.
    if getattr(config, "_tune_resources", None) is not None:
        trainable._tune_resources = dict(config._tune_resources)
    return trainable

