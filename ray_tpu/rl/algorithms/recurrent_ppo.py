"""Recurrent PPO: proximal policy optimization with a stateful policy.

The structural piece the MLP stack cannot express: the policy carries a
GRU hidden state across steps (reset at episode boundaries), rollouts
ship the state each window started with, and the learner replays whole
[B, T] sequences through forward_seq so the recomputed logits/values see
exactly the states the behavior policy saw.

Reference analog: recurrent-model support + stored-state replay
(rllib/models/torch/recurrent_net.py, rllib/algorithms/r2d2/ — the
use_lstm path of PPO's old stack). Minibatching is over SEQUENCES
(rollout windows), never over shuffled timesteps, which would sever the
state chain.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import numpy as np

import ray_tpu as rt
from ray_tpu.rl.algorithms.algorithm import AlgorithmBase
from ray_tpu.rl.algorithms.ppo import PPOConfig, clipped_surrogate
from ray_tpu.rl.core.learner_group import LearnerGroup
from ray_tpu.rl.core.rl_module import (
    RecurrentModuleSpec,
    RecurrentPolicyModule,
)
from ray_tpu.rl.env_runner import RecurrentEnvRunner, compute_gae


def recurrent_ppo_loss(params, module, batch):
    """Clipped-surrogate PPO over [B, T] sequences replayed through the
    GRU (batch carries state0 [B, H] and dones [B, T]); the surrogate
    body is shared with plain PPO (ppo.clipped_surrogate)."""
    out = module.forward_seq(
        params, batch["obs"], batch["state0"], batch["dones"]
    )
    return clipped_surrogate(out, batch)


@dataclass
class RecurrentPPOConfig(PPOConfig):
    """PPOConfig plus the recurrent knobs; hidden state size rides
    state_dim. minibatch_size is ignored (sequence-level batching)."""

    state_dim: int = 32

    def build(self) -> "RecurrentPPO":
        return RecurrentPPO(self)


class RecurrentPPO(AlgorithmBase):
    def __init__(self, config: RecurrentPPOConfig):
        assert config.env_creator is not None, "config.environment(...) first"
        if config.obs_shape is not None:
            raise ValueError(
                "RecurrentPPO takes vector observations (obs_dim=...); "
                "a conv+recurrent torso is not composed here"
            )
        if config.num_learners > config.num_env_runners:
            # The recurrent batch axis is SEQUENCES (one per runner
            # window): more learners than runners would shard to empty
            # batches and train on NaNs.
            raise ValueError(
                f"num_learners={config.num_learners} exceeds "
                f"num_env_runners={config.num_env_runners}; recurrent "
                "batches shard by rollout window"
            )
        self.config = config
        spec = RecurrentModuleSpec(
            config.obs_dim, config.num_actions,
            state_dim=config.state_dim, hidden=config.hidden[-1:] or (32,),
        )
        module_factory = self._module_factory = (  # noqa: E731
            lambda: RecurrentPolicyModule(spec)
        )
        self.learner_group = LearnerGroup(
            module_factory,
            config.loss_fn or recurrent_ppo_loss,
            num_learners=config.num_learners,
            seed=config.seed,
            lr=config.lr,
        )
        self.env_runners = [
            RecurrentEnvRunner.options(num_cpus=0.5).remote(
                config.env_creator,
                module_factory,
                seed=config.seed + 1 + i,
                rollout_length=config.rollout_length,
                connectors=(
                    config.connectors_factory()
                    if config.connectors_factory else None
                ),
                gamma=config.gamma,
            )
            for i in range(config.num_env_runners)
        ]
        self._iteration = 0
        self._broadcast_weights()

    def _broadcast_weights(self):
        weights = self.learner_group.get_weights()
        rt.get([r.set_weights.remote(weights) for r in self.env_runners],
               timeout=300)

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        rollouts = rt.get(
            [r.sample.remote() for r in self.env_runners], timeout=600
        )
        processed = [compute_gae(b, cfg.gamma, cfg.lambda_) for b in rollouts]
        # Sequences stay whole: [B, T, ...] with B = rollout windows.
        batch = {
            k: np.stack([p[k] for p in processed])
            for k in ("obs", "actions", "logp", "advantages", "returns",
                      "dones")
        }
        batch["state0"] = np.stack([p["state0"] for p in processed])
        metrics: Dict[str, float] = {}
        for _ in range(cfg.num_epochs):
            metrics = self.learner_group.update_from_batch(batch)
        self._broadcast_weights()
        self._iteration += 1
        stats = rt.get(
            [r.episode_stats.remote() for r in self.env_runners], timeout=300
        )
        returns = [s["mean_return"] for s in stats if s["episodes"] > 0]
        return self._finish_iteration({
            "training_iteration": self._iteration,
            "episode_return_mean": float(np.mean(returns)) if returns else 0.0,
            "episodes_total": sum(s["episodes"] for s in stats),
            **{f"learner/{k}": v for k, v in metrics.items()},
        })

    def stop(self):
        self.stop_eval_runners()
        self.learner_group.shutdown()
        for r in self.env_runners:
            try:
                rt.kill(r)
            except Exception:  # noqa: BLE001
                pass
