"""APPO: asynchronous PPO with V-trace off-policy correction.

Reference analog: rllib/algorithms/appo (APPOConfig/APPO + its
appo_learner loss: PPO's clipped surrogate computed on V-trace-corrected
advantages, so slightly-stale rollouts from non-blocking samplers stay
usable). TPU-first differences: rollouts come from VectorEnvRunner
actors (one batched device call per step across N envs), the loss is a
single jitted function over [B, T] rollouts, and the driver keeps sample
futures standing across updates exactly like IMPALA's harvest loop.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu as rt
from ray_tpu.rl.algorithms.algorithm import AlgorithmBase, ConfigEvalMixin
from ray_tpu.rl.core.learner_group import LearnerGroup
from ray_tpu.rl.core.rl_module import DiscretePolicyModule, RLModuleSpec
from ray_tpu.rl.algorithms.impala import vtrace
from ray_tpu.rl.env_runner import VectorEnvRunner


def appo_loss(params, module, batch, gamma: float = 0.99,
              clip_eps: float = 0.2, vf_coeff: float = 0.5,
              entropy_coeff: float = 0.01):
    """Clipped-surrogate policy loss on V-trace advantages, [B, T] batch."""
    B, T = batch["actions"].shape
    obs = batch["obs"].reshape(B * T, -1)
    out = module.forward(params, obs)
    logp_all = jax.nn.log_softmax(out["action_logits"]).reshape(B, T, -1)
    values = out["value"].reshape(B, T)
    target_logp = jnp.take_along_axis(
        logp_all, batch["actions"][..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    bootstrap = batch.get("last_values")
    if bootstrap is None:
        bootstrap = module.forward(params, batch["last_obs"])["value"]

    vs, pg_adv = jax.vmap(
        lambda bl, tl, r, v, bv, d: vtrace(bl, tl, r, v, bv, d, gamma=gamma)
    )(batch["logp"], target_logp, batch["rewards"], values, bootstrap,
      batch["dones"])
    adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)

    ratio = jnp.exp(target_logp - batch["logp"])
    surr = jnp.minimum(
        ratio * adv,
        jnp.clip(ratio, 1.0 - clip_eps, 1.0 + clip_eps) * adv,
    )
    policy_loss = -surr.mean()
    value_loss = ((values - vs) ** 2).mean()
    entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
    loss = policy_loss + vf_coeff * value_loss - entropy_coeff * entropy
    return loss, {
        "total_loss": loss,
        "policy_loss": policy_loss,
        "vf_loss": value_loss,
        "entropy": entropy,
        "mean_ratio": ratio.mean(),
    }


@dataclass
class APPOConfig(ConfigEvalMixin):
    """Builder-style config (reference: APPOConfig)."""

    env_creator: Optional[Callable] = None
    obs_dim: int = 4
    num_actions: int = 2
    hidden: tuple = (64, 64)
    num_env_runners: int = 2
    num_envs_per_runner: int = 4
    rollout_length: int = 64
    lr: float = 3e-3
    gamma: float = 0.99
    clip_eps: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    updates_per_iteration: int = 8
    rollouts_per_update: int = 1
    seed: int = 0

    def environment(self, env_creator=None, obs_dim=None, num_actions=None):
        if env_creator is not None:
            self.env_creator = env_creator
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        return self

    def env_runners(self, num_env_runners=None, num_envs_per_runner=None,
                    rollout_length=None):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_runner is not None:
            self.num_envs_per_runner = num_envs_per_runner
        if rollout_length is not None:
            self.rollout_length = rollout_length
        return self

    def training(self, lr=None, gamma=None, clip_eps=None,
                 updates_per_iteration=None, rollouts_per_update=None,
                 vf_coeff=None, entropy_coeff=None):
        for k, v in (("lr", lr), ("gamma", gamma), ("clip_eps", clip_eps),
                     ("updates_per_iteration", updates_per_iteration),
                     ("rollouts_per_update", rollouts_per_update),
                     ("vf_coeff", vf_coeff),
                     ("entropy_coeff", entropy_coeff)):
            if v is not None:
                setattr(self, k, v)
        return self

    def build(self) -> "APPO":
        return APPO(self)


class APPO(AlgorithmBase):
    """Async actor-learner loop over vectorized samplers.

    Sample futures stay standing across updates (IMPALA's harvest
    pattern); each harvested (T, N, ...) rollout transposes to the
    [B=N, T] layout the V-trace loss consumes."""

    def __init__(self, config: APPOConfig):
        assert config.env_creator is not None, "config.environment(...) first"
        self.config = config
        spec = RLModuleSpec(config.obs_dim, config.num_actions, config.hidden)
        module_factory = self._module_factory = lambda: DiscretePolicyModule(spec)  # noqa: E731

        loss = lambda p, m, b: appo_loss(  # noqa: E731
            p, m, b, gamma=config.gamma, clip_eps=config.clip_eps,
            vf_coeff=config.vf_coeff, entropy_coeff=config.entropy_coeff,
        )
        self.learner_group = LearnerGroup(
            module_factory, loss, num_learners=1, seed=config.seed,
            lr=config.lr,
        )
        self.env_runners = [
            VectorEnvRunner.options(num_cpus=0.5).remote(
                config.env_creator,
                module_factory,
                num_envs=config.num_envs_per_runner,
                seed=config.seed + 1 + i,
                rollout_length=config.rollout_length,
                gamma=config.gamma,
            )
            for i in range(config.num_env_runners)
        ]
        weights = self.learner_group.get_weights()
        rt.get([r.set_weights.remote(weights) for r in self.env_runners],
               timeout=300)
        self._pending: Dict[Any, Any] = {
            r.sample.remote(): r for r in self.env_runners
        }
        self._iteration = 0

    @staticmethod
    def _to_bt(rollout: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """(T, N, ...) time-major sample -> (B=N, T, ...) rollout batch."""
        out = {}
        for k in ("obs", "actions", "logp", "values", "rewards", "dones"):
            a = rollout[k]
            out[k] = np.swapaxes(a, 0, 1)
        out["last_values"] = rollout["last_values"]
        out["last_obs"] = rollout["last_obs"]
        return out

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        metrics: Dict[str, float] = {}
        for _ in range(cfg.updates_per_iteration):
            want = min(cfg.rollouts_per_update, len(self._pending))
            ready, _ = rt.wait(
                list(self._pending), num_returns=want, timeout=300
            )
            if not ready:
                continue
            rollouts = [self._to_bt(b) for b in rt.get(ready, timeout=300)]
            runners = [self._pending.pop(ref) for ref in ready]
            batch = {
                k: np.concatenate([b[k] for b in rollouts])
                for k in rollouts[0]
            }
            metrics = self.learner_group.update_from_batch(batch)
            weights = self.learner_group.get_weights()
            # The harvested runners are idle here, so awaiting the weight
            # push is cheap (in-memory swap) and surfaces a dead runner
            # now instead of leaking the error with the dropped ref.
            rt.get([r.set_weights.remote(weights) for r in runners],
                   timeout=300)
            for r in runners:
                self._pending[r.sample.remote()] = r
        self._iteration += 1
        stats = rt.get(
            [r.episode_stats.remote() for r in self.env_runners], timeout=300
        )
        returns = [s["mean_return"] for s in stats if s["episodes"] > 0]
        return self._finish_iteration({
            "training_iteration": self._iteration,
            "episode_return_mean": float(np.mean(returns)) if returns else 0.0,
            "episodes_total": sum(s["episodes"] for s in stats),
            **{f"learner/{k}": v for k, v in metrics.items()},
        })

    def stop(self):
        self.stop_eval_runners()
        self.learner_group.shutdown()
        for r in self.env_runners:
            try:
                rt.kill(r)
            except Exception:  # noqa: BLE001
                pass
