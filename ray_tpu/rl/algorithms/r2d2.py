"""R2D2: recurrent replay distributed DQN.

Analog of the reference's R2D2 (rllib/algorithms/r2d2/): value-based
learning with a recurrent (GRU) Q-network over SEQUENCE replay — the
buffer stores fixed-length windows with the hidden state each window
started from (stored-state strategy), the learner replays whole windows
through the GRU with a burn-in prefix that refreshes the state under
current weights but takes no gradient, and targets are double-DQN over
the target network's replay of the same window.

TPU-first: one jitted update consumes a [B, T, ...] window batch; the
GRU scan, burn-in masking, double-Q argmax, and Huber loss all live in
one compiled program.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu as rt
from ray_tpu.rl.algorithms.algorithm import AlgorithmBase, ConfigEvalMixin
from ray_tpu.rl.core.rl_module import (
    RecurrentModuleSpec,
    RecurrentQNetworkModule,
)
from ray_tpu.rl.env_runner import _EnvRunnerBase


@rt.remote
class RecurrentWindowRunner(_EnvRunnerBase):
    """Collects fixed-length windows for sequence replay: each window
    ships the GRU state it STARTED from plus per-step
    (obs, action, reward, done) — the stored-state scheme R2D2 uses."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._policy_state = None

    def sample(self, epsilon: float = 0.0) -> Dict[str, np.ndarray]:
        import jax as _jax

        self._begin_rollout()
        if self._policy_state is None:
            self._policy_state = self.module.initial_state(1)
        T = self.rollout_length
        state0 = np.asarray(self._policy_state)[0]
        obs_buf, act_buf, rew_buf, done_buf = [], [], [], []
        for _ in range(T):
            self.rng, key = _jax.random.split(self.rng)
            obs = self._obs_conn
            action, self._policy_state = self._sample(
                self.params, obs[None], key, self._policy_state, epsilon
            )
            action = int(np.asarray(action)[0])
            obs_buf.append(obs)
            act_buf.append(action)
            nxt, reward, terminated, truncated, _ = self.env.step(action)
            rew = self._reward(reward)
            self._advance(nxt, reward, terminated, truncated)
            if terminated or truncated:
                self._policy_state = self.module.initial_state(1)
            rew_buf.append(rew)
            done_buf.append(bool(terminated or truncated))
        return {
            "obs": np.stack(obs_buf).astype(np.float32),
            "actions": np.asarray(act_buf, dtype=np.int32),
            "rewards": np.asarray(rew_buf, dtype=np.float32),
            "dones": np.asarray(done_buf, dtype=np.float32),
            "state0": state0.astype(np.float32),
        }


class SequenceReplayBuffer:
    """Uniform ring buffer of windows (reference:
    rllib/utils/replay_buffers storing SampleBatch sequences)."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._items: list = []
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def add(self, window: Dict[str, np.ndarray]):
        if len(self._items) < self.capacity:
            self._items.append(window)
        else:
            self._items[self._next] = window
            self._next = (self._next + 1) % self.capacity

    def __len__(self):
        return len(self._items)

    def sample(self, n: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, len(self._items), size=n)
        return {
            k: np.stack([self._items[i][k] for i in idx])
            for k in self._items[0]
        }


def r2d2_update_fn(module, gamma: float, burn_in: int):
    """One jitted update over a [B, T] window batch.

    Burn-in: the first `burn_in` steps replay only to refresh the GRU
    state (their TD terms are masked out of the loss). Targets are
    within-window double-DQN: a* from the online replay at t+1, value
    from the target replay at t+1; the window's last step has no
    in-window successor and is masked too."""

    def loss_fn(params, target_params, batch):
        q_online = module.forward_seq(
            params, batch["obs"], batch["state0"], batch["dones"]
        )["q_values"]                                   # [B, T, A]
        q_target = module.forward_seq(
            target_params, batch["obs"], batch["state0"], batch["dones"]
        )["q_values"]
        q_taken = jnp.take_along_axis(
            q_online, batch["actions"][..., None].astype(jnp.int32), -1
        )[..., 0]                                       # [B, T]
        a_star = jnp.argmax(q_online[:, 1:], axis=-1)   # [B, T-1]
        next_v = jnp.take_along_axis(
            q_target[:, 1:], a_star[..., None], -1
        )[..., 0]
        r = batch["rewards"][:, :-1]
        nonterminal = 1.0 - batch["dones"][:, :-1]
        td_target = r + gamma * nonterminal * jax.lax.stop_gradient(next_v)
        td = q_taken[:, :-1] - td_target
        T = q_taken.shape[1]
        mask = (jnp.arange(T - 1) >= burn_in).astype(jnp.float32)[None, :]
        # Huber on the TD error (R2D2 uses clipped/rescaled losses; the
        # invertible value rescaling is omitted at these reward scales).
        huber = jnp.where(jnp.abs(td) <= 1.0, 0.5 * td ** 2,
                          jnp.abs(td) - 0.5)
        loss = (huber * mask).sum() / jnp.maximum(mask.sum() * td.shape[0], 1)
        return loss, {"td_loss": loss,
                      "q_mean": (q_taken[:, :-1] * mask).mean()}

    return loss_fn


@dataclass
class R2D2Config(ConfigEvalMixin):
    env_creator: Optional[Callable] = None
    obs_dim: int = 4
    num_actions: int = 2
    state_dim: int = 32
    hidden: tuple = (32,)
    num_env_runners: int = 2
    window_length: int = 16
    burn_in: int = 2
    buffer_capacity: int = 2000       # windows
    learning_starts: int = 32         # windows before updates begin
    train_batch_size: int = 16        # windows per update
    updates_per_iteration: int = 16
    target_update_freq: int = 2       # iterations between target syncs
    lr: float = 1e-3
    gamma: float = 0.99
    epsilon_start: float = 1.0
    epsilon_end: float = 0.05
    epsilon_decay_iters: int = 10
    seed: int = 0
    connectors_factory: Optional[Callable] = None

    def environment(self, env_creator=None, obs_dim=None, num_actions=None):
        if env_creator is not None:
            self.env_creator = env_creator
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        return self

    def env_runners(self, num_env_runners=None, window_length=None,
                    connectors_factory=None):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if window_length is not None:
            self.window_length = window_length
        if connectors_factory is not None:
            self.connectors_factory = connectors_factory
        return self

    def training(self, lr=None, gamma=None, train_batch_size=None,
                 updates_per_iteration=None, target_update_freq=None,
                 buffer_capacity=None, learning_starts=None, burn_in=None):
        for name, val in (
            ("lr", lr), ("gamma", gamma),
            ("train_batch_size", train_batch_size),
            ("updates_per_iteration", updates_per_iteration),
            ("target_update_freq", target_update_freq),
            ("buffer_capacity", buffer_capacity),
            ("learning_starts", learning_starts),
            ("burn_in", burn_in),
        ):
            if val is not None:
                setattr(self, name, val)
        return self

    def exploration(self, epsilon_start=None, epsilon_end=None,
                    epsilon_decay_iters=None):
        for name, val in (
            ("epsilon_start", epsilon_start),
            ("epsilon_end", epsilon_end),
            ("epsilon_decay_iters", epsilon_decay_iters),
        ):
            if val is not None:
                setattr(self, name, val)
        return self

    def build(self) -> "R2D2":
        return R2D2(self)


class R2D2(AlgorithmBase):
    def __init__(self, config: R2D2Config):
        assert config.env_creator is not None, "config.environment(...) first"
        import optax

        self.config = config
        spec = RecurrentModuleSpec(
            config.obs_dim, config.num_actions,
            state_dim=config.state_dim, hidden=config.hidden,
        )
        self.module = RecurrentQNetworkModule(spec)
        self._module_factory = lambda: RecurrentQNetworkModule(spec)  # noqa: E731
        self.params = self.module.init(jax.random.PRNGKey(config.seed))
        self.target_params = self.params
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(10.0), optax.adam(config.lr)
        )
        self.opt_state = self.optimizer.init(self.params)
        loss_fn = r2d2_update_fn(self.module, config.gamma, config.burn_in)

        def update(params, target_params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, target_params, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, metrics

        self._update = jax.jit(update)
        self.buffer = SequenceReplayBuffer(config.buffer_capacity,
                                           seed=config.seed)
        self.env_runners = [
            RecurrentWindowRunner.options(num_cpus=0.5).remote(
                config.env_creator,
                self._module_factory,
                seed=config.seed + 1 + i,
                rollout_length=config.window_length,
                connectors=(config.connectors_factory()
                            if config.connectors_factory else None),
                gamma=config.gamma,
            )
            for i in range(config.num_env_runners)
        ]
        self._iteration = 0
        self._broadcast_weights()

    # AlgorithmBase state hooks (save/restore without a LearnerGroup).
    def _get_learner_state(self):
        return {
            "params": jax.device_get(self.params),
            "target_params": jax.device_get(self.target_params),
            "opt_state": jax.device_get(self.opt_state),
        }

    def _set_learner_state(self, state):
        self.params = state["params"]
        self.target_params = state["target_params"]
        self.opt_state = state["opt_state"]
        self._broadcast_weights()

    def _current_weights(self):
        return jax.device_get(self.params)

    def _broadcast_weights(self):
        weights = jax.device_get(self.params)
        rt.get([r.set_weights.remote(weights) for r in self.env_runners],
               timeout=300)

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._iteration / max(cfg.epsilon_decay_iters, 1))
        return cfg.epsilon_start + frac * (cfg.epsilon_end -
                                           cfg.epsilon_start)

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        eps = self._epsilon()
        windows = rt.get(
            [r.sample.remote(eps) for r in self.env_runners], timeout=600
        )
        for w in windows:
            self.buffer.add(w)
        metrics: Dict[str, float] = {}
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iteration):
                batch = self.buffer.sample(cfg.train_batch_size)
                self.params, self.opt_state, m = self._update(
                    self.params, self.target_params, self.opt_state,
                    {k: jnp.asarray(v) for k, v in batch.items()},
                )
                metrics = {k: float(v) for k, v in m.items()}
        if self._iteration % cfg.target_update_freq == 0:
            self.target_params = self.params
        self._broadcast_weights()
        self._iteration += 1
        stats = rt.get(
            [r.episode_stats.remote() for r in self.env_runners], timeout=300
        )
        returns = [s["mean_return"] for s in stats if s["episodes"] > 0]
        return self._finish_iteration({
            "training_iteration": self._iteration,
            "episode_return_mean": float(np.mean(returns)) if returns else 0.0,
            "episodes_total": sum(s["episodes"] for s in stats),
            "buffer_windows": len(self.buffer),
            "epsilon": eps,
            **{f"learner/{k}": v for k, v in metrics.items()},
        })

    def stop(self):
        self.stop_eval_runners()
        for r in self.env_runners:
            try:
                rt.kill(r)
            except Exception:  # noqa: BLE001
                pass
