"""APEX-DQN: distributed prioritized experience replay (Horgan et al. 2018).

Reference analog: rllib/algorithms/apex_dqn — DQN scaled out by (a) many
env runners with a fixed per-runner exploration ladder
(eps_i = base ** (1 + 7 i/(N-1)), so some runners always explore hard
while others exploit), (b) the replay buffer sharded across dedicated
REPLAY ACTORS so insertion/sampling never contends with the driver, and
(c) asynchronous collection: runners sample continuously and the driver
routes whichever rollouts finish first to a shard (rt.wait), instead of
barriering on all runners each iteration.

The TD-update math (double-Q, n-step discounts, C51 projection, PER
weights) is inherited from DQN unchanged — only the replay plumbing is
swapped via the buffer interface hooks (_collect/_sample_minibatch/
_update_priorities/_buffer_size).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

import ray_tpu as rt
from ray_tpu.rl.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rl.replay import PrioritizedReplayBuffer


@rt.remote
class ReplayShardActor:
    """One shard of the distributed prioritized replay (reference: the
    ReplayActor rllib creates per apex replay shard)."""

    def __init__(self, capacity: int, obs_dim: int, seed: int, alpha: float):
        self.buf = PrioritizedReplayBuffer(
            capacity, obs_dim, seed=seed, alpha=alpha, store_discounts=True
        )

    def add_batch(self, batch) -> int:
        self.buf.add_batch(batch)
        return len(self.buf)

    def sample(self, n: int, beta: float):
        if len(self.buf) < n:
            return None
        return self.buf.sample(n, beta=beta)

    def update_priorities(self, indices, td_abs) -> bool:
        self.buf.update_priorities(np.asarray(indices), np.asarray(td_abs))
        return True

    def size(self) -> int:
        return len(self.buf)


@dataclass
class APEXConfig(DQNConfig):
    """APEX defaults: prioritized replay on, more runners, sharded buffer
    (reference: ApexDQNConfig)."""

    num_env_runners: int = 4
    num_replay_shards: int = 2
    prioritized_replay: bool = True
    # Exploration ladder base (Horgan et al.: eps_i = base^(1 + 7i/(N-1))).
    apex_eps_base: float = 0.4

    def build(self) -> "APEX":
        return APEX(self)


class APEX(DQN):
    def _make_buffer(self):
        # Replay lives in the shard actors; no driver-side buffer (avoids
        # a capacity-sized allocation that would be discarded).
        return None

    def __init__(self, config: APEXConfig):
        super().__init__(config)
        self.shards = [
            ReplayShardActor.options(num_cpus=0.1).remote(
                max(1, config.buffer_capacity // config.num_replay_shards),
                config.obs_shape if config.obs_shape is not None
                else config.obs_dim,
                config.seed + 1000 + i,
                config.per_alpha,
            )
            for i in range(config.num_replay_shards)
        ]
        n = config.num_env_runners
        self._runner_eps = [
            config.apex_eps_base ** (1 + 7 * i / max(n - 1, 1))
            for i in range(n)
        ]
        # Async collection state: one outstanding sample() per runner.
        self._pending = {
            r.sample.remote(self._runner_eps[i]): (r, i)
            for i, r in enumerate(self.env_runners)
        }
        self._shard_sizes = [0] * config.num_replay_shards
        self._next_shard = 0
        self._rng = np.random.default_rng(config.seed + 7)
        # Pipelined episode-stats probes + last-known stats per runner
        # (train() never barriers on a rollout to read stats).
        self._stats_refs: Dict[int, object] = {}
        self._stats_cache: Dict[int, dict] = {}
        # Leash for fire-and-forget calls: refs are kept (so the store
        # can release results and errors are observable) and reaped
        # non-blockingly once enough accumulate.
        self._async_refs: list = []

    def _track_async(self, ref):
        """Track a fire-and-forget ref without blocking the train loop.
        Dropping the ref outright would leak the result in the object
        store and swallow any error; a zero-timeout reap keeps both
        bounded while preserving the async design."""
        self._async_refs.append(ref)
        if len(self._async_refs) < 64:
            return
        ready, pending = rt.wait(
            self._async_refs, num_returns=len(self._async_refs), timeout=0
        )
        for r in ready:
            try:
                rt.get(r, timeout=1)
            except Exception:  # noqa: BLE001
                # Best-effort op (priority refresh / weight push) failed;
                # apex tolerates staleness, the next push retries.
                pass
        self._async_refs = list(pending)

    # -- buffer interface over the shard actors ---------------------------
    def _collect(self, eps: float):
        """Route whichever rollouts have finished to shards round-robin
        and immediately resubmit those runners; never barriers on the
        slowest runner (the iteration's epsilon argument is ignored —
        each runner keeps its ladder epsilon)."""
        # Invariant: every pop below resubmits, so one sample() per
        # runner is always outstanding.
        ready, _ = rt.wait(
            list(self._pending), num_returns=1, timeout=60.0
        )
        if not ready:
            return
        done = list(ready)
        rest = [r for r in self._pending if r not in set(done)]
        if rest:
            # Drain everything already finished, not just the first.
            more, _ = rt.wait(rest, num_returns=len(rest), timeout=0.0)
            done.extend(more)
        adds = []
        for ref in done:
            runner, idx = self._pending.pop(ref)
            try:
                batch = rt.get(ref, timeout=60)
            except Exception:  # noqa: BLE001 — runner died: resubmit
                # anyway so a restarted actor (max_restarts) rejoins the
                # pool; a permanently-dead one just errors again next
                # tick (bounded: one failed ref per collect pass).
                self._pending[
                    runner.sample.remote(self._runner_eps[idx])
                ] = (runner, idx)
                continue
            shard = self._next_shard % len(self.shards)
            self._next_shard += 1
            adds.append((shard, self.shards[shard].add_batch.remote(batch)))
            # Queue the stats probe BEFORE the next rollout so it runs
            # right away on the serial actor instead of waiting a full
            # rollout; train() reads whichever probes resolved.
            self._stats_refs[idx] = runner.episode_stats.remote()
            self._pending[
                runner.sample.remote(self._runner_eps[idx])
            ] = (runner, idx)
        for shard, ref in adds:
            try:
                self._shard_sizes[shard] = rt.get(ref, timeout=60)
            except Exception:  # noqa: BLE001
                pass

    def _buffer_size(self) -> int:
        return int(sum(self._shard_sizes))

    def _sample_minibatch(self, beta: float):
        shard = int(self._rng.integers(len(self.shards)))
        mb = rt.get(
            self.shards[shard].sample.remote(
                self.config.train_batch_size, beta
            ),
            timeout=60,
        )
        if mb is not None:
            mb["_shard"] = shard
        return mb

    def _update_priorities(self, mb, td_abs: np.ndarray):
        # Fire-and-forget: priority freshness is best-effort in apex.
        self._track_async(
            self.shards[mb["_shard"]].update_priorities.remote(
                mb["indices"], td_abs
            )
        )

    def _episode_stats(self):
        """Non-blocking: harvest whichever pipelined stats probes
        resolved; runners mid-rollout report their last-known stats."""
        refs = list(self._stats_refs.items())
        if refs:
            ready, _ = rt.wait(
                [r for _, r in refs], num_returns=len(refs), timeout=1.0
            )
            ready_set = set(ready)
            for idx, ref in refs:
                if ref in ready_set:
                    try:
                        self._stats_cache[idx] = rt.get(ref, timeout=30)
                    except Exception:  # noqa: BLE001 — runner died
                        pass
                    self._stats_refs.pop(idx, None)
        return list(self._stats_cache.values()) or [
            {"episodes": 0, "mean_return": 0.0}
        ]

    def _report_epsilon(self, eps: float):
        # Fixed per-runner ladder, not the DQN decay schedule.
        return [round(e, 4) for e in self._runner_eps]

    def _broadcast_weights(self, weights=None):
        """Fire-and-forget: the new weights queue behind each runner's
        in-flight rollout and apply to its NEXT one (apex's async weight
        update), without train() blocking on the slowest runner."""
        if not hasattr(self, "_runner_eps"):
            # Called from DQN.__init__ before apex state exists: the
            # blocking broadcast is fine there (no rollouts in flight).
            return super()._broadcast_weights(weights)
        if weights is None:
            weights = self.learner_group.get_weights()
        for r in self.env_runners:
            self._track_async(r.set_weights.remote(weights))

    # Note: shard CONTENTS are not checkpointed (fresh shard actors start
    # empty on restore), so _shard_sizes deliberately restarts at 0 — the
    # learning_starts warmup gate re-applies after a restore, exactly as
    # the reference's apex restore refills its replay actors.

    def stop(self):
        super().stop()
        for s in self.shards:
            try:
                rt.kill(s)
            except Exception:  # noqa: BLE001
                pass
