"""CQL: conservative Q-learning for offline continuous control.

Reference analog: rllib/algorithms/cql/ (CQLConfig/CQL layered on SAC).
Kumar et al. 2020's CQL(H): the SAC twin-critic update plus a
conservative penalty that pushes Q down on out-of-distribution actions
(importance-sampled logsumexp over random + policy actions) and up on
dataset actions, so the learned Q never over-estimates actions the
dataset can't support. Like the reference it is offline-first: training
consumes a transition Dataset (episodes_to_dataset rows with
obs/actions/rewards/next_obs/dones), no env interaction.

TPU framing: the entire update — twin-critic TD + conservative penalty
(3K candidate-action Q evaluations batched as one (3K*B, obs+act) tower
pass), reparameterized actor, auto temperature, polyak sync — is ONE
jitted function over a state pytree, so a learner step is a single
device program.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.core.rl_module import (
    ContinuousModuleSpec,
    ContinuousPolicyModule,
)


def make_cql_update(module: ContinuousPolicyModule, pi_tx, q_tx, alpha_tx,
                    gamma: float, tau: float, target_entropy: float,
                    cql_alpha: float, num_candidates: int):
    """Builds the jitted CQL update: state pytree in, state pytree out."""

    d = module.spec.action_dim
    log_unif = -d * jnp.log(2.0)  # uniform density over [-1, 1]^d

    def _tiled_q(params, qp, obs, actions):
        """Q towers over K candidate actions per state: actions is
        (K, B, d); returns two (K, B) value grids in one tower pass."""
        K, B = actions.shape[0], actions.shape[1]
        obs_t = jnp.broadcast_to(obs[None], (K, B, obs.shape[-1]))
        q1, q2 = module.q_values(
            {**params, **qp},
            obs_t.reshape(K * B, -1), actions.reshape(K * B, -1),
        )
        return q1.reshape(K, B), q2.reshape(K, B)

    def update(state, batch, rng):
        params, target = state["params"], state["target"]
        log_alpha = state["log_alpha"]
        alpha = jnp.exp(log_alpha)
        k_next, k_pi, k_rand, k_cur, k_nxt = jax.random.split(rng, 5)
        B = batch["obs"].shape[0]
        K = num_candidates

        # -- twin critic TD loss against the soft target ------------------
        next_a, next_logp = module.sample_with_logp(
            params, batch["next_obs"], k_next
        )
        tq1, tq2 = module.q_values(
            {**params, "q1": target["q1"], "q2": target["q2"]},
            batch["next_obs"], next_a,
        )
        soft_next = jnp.minimum(tq1, tq2) - alpha * next_logp
        td_target = jax.lax.stop_gradient(
            batch["rewards"] + gamma * (1.0 - batch["dones"]) * soft_next
        )

        # -- conservative candidate actions (sampled outside the q grad) --
        a_rand = jax.random.uniform(k_rand, (K, B, d), minval=-1.0,
                                    maxval=1.0)
        def per_key(k, obs):
            return module.sample_with_logp(params, obs, k)

        a_cur, logp_cur = jax.vmap(per_key, in_axes=(0, None))(
            jax.random.split(k_cur, K), batch["obs"]
        )
        a_nxt, logp_nxt = jax.vmap(per_key, in_axes=(0, None))(
            jax.random.split(k_nxt, K), batch["next_obs"]
        )
        a_cur = jax.lax.stop_gradient(a_cur)
        a_nxt = jax.lax.stop_gradient(a_nxt)
        logp_cur = jax.lax.stop_gradient(logp_cur)
        logp_nxt = jax.lax.stop_gradient(logp_nxt)

        def q_loss_fn(qp):
            q1, q2 = module.q_values(
                {**params, **qp}, batch["obs"], batch["actions"]
            )
            td_loss = ((q1 - td_target) ** 2).mean() + (
                (q2 - td_target) ** 2
            ).mean()
            # CQL(H) penalty: importance-sampled logsumexp over
            # {uniform, pi(.|s), pi(.|s')} candidates minus dataset Q.
            r1, r2 = _tiled_q(params, qp, batch["obs"], a_rand)
            c1, c2 = _tiled_q(params, qp, batch["obs"], a_cur)
            n1, n2 = _tiled_q(params, qp, batch["obs"], a_nxt)
            cat1 = jnp.concatenate(
                [r1 - log_unif, c1 - logp_cur, n1 - logp_nxt], axis=0
            )
            cat2 = jnp.concatenate(
                [r2 - log_unif, c2 - logp_cur, n2 - logp_nxt], axis=0
            )
            gap1 = (jax.nn.logsumexp(cat1, axis=0) - jnp.log(3 * K) - q1)
            gap2 = (jax.nn.logsumexp(cat2, axis=0) - jnp.log(3 * K) - q2)
            cql_loss = cql_alpha * (gap1.mean() + gap2.mean())
            return td_loss + cql_loss, (td_loss, cql_loss)

        qp = {"q1": params["q1"], "q2": params["q2"]}
        (q_loss, (td_loss, cql_loss)), q_grads = jax.value_and_grad(
            q_loss_fn, has_aux=True
        )(qp)
        q_updates, q_opt = q_tx.update(q_grads, state["q_opt"], qp)
        qp = optax.apply_updates(qp, q_updates)

        # -- actor loss (reparameterized, against the UPDATED critics) ----
        def pi_loss_fn(pi_params):
            a, logp = module.sample_with_logp(
                {**params, "pi": pi_params}, batch["obs"], k_pi
            )
            q1, q2 = module.q_values({**params, **qp}, batch["obs"], a)
            return (alpha * logp - jnp.minimum(q1, q2)).mean(), logp

        (pi_loss, logp), pi_grads = jax.value_and_grad(
            pi_loss_fn, has_aux=True
        )(params["pi"])
        pi_updates, pi_opt = pi_tx.update(pi_grads, state["pi_opt"],
                                          params["pi"])
        pi_params = optax.apply_updates(params["pi"], pi_updates)

        # -- automatic temperature ---------------------------------------
        def alpha_loss_fn(la):
            return -(
                jnp.exp(la)
                * jax.lax.stop_gradient(logp + target_entropy)
            ).mean()

        alpha_loss, a_grad = jax.value_and_grad(alpha_loss_fn)(log_alpha)
        a_update, alpha_opt = alpha_tx.update(
            a_grad, state["alpha_opt"], log_alpha
        )
        log_alpha = optax.apply_updates(log_alpha, a_update)

        # -- polyak target sync ------------------------------------------
        new_target = jax.tree.map(
            lambda t, o: (1.0 - tau) * t + tau * o,
            target, {"q1": qp["q1"], "q2": qp["q2"]},
        )
        new_state = {
            "params": {"pi": pi_params, **qp},
            "target": new_target,
            "log_alpha": log_alpha,
            "pi_opt": pi_opt,
            "q_opt": q_opt,
            "alpha_opt": alpha_opt,
        }
        metrics = {
            "q_loss": q_loss,
            "td_loss": td_loss,
            "cql_loss": cql_loss,
            "actor_loss": pi_loss,
            "alpha_loss": alpha_loss,
            "alpha": jnp.exp(log_alpha),
            "entropy": -logp.mean(),
        }
        return new_state, metrics

    return jax.jit(update)


@dataclass
class CQLConfig:
    """Builder-style config (reference: CQLConfig extends SACConfig)."""

    obs_dim: int = 3
    action_dim: int = 1
    action_low: float = -1.0
    action_high: float = 1.0
    hidden: tuple = (64, 64)
    lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.005
    target_entropy: Optional[float] = None  # default: -action_dim
    cql_alpha: float = 5.0
    num_candidate_actions: int = 4  # K per candidate family (3K total)
    minibatch_size: int = 128
    seed: int = 0

    def module(self, obs_dim=None, action_dim=None, action_low=None,
               action_high=None, hidden=None):
        for k, v in (("obs_dim", obs_dim), ("action_dim", action_dim),
                     ("action_low", action_low),
                     ("action_high", action_high), ("hidden", hidden)):
            if v is not None:
                setattr(self, k, v)
        return self

    def training(self, lr=None, gamma=None, tau=None, cql_alpha=None,
                 num_candidate_actions=None, minibatch_size=None,
                 target_entropy=None):
        for k, v in (("lr", lr), ("gamma", gamma), ("tau", tau),
                     ("cql_alpha", cql_alpha),
                     ("num_candidate_actions", num_candidate_actions),
                     ("minibatch_size", minibatch_size),
                     ("target_entropy", target_entropy)):
            if v is not None:
                setattr(self, k, v)
        return self

    def build(self) -> "CQL":
        return CQL(self)


class CQL:
    """Offline conservative Q-learning over a transition Dataset.

    Rows need obs/actions/rewards/next_obs/dones (normalized [-1, 1]
    actions, as ContinuousTransitionRunner stores and
    episodes_to_dataset preserves).
    """

    _BATCH_KEYS = ("obs", "actions", "rewards", "next_obs", "dones")

    def __init__(self, config: CQLConfig):
        self.config = config
        spec = ContinuousModuleSpec(
            config.obs_dim, config.action_dim,
            config.action_low, config.action_high, config.hidden,
        )
        self.module = ContinuousPolicyModule(spec)
        params = self.module.init(jax.random.PRNGKey(config.seed))
        pi_tx = optax.adam(config.lr)
        q_tx = optax.adam(config.lr)
        alpha_tx = optax.adam(config.lr)
        qp = {"q1": params["q1"], "q2": params["q2"]}
        self.state = {
            "params": params,
            "target": jax.tree.map(lambda x: x, qp),
            "log_alpha": jnp.asarray(0.0),
            "pi_opt": pi_tx.init(params["pi"]),
            "q_opt": q_tx.init(qp),
            "alpha_opt": alpha_tx.init(jnp.asarray(0.0)),
        }
        tgt_ent = (
            config.target_entropy
            if config.target_entropy is not None
            else -float(config.action_dim)
        )
        self._update = make_cql_update(
            self.module, pi_tx, q_tx, alpha_tx,
            config.gamma, config.tau, tgt_ent,
            config.cql_alpha, config.num_candidate_actions,
        )
        self._rng = jax.random.PRNGKey(config.seed + 99)
        self._np_rng = np.random.default_rng(config.seed)

    def train_on_batch(self, batch: Dict[str, np.ndarray],
                       num_epochs: int = 1) -> Dict[str, float]:
        """Minibatch epochs of the jitted CQL update over materialized
        transition arrays."""
        n = len(batch["obs"])
        metrics = {}
        for _ in range(num_epochs):
            order = self._np_rng.permutation(n)
            for s in range(0, n, self.config.minibatch_size):
                idx = order[s:s + self.config.minibatch_size]
                mb = {
                    k: jnp.asarray(batch[k][idx]) for k in self._BATCH_KEYS
                }
                self._rng, key = jax.random.split(self._rng)
                self.state, m = self._update(self.state, mb, key)
            metrics = {k: float(v) for k, v in m.items()}
        return metrics

    def train_on_dataset(self, ds, num_epochs: int = 1) -> Dict[str, float]:
        """Streaming epochs through the Dataset executor (the reference's
        OfflineData iter_batches loop)."""
        metrics: Dict[str, float] = {}
        for epoch in range(num_epochs):
            shuffled = ds.random_shuffle(seed=self.config.seed + epoch)
            for batch in shuffled.iter_batches(
                batch_size=self.config.minibatch_size, batch_format="numpy"
            ):
                mb = {
                    "obs": np.stack([
                        np.asarray(o, dtype=np.float32) for o in batch["obs"]
                    ]),
                    "actions": np.stack([
                        np.asarray(a, dtype=np.float32)
                        for a in batch["actions"]
                    ]),
                    "rewards": np.asarray(
                        [float(r) for r in batch["rewards"]],
                        dtype=np.float32,
                    ),
                    "next_obs": np.stack([
                        np.asarray(o, dtype=np.float32)
                        for o in batch["next_obs"]
                    ]),
                    "dones": np.asarray(
                        [float(x) for x in batch["dones"]], dtype=np.float32
                    ),
                }
                jb = {k: jnp.asarray(v) for k, v in mb.items()}
                self._rng, key = jax.random.split(self._rng)
                self.state, m = self._update(self.state, jb, key)
                metrics = {k: float(v) for k, v in m.items()}
        return metrics

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        """Deterministic (scaled) policy actions for evaluation."""
        a_norm = self.module.deterministic_action(
            self.state["params"], jnp.asarray(obs)
        )
        return np.asarray(self.module.scale_action(a_norm))
