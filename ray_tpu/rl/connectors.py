"""Connectors: composable observation/action transform pipelines.

Analog of the reference's connector framework (rllib/connectors/, ~4k LoC
of env-to-module and module-to-env pipelines). Connectors sit between the
env and the policy inside env runners so preprocessing (flattening,
normalization, reward clipping) is part of the sampling path and the
exact transformed observations land in the training batch — the learner
never needs to replicate the transform.

Stateful connectors (running normalization) expose get_state/set_state so
their statistics can ship with checkpoints or merge across runners, the
reference's connector-state sync shape.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class Connector:
    """One transform stage. Subclasses override __call__ (obs -> obs)."""

    def __call__(self, obs: np.ndarray) -> np.ndarray:  # pragma: no cover
        raise NotImplementedError

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass


class FlattenObs(Connector):
    """Flattens any observation shape to a 1-D float32 vector (reference:
    the flatten-observations default connector)."""

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(obs, dtype=np.float32).reshape(-1)


class NormalizeObs(Connector):
    """Running mean/std observation normalization (Welford update).

    Reference analog: MeanStdFilter / the normalize-observations
    connector. Stats update on every observation seen during sampling;
    the normalized obs is what lands in the batch, so the learner sees a
    consistent distribution without needing the stats itself.
    """

    def __init__(self, clip: float = 10.0, eps: float = 1e-8):
        self.clip = clip
        self.eps = eps
        self.count = 0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs, dtype=np.float32)
        if self.mean is None:
            self.mean = np.zeros_like(obs, dtype=np.float64)
            self.m2 = np.zeros_like(obs, dtype=np.float64)
        self.count += 1
        delta = obs - self.mean
        self.mean = self.mean + delta / self.count
        self.m2 = self.m2 + delta * (obs - self.mean)
        if self.count < 2:
            return np.clip(obs, -self.clip, self.clip).astype(np.float32)
        std = np.sqrt(self.m2 / (self.count - 1)) + self.eps
        return np.clip(
            (obs - self.mean) / std, -self.clip, self.clip
        ).astype(np.float32)

    def get_state(self) -> Dict[str, Any]:
        return {"count": self.count, "mean": self.mean, "m2": self.m2}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.count = state["count"]
        self.mean = state["mean"]
        self.m2 = state["m2"]


class ClipReward(Connector):
    """Clips rewards to [-bound, bound]; applied via transform_reward
    (reference: the clip-rewards connector / config.clip_rewards)."""

    def __init__(self, bound: float = 1.0):
        self.bound = bound

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        return obs  # identity on observations

    def transform_reward(self, reward: float) -> float:
        return float(np.clip(reward, -self.bound, self.bound))


class ConnectorPipeline:
    """Ordered connector list applied obs -> obs; rewards pass through
    every stage that defines transform_reward."""

    def __init__(self, connectors: List[Connector]):
        self.connectors = list(connectors)

    def __call__(self, obs) -> np.ndarray:
        out = np.asarray(obs, dtype=np.float32)
        for c in self.connectors:
            out = c(out)
        return out

    def transform_reward(self, reward: float) -> float:
        for c in self.connectors:
            fn = getattr(c, "transform_reward", None)
            if fn is not None:
                reward = fn(reward)
        return float(reward)

    def get_state(self) -> List[Dict[str, Any]]:
        return [c.get_state() for c in self.connectors]

    def set_state(self, states: List[Dict[str, Any]]) -> None:
        for c, s in zip(self.connectors, states):
            c.set_state(s)
