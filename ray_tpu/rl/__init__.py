"""ray_tpu.rl: reinforcement learning on TPU learner actors.

The reference's RLlib capability rebuilt on the new-API-stack shape
(SURVEY.md §2.3: Algorithm / Learner / LearnerGroup / RLModule /
EnvRunner), JAX-first: modules are pure functions, learner updates are
jit-compiled, multi-learner sync is collective-based.
"""

from ray_tpu.rl.core.learner import Learner
from ray_tpu.rl.core.learner_group import LearnerGroup
from ray_tpu.rl.core.rl_module import (
    ContinuousModuleSpec,
    ContinuousPolicyModule,
    ConvModuleSpec,
    ConvPolicyModule,
    ConvQNetworkModule,
    DiscretePolicyModule,
    C51QNetworkModule,
    DuelingQNetworkModule,
    NoisyQNetworkModule,
    RecurrentModuleSpec,
    RecurrentPolicyModule,
    RecurrentQNetworkModule,
    RLModuleSpec,
)
from ray_tpu.rl.algorithms.recurrent_ppo import (
    RecurrentPPO,
    RecurrentPPOConfig,
    recurrent_ppo_loss,
)
from ray_tpu.rl.algorithms.r2d2 import R2D2, R2D2Config
from ray_tpu.rl.env_runner import (
    ContinuousTransitionRunner,
    EnvRunner,
    VectorEnvRunner,
    compute_gae,
)
from ray_tpu.rl.algorithms.appo import APPO, APPOConfig, appo_loss
from ray_tpu.rl.algorithms.dqn import (
    DQN,
    DQNConfig,
    c51_loss,
    categorical_projection,
    dqn_loss,
    noisy_dqn_loss,
)
from ray_tpu.rl.algorithms.sac import SAC, SACConfig
from ray_tpu.rl.algorithms.apex import (
    APEX,
    APEXConfig,
    ReplayShardActor,
)
from ray_tpu.rl.algorithms.cql import CQL, CQLConfig
from ray_tpu.rl.algorithms.td3 import DDPGConfig, TD3, TD3Config
from ray_tpu.rl.algorithms.impala import (
    IMPALA,
    IMPALAConfig,
    impala_loss,
    vtrace,
)
from ray_tpu.rl.algorithms.ppo import (
    A2CConfig,
    PPO,
    PPOConfig,
    a2c_loss,
    ppo_loss,
)
from ray_tpu.rl.connectors import (
    ClipReward,
    Connector,
    ConnectorPipeline,
    FlattenObs,
    NormalizeObs,
)
from ray_tpu.rl.env_runner import TransitionEnvRunner
from ray_tpu.rl.multi_agent import (
    MultiAgentEnv,
    MultiAgentEnvRunner,
    MultiAgentPPO,
    MultiAgentPPOConfig,
    MultiRLModule,
)
from ray_tpu.rl.offline import (
    BC,
    BCConfig,
    MARWIL,
    MARWILConfig,
    bc_loss,
    dataset_to_batch,
    episodes_to_dataset,
)
from ray_tpu.rl.replay import (
    PrioritizedReplayBuffer,
    ReplayBuffer,
    n_step_transitions,
)

__all__ = [
    "SAC",
    "SACConfig",
    "TD3",
    "TD3Config",
    "CQL",
    "CQLConfig",
    "APEX",
    "APEXConfig",
    "ReplayShardActor",
    "DDPGConfig",
    "ContinuousModuleSpec",
    "ContinuousPolicyModule",
    "ContinuousTransitionRunner",
    "APPO",
    "APPOConfig",
    "appo_loss",
    "VectorEnvRunner",
    "Learner",
    "LearnerGroup",
    "RLModuleSpec",
    "ConvModuleSpec",
    "ConvPolicyModule",
    "ConvQNetworkModule",
    "DiscretePolicyModule",
    "RecurrentModuleSpec",
    "RecurrentPolicyModule",
    "RecurrentQNetworkModule",
    "RecurrentPPO",
    "RecurrentPPOConfig",
    "R2D2",
    "R2D2Config",
    "recurrent_ppo_loss",
    "DuelingQNetworkModule",
    "EnvRunner",
    "compute_gae",
    "DQN",
    "DQNConfig",
    "dqn_loss",
    "ReplayBuffer",
    "PrioritizedReplayBuffer",
    "n_step_transitions",
    "TransitionEnvRunner",
    "PPO",
    "PPOConfig",
    "ppo_loss",
    "A2CConfig",
    "a2c_loss",
    "c51_loss",
    "categorical_projection",
    "C51QNetworkModule",
    "NoisyQNetworkModule",
    "noisy_dqn_loss",
    "IMPALA",
    "IMPALAConfig",
    "impala_loss",
    "vtrace",
    "MultiAgentEnv",
    "MultiAgentEnvRunner",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "MultiRLModule",
    "Connector",
    "ConnectorPipeline",
    "FlattenObs",
    "NormalizeObs",
    "ClipReward",
    "BC",
    "BCConfig",
    "MARWIL",
    "MARWILConfig",
    "bc_loss",
    "episodes_to_dataset",
    "dataset_to_batch",
]
