"""Env runners: collect experience with the current policy.

Analog of the reference's EnvRunner/SingleAgentEnvRunner
(rllib/env/env_runner.py, env/single_agent_env_runner.py:29): actors that
step gymnasium envs with the current weights and return sample batches
(obs/actions/logp/values/rewards/dones arranged for GAE).

Truncation semantics (gymnasium): a truncated episode ends but its final
state still has value. The on-policy runner folds that value into the
last reward — reward += gamma * V(s_final) — and marks the step done,
which is algebraically identical to bootstrapping for both GAE and
V-trace while keeping the batch schema flat. The off-policy runner
instead ships the true next_obs with dones = terminated-only, which is
already exact for Q targets.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

import ray_tpu as rt


class EpisodeTracker:
    """Episode return bookkeeping shared by all runner flavors."""

    def __init__(self):
        self.current = 0.0
        self.returns: list = []

    def add(self, reward: float):
        self.current += float(reward)

    def end_episode(self):
        self.returns.append(self.current)
        self.current = 0.0

    def stats(self) -> Dict[str, Any]:
        return {
            "episodes": len(self.returns),
            "mean_return": (
                float(np.mean(self.returns[-20:])) if self.returns else 0.0
            ),
        }


class _EnvRunnerBase:
    """Shared env-runner scaffolding: env/module setup, weight sync, lazy
    jitted sampler, connector pipeline, episode bookkeeping. Subclasses
    implement sample()."""

    def __init__(self, env_creator, module_factory, seed: int = 0,
                 rollout_length: int = 200, connectors=None,
                 gamma: float = 0.99):
        import jax

        self.env = env_creator()
        self.module = module_factory()
        self.rollout_length = rollout_length
        self.gamma = gamma
        self.rng = jax.random.PRNGKey(seed)
        self.params = None
        self.connectors = connectors  # ConnectorPipeline or None
        self._obs = None        # raw current observation
        self._obs_conn = None   # its connected form (computed exactly once)
        self._tracker = EpisodeTracker()
        self._sample = None  # jitted sampler

    def _connect(self, obs) -> np.ndarray:
        """Env-to-module connector pass (identity when unconfigured)."""
        if self.connectors is None:
            return np.asarray(obs, dtype=np.float32)
        return self.connectors(obs)

    def _reward(self, reward: float) -> float:
        if self.connectors is None:
            return float(reward)
        return self.connectors.transform_reward(float(reward))

    def get_connector_state(self):
        return None if self.connectors is None else self.connectors.get_state()

    def set_weights(self, weights):
        self.params = weights
        return True

    def _set_obs(self, raw):
        """Install a new current observation, connecting it exactly once
        (stateful connectors like NormalizeObs must see each state once)."""
        self._obs = raw
        self._obs_conn = self._connect(raw)

    def _begin_rollout(self):
        import jax

        assert self.params is not None, "set_weights first"
        if self._sample is None:
            self._sample = jax.jit(self.module.sample_action)
        if self._obs is None:
            obs, _ = self.env.reset()
            self._set_obs(obs)

    def _advance(self, nxt, reward, terminated, truncated) -> np.ndarray:
        """Track episode returns and install the next observation. Returns
        the connected form of the true successor state (on episode end,
        that's `nxt` connected once; the env is then reset)."""
        self._tracker.add(reward)
        if terminated or truncated:
            nxt_conn = self._connect(nxt)
            self._tracker.end_episode()
            obs, _ = self.env.reset()
            self._set_obs(obs)
        else:
            self._set_obs(nxt)
            nxt_conn = self._obs_conn
        return nxt_conn

    def episode_stats(self) -> Dict[str, Any]:
        return self._tracker.stats()

    # -- checkpoint support (Algorithm.save/restore) ---------------------
    def get_runner_state(self) -> Dict[str, Any]:
        """Everything needed to resume sampling bit-exactly: RNG key,
        current observation (raw + connected — reconnecting would
        double-count stateful connector statistics), episode tracker,
        connector pipeline, and the env itself when it pickles."""
        import cloudpickle

        state = {
            "rng": np.asarray(self.rng),
            "obs": self._obs,
            "obs_conn": self._obs_conn,
            "tracker": cloudpickle.dumps(self._tracker),
            "connectors": (cloudpickle.dumps(self.connectors)
                           if self.connectors is not None else None),
        }
        try:
            state["env"] = cloudpickle.dumps(self.env)
        except Exception:  # noqa: BLE001 — unpicklable env: fresh on restore
            state["env"] = None
        return state

    def set_runner_state(self, state: Dict[str, Any]):
        import cloudpickle
        import jax.numpy as jnp

        self.rng = jnp.asarray(state["rng"])
        self._tracker = cloudpickle.loads(state["tracker"])
        if state.get("connectors") is not None:
            self.connectors = cloudpickle.loads(state["connectors"])
        if state.get("env") is not None:
            try:
                self.env = cloudpickle.loads(state["env"])
            except Exception:  # noqa: BLE001 — keep the fresh env
                pass
        self._obs = state.get("obs")
        self._obs_conn = state.get("obs_conn")
        return True


@rt.remote
class EnvRunner(_EnvRunnerBase):
    def sample(self) -> Dict[str, np.ndarray]:
        """One fixed-length rollout. Mid-rollout truncations bootstrap by
        folding gamma * V(s_final) into the reward (see module docstring);
        the rollout-end cut bootstraps via `last_value`/`last_obs`."""
        import jax

        self._begin_rollout()
        T = self.rollout_length
        obs_buf, act_buf, logp_buf, val_buf = [], [], [], []
        rew_buf, done_buf = [], []
        for _ in range(T):
            self.rng, key = jax.random.split(self.rng)
            obs = self._obs_conn
            action, logp, value = self._sample(self.params, obs[None], key)
            action = int(np.asarray(action)[0])
            obs_buf.append(obs)
            act_buf.append(action)
            logp_buf.append(float(np.asarray(logp)[0]))
            val_buf.append(float(np.asarray(value)[0]))
            nxt, reward, terminated, truncated, _ = self.env.step(action)
            rew = self._reward(reward)
            nxt_conn = self._advance(nxt, reward, terminated, truncated)
            if truncated and not terminated:
                # The episode was cut by a time limit, not by reaching a
                # terminal state: bootstrap its tail value into the reward.
                self.rng, key = jax.random.split(self.rng)
                _, _, v_final = self._sample(
                    self.params, nxt_conn[None], key
                )
                rew += self.gamma * float(np.asarray(v_final)[0])
            rew_buf.append(rew)
            done_buf.append(bool(terminated or truncated))
        # Bootstrap value of the final observation. last_obs also ships so
        # off-policy consumers (V-trace) can re-bootstrap under the
        # *learner's* current params rather than the behavior policy's.
        obs = self._obs_conn
        self.rng, key = jax.random.split(self.rng)
        _, _, last_value = self._sample(self.params, obs[None], key)
        return {
            "obs": np.stack(obs_buf),
            "actions": np.asarray(act_buf, dtype=np.int32),
            "logp": np.asarray(logp_buf, dtype=np.float32),
            "values": np.asarray(val_buf, dtype=np.float32),
            "rewards": np.asarray(rew_buf, dtype=np.float32),
            "dones": np.asarray(done_buf, dtype=np.float32),
            "last_value": float(np.asarray(last_value)[0]),
            "last_obs": obs,
        }


def compute_gae(batch: Dict[str, np.ndarray], gamma: float = 0.99,
                lam: float = 0.95) -> Dict[str, np.ndarray]:
    """Generalized advantage estimation over one rollout."""
    rewards, values, dones = batch["rewards"], batch["values"], batch["dones"]
    T = len(rewards)
    adv = np.zeros(T, dtype=np.float32)
    last_gae = 0.0
    next_value = batch["last_value"]
    for t in reversed(range(T)):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_value = values[t]
    out = dict(batch)
    out["advantages"] = adv
    out["returns"] = adv + values
    return out


@rt.remote
class TransitionEnvRunner(_EnvRunnerBase):
    """Collects (s, a, r, s', done) transitions with epsilon-greedy
    exploration for off-policy algorithms (DQN family).

    Reference analog: SingleAgentEnvRunner in off-policy mode feeding
    replay buffers (rllib/env/single_agent_env_runner.py:29). Truncation
    needs no special handling here: next_obs is the true successor and
    dones records terminated-only, so Q targets bootstrap correctly
    through time limits.

    With n_step > 1 the rollout is collapsed into n-step transitions
    before shipping (windows cut at episode ends, per-transition
    bootstrap ``discounts`` = gamma**m) — the reference applies the same
    transform learner-side via its n-step connector.
    """

    def __init__(self, env_creator, module_factory, seed: int = 0,
                 rollout_length: int = 200, connectors=None,
                 gamma: float = 0.99, n_step: int = 1):
        super().__init__(env_creator, module_factory, seed=seed,
                         rollout_length=rollout_length,
                         connectors=connectors, gamma=gamma)
        self.n_step = n_step

    def sample(self, epsilon: float = 0.1) -> Dict[str, np.ndarray]:
        import jax

        self._begin_rollout()
        T = self.rollout_length
        obs_buf, act_buf, rew_buf, next_buf = [], [], [], []
        done_buf, end_buf = [], []
        for _ in range(T):
            self.rng, key = jax.random.split(self.rng)
            obs = self._obs_conn
            action = int(np.asarray(
                self._sample(self.params, obs[None], key, epsilon)
            )[0])
            nxt, reward, terminated, truncated, _ = self.env.step(action)
            obs_buf.append(obs)
            act_buf.append(action)
            rew_buf.append(self._reward(reward))
            done_buf.append(bool(terminated))
            end_buf.append(bool(terminated or truncated))
            # next_obs passes the same connector pipeline as obs (Q targets
            # would otherwise mix distributions); _advance connects each
            # successor state exactly once.
            next_buf.append(self._advance(nxt, reward, terminated, truncated))
        batch = {
            "obs": np.stack(obs_buf),
            "actions": np.asarray(act_buf, dtype=np.int32),
            "rewards": np.asarray(rew_buf, dtype=np.float32),
            "next_obs": np.stack(next_buf),
            "dones": np.asarray(done_buf, dtype=np.float32),
        }
        from ray_tpu.rl.replay import n_step_transitions

        return n_step_transitions(
            batch, np.asarray(end_buf, dtype=bool), self.n_step, self.gamma
        )


@rt.remote
class VectorEnvRunner:
    """N envs stepped in lockstep with ONE batched policy call per step.

    The reference reaches vectorized sampling via gym vector envs inside
    an EnvRunner (rllib/env/single_agent_env_runner.py with
    num_envs_per_env_runner > 1). TPU framing: the policy is a jitted
    batch function, so stepping N envs costs one (N, obs_dim) device
    call instead of N scalar calls — host<->device traffic per
    environment step drops by N.

    sample() returns time-major arrays shaped (T, N, ...) plus per-env
    bootstrap values, which transpose directly into the (B=N, T) layout
    the V-trace losses consume.
    """

    def __init__(self, env_creator, module_factory, num_envs: int = 8,
                 seed: int = 0, rollout_length: int = 50,
                 gamma: float = 0.99):
        import jax

        self.envs = [env_creator() for _ in range(num_envs)]
        self.module = module_factory()
        self.num_envs = num_envs
        self.rollout_length = rollout_length
        self.gamma = gamma
        self.rng = jax.random.PRNGKey(seed)
        self._seed0 = seed
        self.params = None
        self._sample = None
        self._trackers = [EpisodeTracker() for _ in range(num_envs)]
        self._obs: Optional[np.ndarray] = None  # (N, obs_dim)

    def set_weights(self, weights):
        self.params = weights
        return True

    def _reset_env(self, i: int) -> np.ndarray:
        obs, _ = self.envs[i].reset(seed=self._seed0 * 10_000 + i)
        self._seed0 += 1
        return np.asarray(obs, dtype=np.float32)

    def sample(self) -> Dict[str, np.ndarray]:
        import jax

        assert self.params is not None, "set_weights first"
        if self._sample is None:
            self._sample = jax.jit(self.module.sample_action)
        N, T = self.num_envs, self.rollout_length
        if self._obs is None:
            self._obs = np.stack([self._reset_env(i) for i in range(N)])
        obs_dim = self._obs.shape[1]
        obs_buf = np.empty((T, N, obs_dim), dtype=np.float32)
        act_buf = np.empty((T, N), dtype=np.int32)
        logp_buf = np.empty((T, N), dtype=np.float32)
        val_buf = np.empty((T, N), dtype=np.float32)
        rew_buf = np.empty((T, N), dtype=np.float32)
        done_buf = np.empty((T, N), dtype=np.float32)
        for t in range(T):
            self.rng, key = jax.random.split(self.rng)
            actions, logp, values = self._sample(self.params, self._obs, key)
            actions = np.asarray(actions)
            obs_buf[t] = self._obs
            act_buf[t] = actions
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(values)
            next_obs = self._obs.copy()
            trunc_pending = []  # (env_idx, connected next obs)
            for i in range(N):
                nxt, reward, terminated, truncated, _ = self.envs[i].step(
                    int(actions[i])
                )
                self._trackers[i].add(float(reward))
                rew_buf[t, i] = float(reward)
                done_buf[t, i] = float(terminated or truncated)
                if terminated or truncated:
                    if truncated and not terminated:
                        trunc_pending.append(
                            (i, np.asarray(nxt, dtype=np.float32))
                        )
                    self._trackers[i].end_episode()
                    next_obs[i] = self._reset_env(i)
                else:
                    next_obs[i] = np.asarray(nxt, dtype=np.float32)
            if trunc_pending:
                # Time-limit cuts bootstrap gamma*V(s_final) into the
                # reward — ONE batched call for every truncated env.
                self.rng, key = jax.random.split(self.rng)
                finals = np.stack([o for _, o in trunc_pending])
                _, _, v_fin = self._sample(self.params, finals, key)
                v_fin = np.asarray(v_fin)
                for j, (i, _) in enumerate(trunc_pending):
                    rew_buf[t, i] += self.gamma * float(v_fin[j])
            self._obs = next_obs
        self.rng, key = jax.random.split(self.rng)
        _, _, last_values = self._sample(self.params, self._obs, key)
        return {
            "obs": obs_buf,
            "actions": act_buf,
            "logp": logp_buf,
            "values": val_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "last_values": np.asarray(last_values, dtype=np.float32),
            "last_obs": self._obs.copy(),
        }

    def episode_stats(self) -> Dict[str, Any]:
        stats = [t.stats() for t in self._trackers]
        episodes = sum(s["episodes"] for s in stats)
        returns = [
            s["mean_return"] for s in stats if s["episodes"] > 0
        ]
        return {
            "episodes": episodes,
            "mean_return": float(np.mean(returns)) if returns else 0.0,
        }


@rt.remote
class ContinuousTransitionRunner:
    """Off-policy transition collector for continuous control (SAC).

    Stores NORMALIZED ([-1, 1]) actions so the learner's Q towers see the
    exact values the policy emitted; env steps receive the scaled form.
    `sample(random_actions=True)` provides the uniform warmup phase
    (reference: SAC's num_steps_sampled_before_learning_starts)."""

    def __init__(self, env_creator, module_factory, seed: int = 0,
                 rollout_length: int = 200):
        import jax

        self.env = env_creator()
        self.module = module_factory()
        self.rollout_length = rollout_length
        self.rng = jax.random.PRNGKey(seed)
        self._np_rng = np.random.default_rng(seed)
        self.params = None
        self._sample = None
        self._obs = None
        self._tracker = EpisodeTracker()

    def set_weights(self, weights):
        self.params = weights
        return True

    def sample(self, random_actions: bool = False) -> Dict[str, np.ndarray]:
        import jax

        if self._sample is None:
            self._sample = jax.jit(self.module.sample_with_logp)
        if self._obs is None:
            obs, _ = self.env.reset()
            self._obs = np.asarray(obs, dtype=np.float32)
        T = self.rollout_length
        adim = self.module.spec.action_dim
        obs_buf = np.empty((T, self._obs.shape[0]), dtype=np.float32)
        act_buf = np.empty((T, adim), dtype=np.float32)
        rew_buf = np.empty(T, dtype=np.float32)
        next_buf = np.empty_like(obs_buf)
        done_buf = np.empty(T, dtype=np.float32)
        for t in range(T):
            if random_actions or self.params is None:
                a_norm = self._np_rng.uniform(-1.0, 1.0, adim).astype(
                    np.float32
                )
            else:
                self.rng, key = jax.random.split(self.rng)
                a, _ = self._sample(self.params, self._obs[None], key)
                a_norm = np.asarray(a)[0]
            scaled = np.asarray(
                self.module.scale_action(a_norm), dtype=np.float64
            )
            nxt, reward, terminated, truncated, _ = self.env.step(scaled)
            self._tracker.add(float(reward))
            obs_buf[t] = self._obs
            act_buf[t] = a_norm
            rew_buf[t] = float(reward)
            next_buf[t] = np.asarray(nxt, dtype=np.float32)
            # Q targets bootstrap through time-limit truncations:
            # dones records TERMINATED only (same contract as
            # TransitionEnvRunner).
            done_buf[t] = float(terminated)
            if terminated or truncated:
                self._tracker.end_episode()
                obs, _ = self.env.reset()
                self._obs = np.asarray(obs, dtype=np.float32)
            else:
                self._obs = next_buf[t]
        return {
            "obs": obs_buf,
            "actions": act_buf,
            "rewards": rew_buf,
            "next_obs": next_buf,
            "dones": done_buf,
        }

    def episode_stats(self) -> Dict[str, Any]:
        return self._tracker.stats()


@rt.remote
class RecurrentEnvRunner(_EnvRunnerBase):
    """On-policy rollouts for stateful policies: the module's hidden
    state threads through steps, resets with the env, and each window
    ships the state it STARTED with (plus dones) so the learner can
    replay the exact sequence (reference analog: the stored-state
    sequence replay of recurrent nets / R2D2,
    rllib/models/torch/recurrent_net.py)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._policy_state = None

    def get_runner_state(self) -> Dict[str, Any]:
        state = super().get_runner_state()
        # The GRU state is part of "resume sampling bit-exactly": a
        # zeroed state on a mid-episode observation loses the memory.
        state["policy_state"] = (
            None if self._policy_state is None
            else np.asarray(self._policy_state)
        )
        return state

    def set_runner_state(self, state: Dict[str, Any]):
        super().set_runner_state(state)
        ps = state.get("policy_state")
        self._policy_state = None if ps is None else np.asarray(ps)
        return True

    def sample(self) -> Dict[str, np.ndarray]:
        import jax

        self._begin_rollout()
        if self._policy_state is None:
            self._policy_state = self.module.initial_state(1)
        T = self.rollout_length
        state0 = np.asarray(self._policy_state)[0]
        obs_buf, act_buf, logp_buf, val_buf = [], [], [], []
        rew_buf, done_buf = [], []
        for _ in range(T):
            self.rng, key = jax.random.split(self.rng)
            obs = self._obs_conn
            action, logp, value, self._policy_state = self._sample(
                self.params, obs[None], key, self._policy_state
            )
            action = int(np.asarray(action)[0])
            obs_buf.append(obs)
            act_buf.append(action)
            logp_buf.append(float(np.asarray(logp)[0]))
            val_buf.append(float(np.asarray(value)[0]))
            nxt, reward, terminated, truncated, _ = self.env.step(action)
            rew = self._reward(reward)
            nxt_conn = self._advance(nxt, reward, terminated, truncated)
            if truncated and not terminated:
                # Bootstrap the cut tail under the state the policy
                # WOULD have had at the final observation.
                self.rng, key = jax.random.split(self.rng)
                _, _, v_final, _ = self._sample(
                    self.params, nxt_conn[None], key, self._policy_state
                )
                rew += self.gamma * float(np.asarray(v_final)[0])
            if terminated or truncated:
                # The env reset: the policy state resets with it —
                # exactly what forward_seq's done-driven resets replay.
                self._policy_state = self.module.initial_state(1)
            rew_buf.append(rew)
            done_buf.append(bool(terminated or truncated))
        obs = self._obs_conn
        self.rng, key = jax.random.split(self.rng)
        _, _, last_value, _ = self._sample(
            self.params, obs[None], key, self._policy_state
        )
        return {
            "obs": np.stack(obs_buf),
            "actions": np.asarray(act_buf, dtype=np.int32),
            "logp": np.asarray(logp_buf, dtype=np.float32),
            "values": np.asarray(val_buf, dtype=np.float32),
            "rewards": np.asarray(rew_buf, dtype=np.float32),
            "dones": np.asarray(done_buf, dtype=np.float32),
            "last_value": float(np.asarray(last_value)[0]),
            "last_obs": obs,
            "state0": state0.astype(np.float32),
        }
