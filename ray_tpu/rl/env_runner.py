"""Env runners: collect experience with the current policy.

Analog of the reference's EnvRunner/SingleAgentEnvRunner
(rllib/env/env_runner.py, env/single_agent_env_runner.py:29): actors that
step gymnasium envs with the current weights and return sample batches
(obs/actions/logp/values/rewards/dones arranged for GAE).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

import ray_tpu as rt


class _EnvRunnerBase:
    """Shared env-runner scaffolding: env/module setup, weight sync, lazy
    jitted sampler, episode bookkeeping. Subclasses implement sample()."""

    def __init__(self, env_creator, module_factory, seed: int = 0,
                 rollout_length: int = 200):
        import jax

        self.env = env_creator()
        self.module = module_factory()
        self.rollout_length = rollout_length
        self.rng = jax.random.PRNGKey(seed)
        self.params = None
        self._obs = None
        self._episode_return = 0.0
        self._episode_returns: list = []
        self._sample = None  # jitted sampler

    def set_weights(self, weights):
        self.params = weights
        return True

    def _begin_rollout(self):
        import jax

        assert self.params is not None, "set_weights first"
        if self._sample is None:
            self._sample = jax.jit(self.module.sample_action)
        if self._obs is None:
            self._obs, _ = self.env.reset()
            self._episode_return = 0.0

    def _advance(self, nxt, reward, terminated, truncated):
        """Track episode returns; returns the next observation state."""
        self._episode_return += float(reward)
        if terminated or truncated:
            self._episode_returns.append(self._episode_return)
            self._obs, _ = self.env.reset()
            self._episode_return = 0.0
        else:
            self._obs = nxt

    def episode_stats(self) -> Dict[str, Any]:
        return {
            "episodes": len(self._episode_returns),
            "mean_return": (
                float(np.mean(self._episode_returns[-20:]))
                if self._episode_returns
                else 0.0
            ),
        }


@rt.remote
class EnvRunner(_EnvRunnerBase):
    def sample(self) -> Dict[str, np.ndarray]:
        """One rollout of fixed length (truncated episodes carry value
        bootstrap info via `last_value`)."""
        import jax

        self._begin_rollout()
        T = self.rollout_length
        obs_buf, act_buf, logp_buf, val_buf = [], [], [], []
        rew_buf, done_buf = [], []
        for _ in range(T):
            self.rng, key = jax.random.split(self.rng)
            obs = np.asarray(self._obs, dtype=np.float32)
            action, logp, value = self._sample(self.params, obs[None], key)
            action = int(np.asarray(action)[0])
            obs_buf.append(obs)
            act_buf.append(action)
            logp_buf.append(float(np.asarray(logp)[0]))
            val_buf.append(float(np.asarray(value)[0]))
            nxt, reward, terminated, truncated, _ = self.env.step(action)
            rew_buf.append(float(reward))
            done_buf.append(bool(terminated))
            self._advance(nxt, reward, terminated, truncated)
        # Bootstrap value of the final observation.
        obs = np.asarray(self._obs, dtype=np.float32)
        self.rng, key = jax.random.split(self.rng)
        _, _, last_value = self._sample(self.params, obs[None], key)
        return {
            "obs": np.stack(obs_buf),
            "actions": np.asarray(act_buf, dtype=np.int32),
            "logp": np.asarray(logp_buf, dtype=np.float32),
            "values": np.asarray(val_buf, dtype=np.float32),
            "rewards": np.asarray(rew_buf, dtype=np.float32),
            "dones": np.asarray(done_buf, dtype=np.float32),
            "last_value": float(np.asarray(last_value)[0]),
        }


def compute_gae(batch: Dict[str, np.ndarray], gamma: float = 0.99,
                lam: float = 0.95) -> Dict[str, np.ndarray]:
    """Generalized advantage estimation over one rollout."""
    rewards, values, dones = batch["rewards"], batch["values"], batch["dones"]
    T = len(rewards)
    adv = np.zeros(T, dtype=np.float32)
    last_gae = 0.0
    next_value = batch["last_value"]
    for t in reversed(range(T)):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_value = values[t]
    out = dict(batch)
    out["advantages"] = adv
    out["returns"] = adv + values
    return out


@rt.remote
class TransitionEnvRunner(_EnvRunnerBase):
    """Collects (s, a, r, s', done) transitions with epsilon-greedy
    exploration for off-policy algorithms (DQN family).

    Reference analog: SingleAgentEnvRunner in off-policy mode feeding
    replay buffers (rllib/env/single_agent_env_runner.py:29).
    """

    def sample(self, epsilon: float = 0.1) -> Dict[str, np.ndarray]:
        import jax

        self._begin_rollout()
        T = self.rollout_length
        obs_buf, act_buf, rew_buf, next_buf, done_buf = [], [], [], [], []
        for _ in range(T):
            self.rng, key = jax.random.split(self.rng)
            obs = np.asarray(self._obs, dtype=np.float32)
            action = int(np.asarray(
                self._sample(self.params, obs[None], key, epsilon)
            )[0])
            nxt, reward, terminated, truncated, _ = self.env.step(action)
            obs_buf.append(obs)
            act_buf.append(action)
            rew_buf.append(float(reward))
            next_buf.append(np.asarray(nxt, dtype=np.float32))
            done_buf.append(bool(terminated))
            self._advance(nxt, reward, terminated, truncated)
        return {
            "obs": np.stack(obs_buf),
            "actions": np.asarray(act_buf, dtype=np.int32),
            "rewards": np.asarray(rew_buf, dtype=np.float32),
            "next_obs": np.stack(next_buf),
            "dones": np.asarray(done_buf, dtype=np.float32),
        }
