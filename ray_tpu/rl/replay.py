"""Replay buffers for off-policy algorithms.

Reference analog: rllib/utils/replay_buffers/ — a uniform ring buffer
(ReplayBuffer) plus proportional prioritized sampling
(PrioritizedReplayBuffer, the reference's
prioritized_episode_buffer.py machinery collapsed to transition arrays),
and the n-step return transform the reference applies in its DQN
connectors (rllib/connectors/learner/add_next_observations_from_episodes
+ n_step handling in dqn.py).
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def n_step_transitions(batch: Dict[str, np.ndarray], ep_ends: np.ndarray,
                       n: int, gamma: float) -> Dict[str, np.ndarray]:
    """Collapse time-ordered 1-step transitions into n-step ones.

    For each start index t the window runs forward until the first
    episode end (terminated OR truncated), the rollout end, or n steps —
    whichever comes first (length m). The output transition carries the
    discounted reward sum over the window, the successor state after the
    window, dones = terminated-at-window-end, and ``discounts`` =
    gamma**m, so Q targets are  R + discount * (1 - done) * V(next_obs).
    Windows never bridge episodes (ep_ends includes truncations even
    though dones does not).
    """
    T = len(batch["obs"])
    if n <= 1:
        return {**batch, "discounts": np.full(T, gamma, dtype=np.float32)}
    rewards = np.zeros(T, dtype=np.float32)
    next_obs = np.empty_like(batch["next_obs"])
    dones = np.zeros(T, dtype=np.float32)
    discounts = np.zeros(T, dtype=np.float32)
    for t in range(T):
        acc, disc = 0.0, 1.0
        m = 0
        for k in range(n):
            j = t + k
            if j >= T:
                break
            acc += disc * float(batch["rewards"][j])
            disc *= gamma
            m = j
            if ep_ends[j]:
                break
        rewards[t] = acc
        next_obs[t] = batch["next_obs"][m]
        dones[t] = batch["dones"][m]
        discounts[t] = disc
    return {
        "obs": batch["obs"],
        "actions": batch["actions"],
        "rewards": rewards,
        "next_obs": next_obs,
        "dones": dones,
        "discounts": discounts,
    }


class ReplayBuffer:
    def __init__(self, capacity: int, obs_dim: int, seed: int = 0,
                 action_dim: int = 0, store_discounts: bool = False):
        """obs_dim: flat dim (int) or an obs SHAPE tuple (image envs).
        action_dim=0 -> discrete int actions; >0 -> float vectors.
        store_discounts: keep a per-transition bootstrap discount
        (gamma**m for m-step windows) alongside the usual fields."""
        self.capacity = capacity
        obs_shape = (obs_dim,) if isinstance(obs_dim, int) else tuple(obs_dim)
        self.obs = np.zeros((capacity, *obs_shape), dtype=np.float32)
        self.next_obs = np.zeros((capacity, *obs_shape), dtype=np.float32)
        if action_dim:
            self.actions = np.zeros((capacity, action_dim), dtype=np.float32)
        else:
            self.actions = np.zeros(capacity, dtype=np.int32)
        self.rewards = np.zeros(capacity, dtype=np.float32)
        self.dones = np.zeros(capacity, dtype=np.float32)
        self.store_discounts = store_discounts
        if store_discounts:
            self.discounts = np.zeros(capacity, dtype=np.float32)
        self._rng = np.random.default_rng(seed)
        self._next = 0
        self._size = 0

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]):
        n = len(batch["obs"])
        idx = (self._next + np.arange(n)) % self.capacity
        self.obs[idx] = batch["obs"]
        self.next_obs[idx] = batch["next_obs"]
        self.actions[idx] = batch["actions"]
        self.rewards[idx] = batch["rewards"]
        self.dones[idx] = batch["dones"]
        if self.store_discounts:
            self.discounts[idx] = batch["discounts"]
        self._next = int((self._next + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))
        return idx

    def _gather(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        out = {
            "obs": self.obs[idx],
            "next_obs": self.next_obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "dones": self.dones[idx],
        }
        if self.store_discounts:
            out["discounts"] = self.discounts[idx]
        return out

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return self._gather(idx)


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized experience replay (Schaul et al. 2016).

    Sampling probability ∝ priority**alpha; importance-sampling weights
    (N * P)**-beta normalized by their max ride along in the batch as
    ``weights`` plus the sampled ``indices`` for update_priorities.
    New transitions enter at the current max priority so every
    transition is seen at least once (reference:
    rllib/utils/replay_buffers/prioritized_episode_buffer.py).
    """

    def __init__(self, capacity: int, obs_dim: int, seed: int = 0,
                 action_dim: int = 0, store_discounts: bool = False,
                 alpha: float = 0.6, eps: float = 1e-6):
        super().__init__(capacity, obs_dim, seed=seed, action_dim=action_dim,
                         store_discounts=store_discounts)
        self.alpha = alpha
        self.eps = eps
        self.priorities = np.zeros(capacity, dtype=np.float64)

    def add_batch(self, batch: Dict[str, np.ndarray]):
        max_p = self.priorities[: self._size].max() if self._size else 1.0
        idx = super().add_batch(batch)
        self.priorities[idx] = max(max_p, self.eps)
        return idx

    def sample(self, batch_size: int, beta: float = 0.4) -> Dict[str, np.ndarray]:
        p = self.priorities[: self._size] ** self.alpha
        probs = p / p.sum()
        idx = self._rng.choice(self._size, size=batch_size, p=probs)
        out = self._gather(idx)
        w = (self._size * probs[idx]) ** (-beta)
        out["weights"] = (w / w.max()).astype(np.float32)
        out["indices"] = idx
        return out

    def update_priorities(self, indices: np.ndarray, td_abs: np.ndarray):
        self.priorities[indices] = np.abs(td_abs).astype(np.float64) + self.eps
