"""Replay buffer for off-policy algorithms.

Reference analog: rllib/utils/replay_buffers/ — a uniform ring buffer over
transition arrays (the PrioritizedEpisodeReplayBuffer family collapses to
this for the DQN core loop).
"""

from __future__ import annotations

from typing import Dict

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, obs_dim: int, seed: int = 0,
                 action_dim: int = 0):
        """action_dim=0 -> discrete int actions; >0 -> float vectors."""
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), dtype=np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), dtype=np.float32)
        if action_dim:
            self.actions = np.zeros((capacity, action_dim), dtype=np.float32)
        else:
            self.actions = np.zeros(capacity, dtype=np.int32)
        self.rewards = np.zeros(capacity, dtype=np.float32)
        self.dones = np.zeros(capacity, dtype=np.float32)
        self._rng = np.random.default_rng(seed)
        self._next = 0
        self._size = 0

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]):
        n = len(batch["obs"])
        idx = (self._next + np.arange(n)) % self.capacity
        self.obs[idx] = batch["obs"]
        self.next_obs[idx] = batch["next_obs"]
        self.actions[idx] = batch["actions"]
        self.rewards[idx] = batch["rewards"]
        self.dones[idx] = batch["dones"]
        self._next = int((self._next + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {
            "obs": self.obs[idx],
            "next_obs": self.next_obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "dones": self.dones[idx],
        }
