"""LearnerGroup: one or more learner actors updating in data parallel.

Analog of the reference's LearnerGroup (rllib/core/learner/learner_group.py:71,
which reuses Ray Train's BackendExecutor :148-170 for multi-GPU learners).
Here learner actors are placed like Train workers (TPU resources flow
through actor options); with N learners each takes 1/N of the batch and
gradients sync through the eager DCN group (CPU) — on TPU learner gangs
the update itself is pjit-sharded instead.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import ray_tpu as rt


@rt.remote
class _LearnerActor:
    def __init__(self, module_factory, loss_fn, seed, rank, world_size,
                 lr=3e-4):
        from ray_tpu.rl.core.learner import Learner

        self.learner = Learner(module_factory(), loss_fn, seed=seed, lr=lr)
        self.rank = rank
        self.world_size = world_size

    def init_collective(self, world_size, rank, backend, group_name,
                        epoch=0):
        from ray_tpu.util import collective as col

        col.init_collective_group(world_size, rank, backend, group_name,
                                  epoch=epoch)
        self._group = group_name
        return True

    def update(self, batch_shard) -> Dict:
        if self.world_size == 1:
            return self.learner.update_from_batch(batch_shard)
        import jax

        from ray_tpu.util import collective as col

        grads, metrics = self.learner.compute_gradients(batch_shard)
        leaves, treedef = jax.tree.flatten(grads)
        reduced = [
            col.allreduce(np.asarray(leaf), self._group) / self.world_size
            for leaf in leaves
        ]
        self.learner.apply_gradients(jax.tree.unflatten(treedef, reduced))
        return metrics

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights):
        self.learner.set_weights(weights)
        return True

    def get_state(self):
        return self.learner.get_state()

    def set_state(self, state):
        self.learner.set_state(state)
        return True


class LearnerGroup:
    def __init__(
        self,
        module_factory,
        loss_fn,
        num_learners: int = 1,
        resources_per_learner: Optional[Dict[str, float]] = None,
        seed: int = 0,
        lr: float = 3e-4,
    ):
        self.num_learners = max(1, num_learners)
        res = resources_per_learner or {"CPU": 1}
        self.actors = [
            _LearnerActor.options(
                num_cpus=res.get("CPU", 1),
                resources={k: v for k, v in res.items() if k != "CPU"},
            ).remote(module_factory, loss_fn, seed, i, self.num_learners, lr)
            for i in range(self.num_learners)
        ]
        if self.num_learners > 1:
            from ray_tpu.util import collective as col

            # epoch=0: learner gangs are never rebuilt in place — a
            # failed LearnerGroup is recreated wholesale (fresh actors,
            # fresh group name registrations), so no stale rank exists.
            col.create_collective_group(
                self.actors,
                self.num_learners,
                list(range(self.num_learners)),
                backend="dcn",
                group_name="learner_group",
                epoch=0,
            )

    def update_from_batch(self, batch: Dict[str, np.ndarray]) -> Dict:
        """Split the batch across learners; return averaged metrics
        (reference: learner_group.py:210 update_from_batch)."""
        if self.num_learners == 1:
            return rt.get(self.actors[0].update.remote(batch), timeout=300)
        shards = _split_batch(batch, self.num_learners)
        all_metrics = rt.get(
            [a.update.remote(s) for a, s in zip(self.actors, shards)],
            timeout=300,
        )
        out: Dict = {}
        for k in all_metrics[0]:
            out[k] = float(np.mean([m[k] for m in all_metrics]))
        return out

    def get_weights(self):
        return rt.get(self.actors[0].get_weights.remote(), timeout=300)

    def set_weights(self, weights):
        rt.get([a.set_weights.remote(weights) for a in self.actors], timeout=300)

    def get_state(self):
        """Optimizer-inclusive learner state (rank 0; replicas are
        identical under data-parallel updates)."""
        return rt.get(self.actors[0].get_state.remote(), timeout=300)

    def set_state(self, state):
        rt.get([a.set_state.remote(state) for a in self.actors], timeout=300)

    def shutdown(self):
        for a in self.actors:
            try:
                rt.kill(a)
            except Exception:
                pass


# Batch entries that are shared per-update state rather than per-sample
# rows (NoisyNet's factorized noise vectors): replicated to every
# learner shard instead of sliced. Explicit by name — a length
# heuristic would misfire when a vector width coincides with the
# batch size.
SHARED_BATCH_KEYS = frozenset({"eps_in", "eps_out"})


def _split_batch(batch: Dict[str, np.ndarray], n: int) -> List[Dict]:
    size = len(next(
        v for k, v in batch.items() if k not in SHARED_BATCH_KEYS
    ))
    per = size // n
    return [
        {
            k: (v if k in SHARED_BATCH_KEYS
                else v[i * per: (i + 1) * per])
            for k, v in batch.items()
        }
        for i in range(n)
    ]
