"""RLModule: the neural policy/value container.

Analog of the reference's RLModule (rllib/core/rl_module/rl_module.py:237)
reworked functional-JAX: a module is init/forward pure functions over a
params pytree, so the same module runs in env-runner actors (CPU
inference) and learner actors (TPU training) without framework adapters
(the reference needs torch/tf-specific subclasses).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.conv import TINY_FILTERS, cnn_torso_forward, init_cnn_torso
from ray_tpu.models.mlp import init_mlp, mlp_forward


@dataclass(frozen=True)
class RLModuleSpec:
    """Analog of RLModuleSpec: architecture + spaces."""

    obs_dim: int
    num_actions: int
    hidden: Tuple[int, ...] = (64, 64)


@dataclass(frozen=True)
class ConvModuleSpec:
    """Spec for image-observation modules (the conv_filters catalog
    path, reference rllib/models/catalog.py:105-116): obs are
    (H, W, C) float frames; conv_filters is (out_ch, kernel, stride)
    per layer."""

    obs_shape: Tuple[int, int, int]
    num_actions: int
    conv_filters: Tuple[Tuple[int, int, int], ...] = TINY_FILTERS
    feature_dim: int = 128
    hidden: Tuple[int, ...] = (64,)


class DiscretePolicyModule:
    """Separate policy and value MLP towers over a shared spec."""

    def __init__(self, spec: RLModuleSpec):
        self.spec = spec

    def init(self, rng: jax.Array) -> Dict:
        k1, k2 = jax.random.split(rng)
        sizes = [self.spec.obs_dim, *self.spec.hidden]
        return {
            "pi": init_mlp(k1, sizes + [self.spec.num_actions]),
            "vf": init_mlp(k2, sizes + [1]),
        }

    def forward(self, params: Dict, obs: jax.Array) -> Dict[str, jax.Array]:
        logits = mlp_forward(params["pi"], obs)
        value = mlp_forward(params["vf"], obs)[..., 0]
        return {"action_logits": logits, "value": value}

    def action_dist(self, logits: jax.Array):
        return jax.nn.log_softmax(logits)

    def sample_action(self, params: Dict, obs: jax.Array, rng: jax.Array):
        out = self.forward(params, obs)
        action = jax.random.categorical(rng, out["action_logits"])
        logp = jax.nn.log_softmax(out["action_logits"])
        chosen_logp = jnp.take_along_axis(logp, action[..., None], axis=-1)[..., 0]
        return action, chosen_logp, out["value"]


def filters_for(obs_shape, conv_filters=None):
    """Resolution-based default conv filters (the role of the catalog's
    _get_filter_config, reference rllib/models/catalog.py): explicit
    filters win; else Atari-scale frames (>=64px) get the 3-layer
    Nature-CNN stack, tiny test frames the 2-layer stack."""
    if conv_filters is not None:
        return tuple(conv_filters)
    from ray_tpu.models.conv import ATARI_FILTERS, TINY_FILTERS

    return (ATARI_FILTERS
            if min(obs_shape[0], obs_shape[1]) >= 64 else TINY_FILTERS)


class ConvPolicyModule(DiscretePolicyModule):
    """Conv torso + policy/value heads for image observations.

    The conv analog of DiscretePolicyModule (reference: the vision nets
    rllib's catalog builds when the obs space is image-shaped,
    rllib/models/catalog.py:105). One shared torso feeds both heads —
    the reference's ``vf_share_layers`` default for vision — so the
    expensive conv features are computed once per step. Sampling and
    the action distribution are inherited: they only consume forward().
    """

    def __init__(self, spec: ConvModuleSpec):
        self.spec = spec

    def init(self, rng: jax.Array) -> Dict:
        kt, kp, kv = jax.random.split(rng, 3)
        feat = self.spec.feature_dim
        sizes = [feat, *self.spec.hidden]
        return {
            "torso": init_cnn_torso(
                kt, self.spec.obs_shape, self.spec.conv_filters,
                out_dim=feat,
            ),
            "pi": init_mlp(kp, sizes + [self.spec.num_actions]),
            "vf": init_mlp(kv, sizes + [1]),
        }

    def forward(self, params: Dict, obs: jax.Array) -> Dict[str, jax.Array]:
        feats = cnn_torso_forward(params["torso"], obs,
                                  self.spec.conv_filters)
        return {
            "action_logits": mlp_forward(params["pi"], feats),
            "value": mlp_forward(params["vf"], feats)[..., 0],
        }


class QNetworkModule:
    """Q-network for value-based algorithms (DQN family).

    Reference analog: the DQN RLModules under rllib/algorithms/dqn/ —
    an MLP mapping observations to per-action Q values, with
    epsilon-greedy sampling for collection.
    """

    def __init__(self, spec: RLModuleSpec):
        self.spec = spec

    def init(self, rng: jax.Array) -> Dict:
        sizes = [self.spec.obs_dim, *self.spec.hidden]
        return {"q": init_mlp(rng, sizes + [self.spec.num_actions])}

    def forward(self, params: Dict, obs: jax.Array) -> Dict[str, jax.Array]:
        return {"q_values": mlp_forward(params["q"], obs)}

    def sample_action(self, params: Dict, obs: jax.Array, rng: jax.Array,
                      epsilon: float = 0.0):
        q = self.forward(params, obs)["q_values"]
        greedy = jnp.argmax(q, axis=-1)
        k1, k2 = jax.random.split(rng)
        random_a = jax.random.randint(
            k1, greedy.shape, 0, self.spec.num_actions
        )
        explore = jax.random.uniform(k2, greedy.shape) < epsilon
        return jnp.where(explore, random_a, greedy)


class ConvQNetworkModule(QNetworkModule):
    """Conv torso + Q head for image observations (pixel DQN; the
    reference's Atari configuration — DQNConfig with conv_filters).
    Epsilon-greedy sampling is inherited — it only consumes q_values."""

    def __init__(self, spec: ConvModuleSpec):
        self.spec = spec

    def init(self, rng: jax.Array) -> Dict:
        kt, kq = jax.random.split(rng)
        feat = self.spec.feature_dim
        return {
            "torso": init_cnn_torso(
                kt, self.spec.obs_shape, self.spec.conv_filters,
                out_dim=feat,
            ),
            "q": init_mlp(kq, [feat, *self.spec.hidden,
                               self.spec.num_actions]),
        }

    def forward(self, params: Dict, obs: jax.Array) -> Dict[str, jax.Array]:
        feats = cnn_torso_forward(params["torso"], obs,
                                  self.spec.conv_filters)
        return {"q_values": mlp_forward(params["q"], feats)}


class DuelingQNetworkModule(QNetworkModule):
    """Dueling Q-network (Wang et al. 2016): a shared trunk feeding
    separate value and advantage streams, combined as
    Q = V + A - mean(A) for identifiability.

    Reference analog: the dueling heads rllib's DQN builds when
    ``DQNConfig.dueling`` is set (rllib/algorithms/dqn/).
    Epsilon-greedy sampling is inherited — it only consumes q_values.
    """

    def init(self, rng: jax.Array) -> Dict:
        if not self.spec.hidden:
            raise ValueError(
                "DuelingQNetworkModule needs at least one hidden layer "
                "(the value/advantage streams branch off the trunk)"
            )
        k1, k2, k3 = jax.random.split(rng, 3)
        trunk_sizes = [self.spec.obs_dim, *self.spec.hidden]
        width = self.spec.hidden[-1]
        return {
            "trunk": init_mlp(k1, trunk_sizes),
            "v": init_mlp(k2, [width, 1]),
            "a": init_mlp(k3, [width, self.spec.num_actions]),
        }

    def forward(self, params: Dict, obs: jax.Array) -> Dict[str, jax.Array]:
        h = jax.nn.relu(mlp_forward(params["trunk"], obs))
        v = mlp_forward(params["v"], h)
        a = mlp_forward(params["a"], h)
        q = v + a - a.mean(axis=-1, keepdims=True)
        return {"q_values": q}


def factorized_noise(rng: jax.Array, n_in: int, n_out: int):
    """Factorized Gaussian noise (Fortunato et al. 2017): two vectors
    through f(x) = sign(x)*sqrt(|x|) outer-product into a weight-noise
    matrix at O(n_in + n_out) sampling cost."""
    k1, k2 = jax.random.split(rng)
    f = lambda x: jnp.sign(x) * jnp.sqrt(jnp.abs(x))  # noqa: E731
    return f(jax.random.normal(k1, (n_in,))), f(
        jax.random.normal(k2, (n_out,))
    )


def factorized_noise_np(rng, n_in: int, n_out: int):
    """Numpy twin of factorized_noise for driver-side batch assembly
    (same transform; keep the two in lockstep)."""
    import numpy as np

    f = lambda x: np.sign(x) * np.sqrt(np.abs(x))  # noqa: E731
    return (
        f(rng.standard_normal(n_in)).astype(np.float32),
        f(rng.standard_normal(n_out)).astype(np.float32),
    )


class NoisyQNetworkModule(QNetworkModule):
    """Q-network with a NoisyNet output layer (Fortunato et al. 2017;
    reference: DQNConfig.noisy). Exploration comes from learned
    parametric noise on the head weights instead of epsilon-greedy:
    w = mu + sigma * (eps_out ⊗ eps_in). The noise vectors are inputs
    (sampled by the caller), so the module stays a pure function and the
    learner's loss trains sigma through the same batch dict plumbing.
    """

    SIGMA0 = 0.5

    def init(self, rng: jax.Array) -> Dict:
        if not self.spec.hidden:
            raise ValueError(
                "NoisyQNetworkModule needs at least one hidden layer "
                "(the noisy head sits atop the trunk)"
            )
        k1, k2, k3 = jax.random.split(rng, 3)
        sizes = [self.spec.obs_dim, *self.spec.hidden]
        width = sizes[-1]
        A = self.spec.num_actions
        bound = width ** -0.5
        return {
            "trunk": init_mlp(k1, sizes),
            "mu_w": jax.random.uniform(
                k2, (width, A), minval=-bound, maxval=bound
            ),
            "mu_b": jax.random.uniform(
                k3, (A,), minval=-bound, maxval=bound
            ),
            "sigma_w": jnp.full((width, A), self.SIGMA0 * bound),
            "sigma_b": jnp.full((A,), self.SIGMA0 * bound),
        }

    def forward(self, params: Dict, obs: jax.Array,
                noise=None) -> Dict[str, jax.Array]:
        """noise = (eps_in (width,), eps_out (A,)) or None for the
        deterministic mu-only head (target computation, evaluation)."""
        h = jax.nn.relu(mlp_forward(params["trunk"], obs))
        w, b = params["mu_w"], params["mu_b"]
        if noise is not None:
            eps_in, eps_out = noise
            w = w + params["sigma_w"] * (eps_in[:, None] * eps_out[None, :])
            b = b + params["sigma_b"] * eps_out
        return {"q_values": h @ w + b}

    def sample_action(self, params: Dict, obs: jax.Array, rng: jax.Array,
                      epsilon: float = 0.0):
        """Noise-driven exploration: one fresh factorized draw per call;
        epsilon is ignored (the reference also zeroes epsilon when noisy
        is on)."""
        width = params["mu_w"].shape[0]
        noise = factorized_noise(rng, width, self.spec.num_actions)
        q = self.forward(params, obs, noise=noise)["q_values"]
        return jnp.argmax(q, axis=-1)


class C51QNetworkModule(QNetworkModule):
    """Categorical distributional Q-network (Bellemare et al. 2017).

    Reference analog: the distributional heads rllib's DQN builds with
    ``DQNConfig.num_atoms > 1``. The net emits logits over num_atoms
    fixed support atoms per action; q_values (driving the inherited
    epsilon-greedy sampling) are the expected values under the softmax
    distribution.
    """

    def __init__(self, spec: RLModuleSpec, num_atoms: int = 51,
                 v_min: float = -10.0, v_max: float = 10.0):
        super().__init__(spec)
        self.num_atoms = num_atoms
        self.support = jnp.linspace(v_min, v_max, num_atoms)

    def init(self, rng: jax.Array) -> Dict:
        sizes = [self.spec.obs_dim, *self.spec.hidden]
        return {
            "q": init_mlp(rng, sizes + [self.spec.num_actions * self.num_atoms])
        }

    def forward(self, params: Dict, obs: jax.Array) -> Dict[str, jax.Array]:
        flat = mlp_forward(params["q"], obs)
        logits = flat.reshape(
            *flat.shape[:-1], self.spec.num_actions, self.num_atoms
        )
        probs = jax.nn.softmax(logits, axis=-1)
        return {
            "q_logits": logits,
            "q_probs": probs,
            "q_values": (probs * self.support).sum(-1),
        }


@dataclass(frozen=True)
class ContinuousModuleSpec:
    """Spec for continuous-control modules (SAC family)."""

    obs_dim: int
    action_dim: int
    action_low: float = -1.0
    action_high: float = 1.0
    hidden: Tuple[int, ...] = (64, 64)


class ContinuousPolicyModule:
    """Tanh-squashed Gaussian policy + twin Q towers (SAC's module;
    reference analog: the SAC RLModules under rllib/algorithms/sac/).

    Internally actions live in [-1, 1] (the tanh image); `scale_action`
    maps to the env's [low, high]. Q towers consume (obs, normalized
    action) concatenations.
    """

    LOG_STD_MIN = -5.0
    LOG_STD_MAX = 2.0

    def __init__(self, spec: ContinuousModuleSpec):
        self.spec = spec

    def init(self, rng: jax.Array) -> Dict:
        kp, k1, k2 = jax.random.split(rng, 3)
        sizes = [self.spec.obs_dim, *self.spec.hidden]
        qin = self.spec.obs_dim + self.spec.action_dim
        qsizes = [qin, *self.spec.hidden, 1]
        return {
            "pi": init_mlp(kp, sizes + [2 * self.spec.action_dim]),
            "q1": init_mlp(k1, qsizes),
            "q2": init_mlp(k2, qsizes),
        }

    def scale_action(self, a_norm: jax.Array) -> jax.Array:
        lo, hi = self.spec.action_low, self.spec.action_high
        return a_norm * (hi - lo) / 2.0 + (hi + lo) / 2.0

    def _dist(self, params: Dict, obs: jax.Array):
        out = mlp_forward(params["pi"], obs)
        mu, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, self.LOG_STD_MIN, self.LOG_STD_MAX)
        return mu, log_std

    def sample_with_logp(self, params: Dict, obs: jax.Array,
                         rng: jax.Array):
        """Reparameterized tanh-Gaussian sample + its log-prob."""
        mu, log_std = self._dist(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(rng, mu.shape)
        pre = mu + std * eps
        a = jnp.tanh(pre)
        # N(pre; mu, std) log-density with the tanh change of variables.
        gauss_logp = (
            -0.5 * (eps ** 2) - log_std - 0.5 * jnp.log(2.0 * jnp.pi)
        ).sum(-1)
        logp = gauss_logp - jnp.log(1.0 - a ** 2 + 1e-6).sum(-1)
        return a, logp

    def deterministic_action(self, params: Dict, obs: jax.Array):
        mu, _ = self._dist(params, obs)
        return jnp.tanh(mu)

    def q_values(self, params: Dict, obs: jax.Array, a_norm: jax.Array):
        x = jnp.concatenate([obs, a_norm], axis=-1)
        q1 = mlp_forward(params["q1"], x)[..., 0]
        q2 = mlp_forward(params["q2"], x)[..., 0]
        return q1, q2

    def sample_action(self, params: Dict, obs: jax.Array, rng: jax.Array):
        """EnvRunner-facing: scaled action, logp, dummy value."""
        a, logp = self.sample_with_logp(params, obs, rng)
        return self.scale_action(a), logp, jnp.zeros(obs.shape[0])


@dataclass(frozen=True)
class RecurrentModuleSpec:
    """Spec for stateful (recurrent) policies. The structural gap the
    reference fills with recurrent nets + state plumbing
    (rllib/models/torch/recurrent_net.py; R2D2's stored-state replay):
    the policy carries a hidden state across steps, reset at episode
    boundaries, and the learner replays sequences from the state each
    rollout window started with."""

    obs_dim: int
    num_actions: int
    state_dim: int = 32
    hidden: Tuple[int, ...] = (32,)


class RecurrentPolicyModule:
    """GRU torso + policy/value heads (functional JAX).

    Three entry points: forward_step (one step, rollout time),
    forward_seq (whole [B, T] window via lax.scan with done-resets,
    learner time), and sample_action (rollout sampling; returns the new
    state so the runner can thread it)."""

    def __init__(self, spec: RecurrentModuleSpec):
        self.spec = spec

    def init(self, rng: jax.Array) -> Dict:
        kw, ku, kp, kv = jax.random.split(rng, 4)
        d, h = self.spec.obs_dim, self.spec.state_dim
        sizes = [h, *self.spec.hidden]
        scale_w = (1.0 / d) ** 0.5
        scale_u = (1.0 / h) ** 0.5
        return {
            # Fused GRU weights: [z | r | candidate].
            "gru_w": jax.random.normal(kw, (d, 3 * h)) * scale_w,
            "gru_u": jax.random.normal(ku, (h, 3 * h)) * scale_u,
            "gru_b": jnp.zeros((3 * h,)),
            "pi": init_mlp(kp, sizes + [self.spec.num_actions]),
            "vf": init_mlp(kv, sizes + [1]),
        }

    def initial_state(self, batch: int = 1) -> jax.Array:
        return jnp.zeros((batch, self.spec.state_dim))

    def _cell(self, params: Dict, x: jax.Array, h: jax.Array) -> jax.Array:
        """One GRU step: x [B, D], h [B, H] -> h' [B, H]."""
        H = self.spec.state_dim
        gx = x @ params["gru_w"] + params["gru_b"]
        gh = h @ params["gru_u"]
        z = jax.nn.sigmoid(gx[:, :H] + gh[:, :H])
        r = jax.nn.sigmoid(gx[:, H:2 * H] + gh[:, H:2 * H])
        cand = jnp.tanh(gx[:, 2 * H:] + r * gh[:, 2 * H:])
        return (1.0 - z) * h + z * cand

    def _heads(self, params: Dict, h: jax.Array) -> Dict[str, jax.Array]:
        return {
            "action_logits": mlp_forward(params["pi"], h),
            "value": mlp_forward(params["vf"], h)[..., 0],
        }

    def forward_step(self, params: Dict, obs: jax.Array, state: jax.Array):
        h = self._cell(params, obs, state)
        return self._heads(params, h), h

    def forward_seq(self, params: Dict, obs: jax.Array, state0: jax.Array,
                    dones: jax.Array) -> Dict[str, jax.Array]:
        """Replay a [B, T] window exactly as it was collected: the state
        enters as state0 (the window's first step) and resets to zero
        AFTER any step whose done flag is set — matching the runner,
        which zeroes its state when the env resets."""

        def scan_fn(h, inp):
            x_t, reset_t = inp
            h = h * (1.0 - reset_t)[:, None]
            h = self._cell(params, x_t, h)
            return h, h

        T = obs.shape[1]
        # resets[t] = dones[t-1]: state carried INTO step t.
        resets = jnp.concatenate(
            [jnp.zeros_like(dones[:, :1]), dones[:, :-1]], axis=1
        )
        _, hs = jax.lax.scan(
            scan_fn, state0,
            (jnp.swapaxes(obs, 0, 1), jnp.swapaxes(resets, 0, 1)),
        )
        hs = jnp.swapaxes(hs, 0, 1)  # [B, T, H]
        return self._heads(params, hs)

    def sample_action(self, params: Dict, obs: jax.Array, rng: jax.Array,
                      state: jax.Array):
        out, h = self.forward_step(params, obs, state)
        action = jax.random.categorical(rng, out["action_logits"])
        logp = jax.nn.log_softmax(out["action_logits"])
        chosen = jnp.take_along_axis(logp, action[..., None], axis=-1)[..., 0]
        return action, chosen, out["value"], h


class RecurrentQNetworkModule(RecurrentPolicyModule):
    """GRU torso + Q head: the value-based stateful module (R2D2's
    network shape, reference rllib/algorithms/r2d2/). Shares the GRU
    cell and state plumbing with the policy variant; only the heads
    differ (Q values instead of policy/value towers)."""

    def init(self, rng: jax.Array) -> Dict:
        kw, ku, kq = jax.random.split(rng, 3)
        d, h = self.spec.obs_dim, self.spec.state_dim
        return {
            "gru_w": jax.random.normal(kw, (d, 3 * h)) * (1.0 / d) ** 0.5,
            "gru_u": jax.random.normal(ku, (h, 3 * h)) * (1.0 / h) ** 0.5,
            "gru_b": jnp.zeros((3 * h,)),
            "q": init_mlp(kq, [h, *self.spec.hidden,
                               self.spec.num_actions]),
        }

    def _heads(self, params: Dict, h: jax.Array) -> Dict[str, jax.Array]:
        return {"q_values": mlp_forward(params["q"], h)}

    def sample_action(self, params: Dict, obs: jax.Array, rng: jax.Array,
                      state: jax.Array, epsilon: float = 0.0):
        out, h = self.forward_step(params, obs, state)
        q = out["q_values"]
        greedy = jnp.argmax(q, axis=-1)
        k1, k2 = jax.random.split(rng)
        random_a = jax.random.randint(
            k1, greedy.shape, 0, self.spec.num_actions
        )
        explore = jax.random.uniform(k2, greedy.shape) < epsilon
        return jnp.where(explore, random_a, greedy), h
