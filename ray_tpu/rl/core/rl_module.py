"""RLModule: the neural policy/value container.

Analog of the reference's RLModule (rllib/core/rl_module/rl_module.py:237)
reworked functional-JAX: a module is init/forward pure functions over a
params pytree, so the same module runs in env-runner actors (CPU
inference) and learner actors (TPU training) without framework adapters
(the reference needs torch/tf-specific subclasses).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.mlp import init_mlp, mlp_forward


@dataclass(frozen=True)
class RLModuleSpec:
    """Analog of RLModuleSpec: architecture + spaces."""

    obs_dim: int
    num_actions: int
    hidden: Tuple[int, ...] = (64, 64)


class DiscretePolicyModule:
    """Separate policy and value MLP towers over a shared spec."""

    def __init__(self, spec: RLModuleSpec):
        self.spec = spec

    def init(self, rng: jax.Array) -> Dict:
        k1, k2 = jax.random.split(rng)
        sizes = [self.spec.obs_dim, *self.spec.hidden]
        return {
            "pi": init_mlp(k1, sizes + [self.spec.num_actions]),
            "vf": init_mlp(k2, sizes + [1]),
        }

    def forward(self, params: Dict, obs: jax.Array) -> Dict[str, jax.Array]:
        logits = mlp_forward(params["pi"], obs)
        value = mlp_forward(params["vf"], obs)[..., 0]
        return {"action_logits": logits, "value": value}

    def action_dist(self, logits: jax.Array):
        return jax.nn.log_softmax(logits)

    def sample_action(self, params: Dict, obs: jax.Array, rng: jax.Array):
        out = self.forward(params, obs)
        action = jax.random.categorical(rng, out["action_logits"])
        logp = jax.nn.log_softmax(out["action_logits"])
        chosen_logp = jnp.take_along_axis(logp, action[..., None], axis=-1)[..., 0]
        return action, chosen_logp, out["value"]


class QNetworkModule:
    """Q-network for value-based algorithms (DQN family).

    Reference analog: the DQN RLModules under rllib/algorithms/dqn/ —
    an MLP mapping observations to per-action Q values, with
    epsilon-greedy sampling for collection.
    """

    def __init__(self, spec: RLModuleSpec):
        self.spec = spec

    def init(self, rng: jax.Array) -> Dict:
        sizes = [self.spec.obs_dim, *self.spec.hidden]
        return {"q": init_mlp(rng, sizes + [self.spec.num_actions])}

    def forward(self, params: Dict, obs: jax.Array) -> Dict[str, jax.Array]:
        return {"q_values": mlp_forward(params["q"], obs)}

    def sample_action(self, params: Dict, obs: jax.Array, rng: jax.Array,
                      epsilon: float = 0.0):
        q = self.forward(params, obs)["q_values"]
        greedy = jnp.argmax(q, axis=-1)
        k1, k2 = jax.random.split(rng)
        random_a = jax.random.randint(
            k1, greedy.shape, 0, self.spec.num_actions
        )
        explore = jax.random.uniform(k2, greedy.shape) < epsilon
        return jnp.where(explore, random_a, greedy)
