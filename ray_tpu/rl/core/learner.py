"""Learner: gradient-based policy improvement.

Analog of the reference's Learner (rllib/core/learner/learner.py:106;
compute_gradients :455, apply_gradients :585, update_from_batch :1128) and
TorchLearner (torch_learner.py:52, DDP wrap :369). The TPU-native version
jit-compiles the whole update; multi-learner data parallelism is sharding,
not DDP.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax


class Learner:
    def __init__(
        self,
        module,
        loss_fn: Callable,
        optimizer: Optional[optax.GradientTransformation] = None,
        seed: int = 0,
        grad_clip: Optional[float] = 0.5,
        lr: float = 3e-4,
    ):
        self.module = module
        self.loss_fn = loss_fn
        tx = optimizer or optax.adam(lr)
        if grad_clip:
            tx = optax.chain(optax.clip_by_global_norm(grad_clip), tx)
        self.optimizer = tx
        self.params = module.init(jax.random.PRNGKey(seed))
        self.opt_state = self.optimizer.init(self.params)
        self._update = jax.jit(self._update_impl)
        # Optional flight recorder (train.StepProfiler): when set,
        # update_from_batch records each update as one profiled step
        # (the float() readback already fences, so compute attribution
        # is exact without extra syncs).
        self.profiler = None

    def _update_impl(self, params, opt_state, batch):
        (loss, metrics), grads = jax.value_and_grad(
            self.loss_fn, has_aux=True
        )(params, self.module, batch)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        metrics = dict(metrics)
        metrics["grad_norm"] = gnorm
        return params, opt_state, metrics

    # -- reference API shape ---------------------------------------------
    def update_from_batch(self, batch: Dict[str, jnp.ndarray]) -> Dict:
        prof = self.profiler
        if prof is None:
            self.params, self.opt_state, metrics = self._update(
                self.params, self.opt_state, batch
            )
            return {k: float(v) for k, v in metrics.items()}
        n = len(next(iter(batch.values()))) if batch else None
        with prof.step(samples=n):
            with prof.phase("compute"):
                self.params, self.opt_state, metrics = self._update(
                    self.params, self.opt_state, batch
                )
                return {k: float(v) for k, v in metrics.items()}

    def compute_gradients(self, batch) -> Tuple[Any, Dict]:
        (loss, metrics), grads = jax.value_and_grad(
            self.loss_fn, has_aux=True
        )(self.params, self.module, batch)
        return grads, {k: float(v) for k, v in metrics.items()}

    def apply_gradients(self, grads):
        updates, self.opt_state = self.optimizer.update(
            grads, self.opt_state, self.params
        )
        self.params = optax.apply_updates(self.params, updates)

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights):
        self.params = weights

    def get_state(self) -> Dict:
        """Full optimizer-inclusive state for Algorithm.save (reference:
        learner.py get_state: module weights + optimizer state)."""
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
        }

    def set_state(self, state: Dict):
        self.params = state["params"]
        self.opt_state = jax.tree.map(jnp.asarray, state["opt_state"])


def minibatch_epochs(update_fn, batch, num_epochs: int, minibatch_size: int,
                     rng) -> Dict:
    """Shuffled minibatch-SGD epochs over a flat batch dict; returns the
    last update's metrics. The shared epoch loop for PPO, multi-agent PPO,
    and BC (reference: the minibatch cycling in Learner.update_from_batch,
    learner.py:1128)."""
    n = len(next(iter(batch.values())))
    mb = min(minibatch_size, n)
    metrics: Dict = {}
    for _ in range(num_epochs):
        perm = rng.permutation(n)
        for start in range(0, n - mb + 1, mb):
            idx = perm[start : start + mb]
            metrics = update_fn({k: v[idx] for k, v in batch.items()})
    return metrics
