"""Multi-agent RL: envs, module dicts, runners, and multi-agent PPO.

Analog of the reference's multi-agent stack: MultiAgentEnv
(rllib/env/multi_agent_env.py), MultiRLModule (the per-policy module dict,
rllib/core/rl_module/multi_rl_module.py), the agent->policy mapping fn
(AlgorithmConfig.multi_agent(policy_mapping_fn=...)), and multi-agent
episode collection. JAX-first: each policy is a pure init/forward module,
so per-policy inference inside the runner is a jitted call per policy, and
per-policy learners update independently (shared or separate policies both
fall out of the mapping fn).

Synchronous stepping: every agent acts at every env step until the episode
ends for all (the "__all__" key, as the reference's terminateds dict).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu as rt
from ray_tpu.rl.core.learner import Learner
from ray_tpu.rl.core.rl_module import DiscretePolicyModule, RLModuleSpec
from ray_tpu.rl.env_runner import compute_gae
from ray_tpu.rl.algorithms.ppo import ppo_loss


class MultiAgentEnv:
    """Base class for synchronous multi-agent envs.

    reset() -> (obs_dict, info); step(action_dict) ->
    (obs_dict, reward_dict, terminated_dict, truncated_dict, info) where
    terminated_dict carries the "__all__" episode-end key (reference
    convention: rllib/env/multi_agent_env.py).
    """

    agent_ids: Tuple[str, ...] = ()

    def reset(self):  # pragma: no cover - interface
        raise NotImplementedError

    def step(self, action_dict: Dict[str, int]):  # pragma: no cover
        raise NotImplementedError


class MultiRLModule:
    """A dict of policy modules keyed by policy id (reference:
    MultiRLModule / MultiAgentRLModuleSpec)."""

    def __init__(self, specs: Dict[str, RLModuleSpec]):
        self.specs = specs
        self.modules = {
            pid: DiscretePolicyModule(spec) for pid, spec in specs.items()
        }

    def init(self, rng) -> Dict[str, Dict]:
        import jax

        keys = jax.random.split(rng, len(self.modules))
        return {
            pid: m.init(k)
            for (pid, m), k in zip(sorted(self.modules.items()), keys)
        }

    def __getitem__(self, policy_id: str) -> DiscretePolicyModule:
        return self.modules[policy_id]


@rt.remote
class MultiAgentEnvRunner:
    """Collects per-policy, per-agent trajectories from a synchronous
    multi-agent env.

    Each env step samples one action per agent from that agent's mapped
    policy. Experience is buffered PER AGENT (agents sharing a policy must
    not interleave into one sequence — GAE assumes temporal adjacency);
    completed trajectories are grouped under their policy id, the
    reference's shared-policy semantics.

    Bootstraps mirror the single-agent runner: termination ends the value
    chain; truncation ("__all__" truncs without terms) folds
    gamma * V(s_final) into the final reward; a rollout cut mid-episode
    bootstraps via the trajectory's `last_value` = V(current obs).
    """

    def __init__(self, env_creator, specs: Dict[str, RLModuleSpec],
                 policy_mapping_fn: Callable[[str], str], seed: int = 0,
                 rollout_length: int = 200, gamma: float = 0.99):
        import jax

        self.env = env_creator()
        self.marl = MultiRLModule(specs)
        self.mapping = policy_mapping_fn
        self.rollout_length = rollout_length
        self.gamma = gamma
        self.rng = jax.random.PRNGKey(seed)
        self.params: Optional[Dict[str, Dict]] = None
        self._samplers = {
            pid: jax.jit(m.sample_action) for pid, m in self.marl.modules.items()
        }
        self._values = {
            pid: jax.jit(lambda p, o, m=m: m.forward(p, o)["value"])
            for pid, m in self.marl.modules.items()
        }
        self._obs = None
        from ray_tpu.rl.env_runner import EpisodeTracker

        self._tracker = EpisodeTracker()

    def set_weights(self, weights: Dict[str, Dict]):
        self.params = weights
        return True

    def _value_of(self, pid: str, obs: np.ndarray) -> float:
        return float(np.asarray(
            self._values[pid](self.params[pid], obs[None])
        )[0])

    def sample(self) -> Dict[str, List[Dict[str, np.ndarray]]]:
        """Returns {policy_id: [trajectory, ...]}, each trajectory a
        GAE-ready batch for one agent's episode segment."""
        import jax

        assert self.params is not None, "set_weights first"
        if self._obs is None:
            self._obs, _ = self.env.reset()
        agent_bufs: Dict[str, Dict[str, list]] = {}
        out: Dict[str, List[Dict[str, np.ndarray]]] = {
            pid: [] for pid in self.marl.modules
        }

        def finalize(aid: str, last_value: float):
            b = agent_bufs.pop(aid, None)
            if not b or not b["obs"]:
                return
            out[self.mapping(aid)].append({
                "obs": np.stack(b["obs"]),
                "actions": np.asarray(b["actions"], dtype=np.int32),
                "logp": np.asarray(b["logp"], dtype=np.float32),
                "values": np.asarray(b["values"], dtype=np.float32),
                "rewards": np.asarray(b["rewards"], dtype=np.float32),
                "dones": np.asarray(b["dones"], dtype=np.float32),
                "last_value": float(last_value),
            })

        for _ in range(self.rollout_length):
            actions: Dict[str, int] = {}
            step_meta: Dict[str, Tuple[str, np.ndarray, float, float]] = {}
            for aid, obs in self._obs.items():
                pid = self.mapping(aid)
                self.rng, key = jax.random.split(self.rng)
                obs = np.asarray(obs, dtype=np.float32)
                a, logp, value = self._samplers[pid](
                    self.params[pid], obs[None], key
                )
                actions[aid] = int(np.asarray(a)[0])
                step_meta[aid] = (
                    pid, obs, float(np.asarray(logp)[0]),
                    float(np.asarray(value)[0]),
                )
            nxt, rewards, terms, truncs, _ = self.env.step(actions)
            terminated = bool(terms.get("__all__", False))
            truncated = bool(truncs.get("__all__", False))
            done = terminated or truncated
            for aid, (pid, obs, logp, value) in step_meta.items():
                b = agent_bufs.setdefault(
                    aid, {k: [] for k in ("obs", "actions", "logp", "values",
                                          "rewards", "dones")}
                )
                rew = float(rewards.get(aid, 0.0))
                if truncated and not terminated:
                    # Time-limit cut: the final state still has value.
                    final_obs = np.asarray(nxt[aid], dtype=np.float32)
                    rew += self.gamma * self._value_of(pid, final_obs)
                b["obs"].append(obs)
                b["actions"].append(actions[aid])
                b["logp"].append(logp)
                b["values"].append(value)
                b["rewards"].append(rew)
                b["dones"].append(float(done))
            self._tracker.add(float(sum(rewards.values())))
            if done:
                for aid in list(agent_bufs):
                    finalize(aid, 0.0)  # terminal (or folded) — no bootstrap
                self._tracker.end_episode()
                self._obs, _ = self.env.reset()
            else:
                self._obs = nxt
        # Rollout ended mid-episode: bootstrap each agent with the value of
        # its current observation under its own policy.
        for aid in list(agent_bufs):
            pid = self.mapping(aid)
            obs = np.asarray(self._obs[aid], dtype=np.float32)
            finalize(aid, self._value_of(pid, obs))
        return out

    def episode_stats(self) -> Dict[str, Any]:
        return self._tracker.stats()


@dataclass
class MultiAgentPPOConfig:
    """Multi-agent PPO config (reference: PPOConfig().multi_agent(...))."""

    env_creator: Optional[Callable] = None
    policies: Dict[str, RLModuleSpec] = field(default_factory=dict)
    policy_mapping_fn: Callable[[str], str] = lambda aid: "default"
    num_env_runners: int = 2
    rollout_length: int = 128
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    num_epochs: int = 4
    minibatch_size: int = 64
    seed: int = 0

    def environment(self, env_creator):
        self.env_creator = env_creator
        return self

    def multi_agent(self, policies: Dict[str, RLModuleSpec],
                    policy_mapping_fn: Callable[[str], str]):
        self.policies = policies
        self.policy_mapping_fn = policy_mapping_fn
        return self

    def env_runners(self, num_env_runners=None, rollout_length=None):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if rollout_length is not None:
            self.rollout_length = rollout_length
        return self

    def training(self, lr=None, num_epochs=None, minibatch_size=None,
                 gamma=None, lambda_=None):
        for name, val in (
            ("lr", lr), ("num_epochs", num_epochs),
            ("minibatch_size", minibatch_size), ("gamma", gamma),
            ("lambda_", lambda_),
        ):
            if val is not None:
                setattr(self, name, val)
        return self

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO:
    """Per-policy PPO learners over shared multi-agent rollouts."""

    def __init__(self, config: MultiAgentPPOConfig):
        assert config.env_creator is not None, "config.environment(...) first"
        assert config.policies, "config.multi_agent(policies=...) first"
        self.config = config
        self.marl = MultiRLModule(config.policies)
        self.learners = {
            pid: Learner(self.marl[pid], ppo_loss, seed=config.seed + j,
                         lr=config.lr)
            for j, pid in enumerate(sorted(config.policies))
        }
        self.env_runners = [
            MultiAgentEnvRunner.options(num_cpus=0.5).remote(
                config.env_creator,
                config.policies,
                config.policy_mapping_fn,
                seed=config.seed + 1 + i,
                rollout_length=config.rollout_length,
                gamma=config.gamma,
            )
            for i in range(config.num_env_runners)
        ]
        self._iteration = 0
        self._broadcast_weights()

    def _broadcast_weights(self):
        weights = {pid: l.get_weights() for pid, l in self.learners.items()}
        rt.get([r.set_weights.remote(weights) for r in self.env_runners],
               timeout=300)

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        rollouts = rt.get(
            [r.sample.remote() for r in self.env_runners], timeout=600
        )
        from ray_tpu.rl.core.learner import minibatch_epochs

        metrics: Dict[str, float] = {}
        rng = np.random.default_rng(cfg.seed + self._iteration)
        for pid, learner in self.learners.items():
            # GAE runs per agent-trajectory (temporal adjacency), then the
            # policy's trajectories concatenate into one SGD batch.
            parts = [
                compute_gae(traj, cfg.gamma, cfg.lambda_)
                for r in rollouts for traj in r.get(pid, [])
            ]
            if not parts:
                continue
            batch = {
                k: np.concatenate([p[k] for p in parts])
                for k in ("obs", "actions", "logp", "values", "advantages",
                          "returns")
            }
            m = minibatch_epochs(
                learner.update_from_batch, batch, cfg.num_epochs,
                cfg.minibatch_size, rng,
            )
            metrics.update({f"{pid}/{k}": v for k, v in m.items()})
        self._broadcast_weights()
        self._iteration += 1
        stats = rt.get(
            [r.episode_stats.remote() for r in self.env_runners], timeout=300
        )
        returns = [s["mean_return"] for s in stats if s["episodes"] > 0]
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": float(np.mean(returns)) if returns else 0.0,
            "episodes_total": sum(s["episodes"] for s in stats),
            **{f"learner/{k}": v for k, v in metrics.items()},
        }

    def stop(self):
        for r in self.env_runners:
            try:
                rt.kill(r)
            except Exception:
                pass
