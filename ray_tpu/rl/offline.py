"""Offline RL: experience IO through ray_tpu.data + behavior cloning.

Analog of the reference's offline RL stack (rllib/offline/: JsonWriter /
JsonReader / the offline data input pipeline, and the BC/MARWIL algorithm
family under rllib/algorithms/bc/): collected episodes persist as a
distributed dataset, and offline algorithms train policies straight from
that dataset without touching an environment.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu import data as rt_data
from ray_tpu.rl.core.learner import Learner
from ray_tpu.rl.core.rl_module import DiscretePolicyModule, RLModuleSpec


def episodes_to_dataset(rollouts: List[Dict[str, np.ndarray]],
                        gamma: Optional[float] = None):
    """Flatten sampled rollout batches into a row-per-transition Dataset
    (reference: JsonWriter writing SampleBatches, rllib/offline/json_writer.py).

    Each row carries obs/action plus whatever per-step fields the rollout
    had (logp, rewards, dones, ...) so downstream offline algorithms can
    pick what they need. With `gamma`, each row additionally gets
    "returns" — the discounted return-to-go within its episode — which
    return-conditioned offline algorithms (MARWIL) train against.
    """
    rows = []
    for b in rollouts:
        T = len(b["actions"])
        step_keys = [
            k for k, v in b.items()
            if isinstance(v, np.ndarray) and v.shape[:1] == (T,)
        ]
        returns = None
        if gamma is not None and "rewards" in b:
            returns = np.zeros(T, dtype=np.float32)
            acc = float(b.get("last_value", 0.0))
            dones = b.get("dones", np.zeros(T))
            for t in range(T - 1, -1, -1):
                if dones[t]:
                    acc = 0.0
                acc = float(b["rewards"][t]) + gamma * acc
                returns[t] = acc
        for t in range(T):
            row = {k: b[k][t] for k in step_keys}
            if returns is not None:
                row["returns"] = returns[t]
            rows.append(row)
    return rt_data.from_items(rows)


def dataset_to_batch(ds, keys=("obs", "actions")) -> Dict[str, np.ndarray]:
    """Materialize a transition Dataset into stacked numpy arrays
    (reference: JsonReader producing SampleBatches)."""
    rows = ds.take_all() if hasattr(ds, "take_all") else ds.take(ds.count())
    return {k: np.stack([np.asarray(r[k]) for r in rows]) for k in keys}


def bc_loss(params, module, batch):
    """Negative log-likelihood of the dataset actions (behavior cloning;
    reference: rllib/algorithms/bc/)."""
    out = module.forward(params, batch["obs"])
    logp_all = jax.nn.log_softmax(out["action_logits"])
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][:, None].astype(jnp.int32), axis=-1
    )[:, 0]
    loss = -logp.mean()
    accuracy = (
        jnp.argmax(out["action_logits"], axis=-1) == batch["actions"]
    ).mean()
    return loss, {"total_loss": loss, "accuracy": accuracy}


@dataclass
class BCConfig:
    """Builder-style config for behavior cloning from a Dataset."""

    obs_dim: int = 4
    num_actions: int = 2
    hidden: tuple = (64, 64)
    lr: float = 1e-3
    minibatch_size: int = 128
    seed: int = 0

    def module(self, obs_dim=None, num_actions=None, hidden=None):
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        if hidden is not None:
            self.hidden = hidden
        return self

    def training(self, lr=None, minibatch_size=None):
        if lr is not None:
            self.lr = lr
        if minibatch_size is not None:
            self.minibatch_size = minibatch_size
        return self

    def build(self) -> "BC":
        return BC(self)


class BC:
    """Behavior cloning over an offline transition dataset."""

    def __init__(self, config: BCConfig):
        self.config = config
        spec = RLModuleSpec(config.obs_dim, config.num_actions, config.hidden)
        self.module = DiscretePolicyModule(spec)
        self.learner = Learner(
            self.module, bc_loss, seed=config.seed, lr=config.lr
        )
        self._rng = np.random.default_rng(config.seed)

    def train_on_dataset(self, ds, num_epochs: int = 1) -> Dict[str, float]:
        """Minibatch SGD epochs over the full dataset; returns the last
        update's metrics."""
        batch = dataset_to_batch(ds)
        return self.train_on_batch(batch, num_epochs)

    def train_on_batch(self, batch: Dict[str, np.ndarray],
                       num_epochs: int = 1) -> Dict[str, float]:
        from ray_tpu.rl.core.learner import minibatch_epochs

        return minibatch_epochs(
            self.learner.update_from_batch,
            {k: v for k, v in batch.items() if k in ("obs", "actions")},
            num_epochs, self.config.minibatch_size, self._rng,
        )

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        out = self.module.forward(self.learner.params, obs)
        return np.asarray(jnp.argmax(out["action_logits"], axis=-1))


def marwil_loss(beta: float):
    """Monotonic advantage re-weighted imitation learning (reference:
    rllib/algorithms/marwil/ — Wang et al. 2018): BC where each action's
    log-likelihood is weighted by exp(beta * advantage), advantage
    measured against a jointly-learned value baseline. beta=0 reduces to
    plain BC."""

    def loss(params, module, batch):
        out = module.forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(out["action_logits"])
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None].astype(jnp.int32), axis=-1
        )[:, 0]
        adv = batch["returns"] - out["value"]
        vf_loss = (adv ** 2).mean()
        # Normalized, gradient-stopped exponential weights (the c term of
        # the paper approximated by the batch advantage scale), clipped
        # for stability.
        a = jax.lax.stop_gradient(adv)
        scale = jnp.sqrt((a ** 2).mean()) + 1e-8
        w = jnp.exp(jnp.clip(beta * a / scale, -5.0, 5.0))
        policy_loss = -(w * logp).mean()
        total = policy_loss + 0.5 * vf_loss
        accuracy = (
            jnp.argmax(out["action_logits"], axis=-1) == batch["actions"]
        ).mean()
        return total, {
            "total_loss": total, "policy_loss": policy_loss,
            "vf_loss": vf_loss, "accuracy": accuracy,
            "mean_weight": w.mean(),
        }

    return loss


@dataclass
class MARWILConfig(BCConfig):
    beta: float = 1.0
    gamma: float = 0.99

    def training(self, lr=None, minibatch_size=None, beta=None, gamma=None):
        super().training(lr=lr, minibatch_size=minibatch_size)
        if beta is not None:
            self.beta = beta
        if gamma is not None:
            self.gamma = gamma
        return self

    def build(self) -> "MARWIL":
        return MARWIL(self)


class MARWIL(BC):
    """Advantage-weighted offline training over a transition Dataset
    (rows need obs/actions/returns — see episodes_to_dataset(gamma=...)).
    The dataset-backed loop streams batches through the Dataset executor
    per epoch instead of materializing everything on the driver."""

    _BATCH_KEYS = ("obs", "actions", "returns")

    def __init__(self, config: MARWILConfig):
        self.config = config
        spec = RLModuleSpec(config.obs_dim, config.num_actions, config.hidden)
        self.module = DiscretePolicyModule(spec)
        self.learner = Learner(
            self.module, marwil_loss(config.beta), seed=config.seed,
            lr=config.lr,
        )
        self._rng = np.random.default_rng(config.seed)

    def train_on_dataset(self, ds, num_epochs: int = 1) -> Dict[str, float]:
        """Streaming epochs: shuffle + iter_batches drives the Dataset's
        executor each epoch; minibatches update as they arrive (the
        reference's OfflineData iter_batches loop, offline/offline_data.py)."""
        metrics: Dict[str, float] = {}
        for epoch in range(num_epochs):
            shuffled = ds.random_shuffle(seed=self.config.seed + epoch)
            for batch in shuffled.iter_batches(
                batch_size=self.config.minibatch_size, batch_format="numpy"
            ):
                mb = {
                    # Row values may arrive as an object array of
                    # per-row ndarrays; stack explicitly.
                    "obs": np.stack([
                        np.asarray(o, dtype=np.float32)
                        for o in batch["obs"]
                    ]),
                    "actions": np.asarray(
                        [int(a) for a in batch["actions"]], dtype=np.int32
                    ),
                    "returns": np.asarray(
                        [float(r) for r in batch["returns"]],
                        dtype=np.float32,
                    ),
                }
                metrics = self.learner.update_from_batch(mb)
        return metrics

    def train_on_batch(self, batch: Dict[str, np.ndarray],
                       num_epochs: int = 1) -> Dict[str, float]:
        from ray_tpu.rl.core.learner import minibatch_epochs

        return minibatch_epochs(
            self.learner.update_from_batch,
            {k: v for k, v in batch.items() if k in self._BATCH_KEYS},
            num_epochs, self.config.minibatch_size, self._rng,
        )
