"""Offline RL: experience IO through ray_tpu.data + behavior cloning.

Analog of the reference's offline RL stack (rllib/offline/: JsonWriter /
JsonReader / the offline data input pipeline, and the BC/MARWIL algorithm
family under rllib/algorithms/bc/): collected episodes persist as a
distributed dataset, and offline algorithms train policies straight from
that dataset without touching an environment.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu import data as rt_data
from ray_tpu.rl.core.learner import Learner
from ray_tpu.rl.core.rl_module import DiscretePolicyModule, RLModuleSpec


def episodes_to_dataset(rollouts: List[Dict[str, np.ndarray]]):
    """Flatten sampled rollout batches into a row-per-transition Dataset
    (reference: JsonWriter writing SampleBatches, rllib/offline/json_writer.py).

    Each row carries obs/action plus whatever per-step fields the rollout
    had (logp, rewards, dones, ...) so downstream offline algorithms can
    pick what they need.
    """
    rows = []
    for b in rollouts:
        T = len(b["actions"])
        step_keys = [
            k for k, v in b.items()
            if isinstance(v, np.ndarray) and v.shape[:1] == (T,)
        ]
        for t in range(T):
            rows.append({k: b[k][t] for k in step_keys})
    return rt_data.from_items(rows)


def dataset_to_batch(ds, keys=("obs", "actions")) -> Dict[str, np.ndarray]:
    """Materialize a transition Dataset into stacked numpy arrays
    (reference: JsonReader producing SampleBatches)."""
    rows = ds.take_all() if hasattr(ds, "take_all") else ds.take(ds.count())
    return {k: np.stack([np.asarray(r[k]) for r in rows]) for k in keys}


def bc_loss(params, module, batch):
    """Negative log-likelihood of the dataset actions (behavior cloning;
    reference: rllib/algorithms/bc/)."""
    out = module.forward(params, batch["obs"])
    logp_all = jax.nn.log_softmax(out["action_logits"])
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][:, None].astype(jnp.int32), axis=-1
    )[:, 0]
    loss = -logp.mean()
    accuracy = (
        jnp.argmax(out["action_logits"], axis=-1) == batch["actions"]
    ).mean()
    return loss, {"total_loss": loss, "accuracy": accuracy}


@dataclass
class BCConfig:
    """Builder-style config for behavior cloning from a Dataset."""

    obs_dim: int = 4
    num_actions: int = 2
    hidden: tuple = (64, 64)
    lr: float = 1e-3
    minibatch_size: int = 128
    seed: int = 0

    def module(self, obs_dim=None, num_actions=None, hidden=None):
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        if hidden is not None:
            self.hidden = hidden
        return self

    def training(self, lr=None, minibatch_size=None):
        if lr is not None:
            self.lr = lr
        if minibatch_size is not None:
            self.minibatch_size = minibatch_size
        return self

    def build(self) -> "BC":
        return BC(self)


class BC:
    """Behavior cloning over an offline transition dataset."""

    def __init__(self, config: BCConfig):
        self.config = config
        spec = RLModuleSpec(config.obs_dim, config.num_actions, config.hidden)
        self.module = DiscretePolicyModule(spec)
        self.learner = Learner(
            self.module, bc_loss, seed=config.seed, lr=config.lr
        )
        self._rng = np.random.default_rng(config.seed)

    def train_on_dataset(self, ds, num_epochs: int = 1) -> Dict[str, float]:
        """Minibatch SGD epochs over the full dataset; returns the last
        update's metrics."""
        batch = dataset_to_batch(ds)
        return self.train_on_batch(batch, num_epochs)

    def train_on_batch(self, batch: Dict[str, np.ndarray],
                       num_epochs: int = 1) -> Dict[str, float]:
        from ray_tpu.rl.core.learner import minibatch_epochs

        return minibatch_epochs(
            self.learner.update_from_batch,
            {k: v for k, v in batch.items() if k in ("obs", "actions")},
            num_epochs, self.config.minibatch_size, self._rng,
        )

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        out = self.module.forward(self.learner.params, obs)
        return np.asarray(jnp.argmax(out["action_logits"], axis=-1))
