"""Workload shapes for the macro traffic harness.

Three orthogonal knobs compose a traffic scenario:

  * ``RateCurve`` — the offered-load trajectory qps(t): a base rate,
    an optional linear ramp, a diurnal sine, and flash crowds (step
    multipliers over fixed windows). Pure function of t, JSON-safe, so
    a recorded trace can carry the exact curve it was generated from.
  * ``LengthMix`` — heavy-tailed prompt/output token lengths: a
    bounded lognormal (body) plus a tail bucket hit with probability
    ``tail_p`` (the long-context requests that dominate engine cost).
  * ``TenantBlend`` — a weighted multi-tenant mix, each tenant with
    its own LengthMix, so fairness/SLO-burn behavior is exercised by
    the same run that measures latency.

Everything draws from a caller-owned ``random.Random`` — the single
seed threaded through ray_tpu.loadgen is what makes a scenario
replayable byte-for-byte.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional, Sequence, Tuple


class RateCurve:
    """Offered load qps(t), t in seconds from the run origin.

    qps(t) = max(base(t) * diurnal(t) * flash(t), 0) where base(t)
    ramps linearly from ``base_qps`` to ``ramp_to_qps`` over
    ``ramp_s`` (then holds), diurnal(t) is 1 + amplitude *
    sin(2*pi*t/period), and flash(t) multiplies by ``mult`` inside
    each (start, duration) window.
    """

    def __init__(self, base_qps: float, ramp_to_qps: Optional[float] = None,
                 ramp_s: float = 0.0, diurnal_amplitude: float = 0.0,
                 diurnal_period_s: float = 86400.0,
                 flash: Sequence[Tuple[float, float, float]] = ()):
        if base_qps < 0:
            raise ValueError("base_qps must be >= 0")
        if not 0.0 <= diurnal_amplitude < 1.0:
            raise ValueError("diurnal_amplitude must be in [0, 1)")
        self.base_qps = float(base_qps)
        self.ramp_to_qps = (
            float(ramp_to_qps) if ramp_to_qps is not None else None)
        self.ramp_s = float(ramp_s)
        self.diurnal_amplitude = float(diurnal_amplitude)
        self.diurnal_period_s = float(diurnal_period_s)
        # (start_s, duration_s, multiplier) step windows.
        self.flash = [(float(s), float(d), float(m)) for s, d, m in flash]

    def qps(self, t: float) -> float:
        base = self.base_qps
        if self.ramp_to_qps is not None and self.ramp_s > 0:
            frac = min(max(t / self.ramp_s, 0.0), 1.0)
            base = base + (self.ramp_to_qps - base) * frac
        if self.diurnal_amplitude:
            base *= 1.0 + self.diurnal_amplitude * math.sin(
                2.0 * math.pi * t / self.diurnal_period_s)
        for start, dur, mult in self.flash:
            if start <= t < start + dur:
                base *= mult
        return max(base, 0.0)

    def peak(self, duration_s: float) -> float:
        """Upper bound on qps over [0, duration_s] — the majorizing rate
        for Poisson thinning. Sampled on a 100ms grid plus the exact
        edges of every flash window (step changes between grid points
        must not be missed)."""
        ts = [i * 0.1 for i in range(int(duration_s * 10) + 1)]
        for start, dur, _ in self.flash:
            ts.extend((start, min(start + dur - 1e-9, duration_s)))
        return max((self.qps(min(t, duration_s)) for t in ts),
                   default=self.base_qps)

    def to_doc(self) -> Dict:
        return {
            "base_qps": self.base_qps,
            "ramp_to_qps": self.ramp_to_qps,
            "ramp_s": self.ramp_s,
            "diurnal_amplitude": self.diurnal_amplitude,
            "diurnal_period_s": self.diurnal_period_s,
            "flash": [list(f) for f in self.flash],
        }

    @classmethod
    def from_doc(cls, doc: Dict) -> "RateCurve":
        return cls(
            base_qps=doc["base_qps"],
            ramp_to_qps=doc.get("ramp_to_qps"),
            ramp_s=doc.get("ramp_s", 0.0),
            diurnal_amplitude=doc.get("diurnal_amplitude", 0.0),
            diurnal_period_s=doc.get("diurnal_period_s", 86400.0),
            flash=[tuple(f) for f in doc.get("flash", [])],
        )


class LengthMix:
    """Heavy-tailed token-length distribution: lognormal body with a
    tail bucket. ``draw`` returns an int clamped to [lo, hi]."""

    def __init__(self, median: int = 128, sigma: float = 0.8,
                 lo: int = 1, hi: int = 4096,
                 tail_p: float = 0.02, tail_lo: int = 1024,
                 tail_hi: int = 4096):
        self.median = int(median)
        self.sigma = float(sigma)
        self.lo = int(lo)
        self.hi = int(hi)
        self.tail_p = float(tail_p)
        self.tail_lo = int(tail_lo)
        self.tail_hi = int(tail_hi)

    def draw(self, rng: random.Random) -> int:
        if self.tail_p and rng.random() < self.tail_p:
            return rng.randint(self.tail_lo, self.tail_hi)
        n = int(round(rng.lognormvariate(math.log(self.median),
                                         self.sigma)))
        return min(max(n, self.lo), self.hi)

    def to_doc(self) -> Dict:
        return {
            "median": self.median, "sigma": self.sigma,
            "lo": self.lo, "hi": self.hi, "tail_p": self.tail_p,
            "tail_lo": self.tail_lo, "tail_hi": self.tail_hi,
        }

    @classmethod
    def from_doc(cls, doc: Dict) -> "LengthMix":
        return cls(**doc)


class TenantBlend:
    """Weighted multi-tenant traffic mix. Each tenant carries its own
    prompt/output LengthMix; ``draw`` picks a tenant then its lengths."""

    def __init__(self, tenants: Sequence[Dict]):
        if not tenants:
            raise ValueError("TenantBlend needs at least one tenant")
        self.tenants: List[Dict] = []
        for t in tenants:
            self.tenants.append({
                "name": t["name"],
                "weight": float(t.get("weight", 1.0)),
                "prompt": (t["prompt"] if isinstance(t.get("prompt"),
                                                     LengthMix)
                           else LengthMix(**(t.get("prompt") or {}))),
                "output": (t["output"] if isinstance(t.get("output"),
                                                     LengthMix)
                           else LengthMix(**(t.get("output") or {}))),
            })
        self._cum: List[float] = []
        total = sum(t["weight"] for t in self.tenants)
        acc = 0.0
        for t in self.tenants:
            acc += t["weight"] / total
            self._cum.append(acc)

    def draw(self, rng: random.Random) -> Dict:
        """One request's shape: {tenant, prompt_tokens, max_tokens}."""
        x = rng.random()
        idx = next((i for i, c in enumerate(self._cum) if x <= c),
                   len(self.tenants) - 1)
        t = self.tenants[idx]
        return {
            "tenant": t["name"],
            "prompt_tokens": t["prompt"].draw(rng),
            "max_tokens": t["output"].draw(rng),
        }

    def to_doc(self) -> Dict:
        return {"tenants": [
            {"name": t["name"], "weight": t["weight"],
             "prompt": t["prompt"].to_doc(), "output": t["output"].to_doc()}
            for t in self.tenants
        ]}

    @classmethod
    def from_doc(cls, doc: Dict) -> "TenantBlend":
        return cls([
            {"name": t["name"], "weight": t["weight"],
             "prompt": LengthMix.from_doc(t["prompt"]),
             "output": LengthMix.from_doc(t["output"])}
            for t in doc["tenants"]
        ])


def default_blend() -> TenantBlend:
    """The stock two-tenant blend benches and the CLI default to: an
    interactive tenant (short prompts, short outputs, 80% of traffic)
    and a batch tenant (long prompts, long outputs, heavy tail)."""
    return TenantBlend([
        {"name": "interactive", "weight": 0.8,
         "prompt": LengthMix(median=64, sigma=0.6, hi=512,
                             tail_p=0.01, tail_lo=256, tail_hi=512),
         "output": LengthMix(median=32, sigma=0.5, hi=256,
                             tail_p=0.01, tail_lo=128, tail_hi=256)},
        {"name": "batch", "weight": 0.2,
         "prompt": LengthMix(median=512, sigma=0.9, hi=4096,
                             tail_p=0.05, tail_lo=2048, tail_hi=4096),
         "output": LengthMix(median=128, sigma=0.7, hi=1024,
                             tail_p=0.03, tail_lo=512, tail_hi=1024)},
    ])
