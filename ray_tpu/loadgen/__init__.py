"""ray_tpu.loadgen: the cluster witness.

A load-generator fleet plus a client<->server latency reconciler —
the macro harness that drives the full handle->replica->engine stack
at sustained multi-tenant load and then checks the serving stack's
own latency attribution against what clients actually observed.

  * workload  — RateCurve (ramps, diurnal, flash crowds), heavy-tailed
                LengthMix, multi-tenant TenantBlend
  * arrival   — open-loop (Poisson / Pareto) and closed-loop arrival
                processes, seeded-deterministic
  * trace     — JSONL record / byte-identical replay
  * client    — per-request stamp cards (send / first byte / chunks /
                done + the observatory rid)
  * reconcile — unattributed_gap = client_e2e - server_attributed,
                p50/p99 + the gap_fraction <= 0.05 gate
  * runner    — the fleet driver (also replays chaos schedules
                anchored to the trace origin)

Entry points: ``rt loadgen`` (CLI) and bench_serve_macro.py (the
pinned headline trajectory).
"""

from ray_tpu.loadgen.arrival import (
    closed_loop_think_times,
    open_loop_arrivals,
)
from ray_tpu.loadgen.client import StampCard, call_streaming, call_unary
from ray_tpu.loadgen.reconcile import (
    GAP_FRACTION_LIMIT,
    collect_server_records,
    reconcile,
    render_report,
)
from ray_tpu.loadgen.runner import (
    RunResult,
    apply_chaos_schedule,
    run_trace,
    serve_call_fn,
)
from ray_tpu.loadgen.trace import TraceSpec, generate, regenerate_bytes
from ray_tpu.loadgen.workload import (
    LengthMix,
    RateCurve,
    TenantBlend,
    default_blend,
)

__all__ = [
    "GAP_FRACTION_LIMIT",
    "LengthMix",
    "RateCurve",
    "RunResult",
    "StampCard",
    "TenantBlend",
    "TraceSpec",
    "apply_chaos_schedule",
    "call_streaming",
    "call_unary",
    "closed_loop_think_times",
    "collect_server_records",
    "default_blend",
    "generate",
    "open_loop_arrivals",
    "reconcile",
    "regenerate_bytes",
    "render_report",
    "run_trace",
    "serve_call_fn",
]
