"""Client stamp cards: the witness's half of the reconciliation.

Every generated request gets a ``StampCard`` — send / first-byte /
per-chunk / done perf-clock stamps plus the observatory rid the handle
exposes (DeploymentResponse.rid / StreamingResponse.rid). The card's
``client_e2e_s`` is measured OUTSIDE the serving stack, so joining it
against the server's six-phase attribution (reconcile.py) makes any
time the server failed to attribute visible as a gap — the server can
no longer grade its own homework.

Clock discipline mirrors the observatory: durations come from
``time.perf_counter()`` deltas on the client (immune to clock steps);
the epoch ``send_t`` is kept only for ordering/joining against
schedule offsets, never differenced against server stamps.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private.config import get_config
from ray_tpu.util import journal


class StampCard:
    """Per-request client-side timing record."""

    __slots__ = ("idx", "tenant", "rid", "sched_t", "send_t", "send_p",
                 "first_byte_p", "chunk_p", "done_p", "error", "chunks")

    def __init__(self, idx: int, tenant: str = "", sched_t: float = 0.0):
        self.idx = idx
        self.tenant = tenant or "default"
        self.rid = ""
        self.sched_t = sched_t        # schedule offset the trace assigned
        self.send_t = 0.0             # epoch at send (ordering only)
        self.send_p = 0.0             # perf stamps: the duration axis
        self.first_byte_p: Optional[float] = None
        self.chunk_p: List[float] = []
        self.done_p: Optional[float] = None
        self.error: Optional[str] = None
        self.chunks = 0

    @property
    def ok(self) -> bool:
        return self.error is None and self.done_p is not None

    @property
    def client_e2e_s(self) -> Optional[float]:
        if self.done_p is None:
            return None
        return self.done_p - self.send_p

    @property
    def ttfb_s(self) -> Optional[float]:
        """Client-observed time to first byte (the TTFT the user sees,
        handle overhead and wire included)."""
        if self.first_byte_p is None:
            return None
        return self.first_byte_p - self.send_p

    def to_doc(self) -> Dict:
        return {
            "idx": self.idx, "tenant": self.tenant, "rid": self.rid,
            "sched_t": self.sched_t, "send_t": self.send_t,
            "client_e2e_s": self.client_e2e_s, "ttfb_s": self.ttfb_s,
            "chunks": self.chunks, "error": self.error,
        }


def call_streaming(handle, request: Dict, card: StampCard) -> StampCard:
    """Issue one streaming request and stamp the card. The handle must
    already be bound to the request's tenant
    (``handle.options(stream=True, tenant=...)``)."""
    card.send_t = time.time()
    card.send_p = time.perf_counter()
    try:
        it = handle.remote(request)
        card.rid = getattr(it, "rid", "") or ""
        for _chunk in it:
            now = time.perf_counter()
            if card.first_byte_p is None:
                card.first_byte_p = now
            card.chunk_p.append(now)
            card.chunks += 1
        card.done_p = time.perf_counter()
    except Exception as e:  # noqa: BLE001 — the card IS the error report;
        # a load generator must survive every per-request failure mode
        # (shed, deadline, replica death past the retry budget).
        card.error = f"{type(e).__name__}: {e}"
        card.done_p = None
        journal.emit("client.error", rid=card.rid, tenant=card.tenant,
                     error=type(e).__name__)
    return card


def call_unary(handle, request: Dict, card: StampCard) -> StampCard:
    """Issue one unary request and stamp the card (first byte == done)."""
    card.send_t = time.time()
    card.send_p = time.perf_counter()
    try:
        resp = handle.remote(request)
        card.rid = getattr(resp, "rid", "") or ""
        resp.result(timeout=get_config().serve_rpc_timeout_s)
        card.done_p = time.perf_counter()
        card.first_byte_p = card.done_p
        card.chunks = 1
    except Exception as e:  # noqa: BLE001 — same contract as streaming:
        # failures are data, not crashes.
        card.error = f"{type(e).__name__}: {e}"
        card.done_p = None
        journal.emit("client.error", rid=card.rid, tenant=card.tenant,
                     error=type(e).__name__)
    return card
