"""Client <-> server latency reconciliation.

Joins client stamp cards (loadgen.client) against the observatory's
per-request six-phase attribution by rid and computes, per request,

    unattributed_gap = client_e2e - server_attributed

where server_attributed is the sum of the server's phase vector
(which itself telescopes to the server-side e2e by construction —
PR 7). The gap is therefore exactly the time the serving stack could
not account for: handle-side routing/dispatch overhead beyond the
stamped hops, response-wire time, long-poll scheduling slack, GIL
stalls in the client. ``gap_fraction = gap / client_e2e`` is the
honest version of the observatory's phase-sum gate: measured from
OUTSIDE, so lost time cannot hide. The macro bench gates p99
gap_fraction at <= 0.05.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ray_tpu.serve.observatory import percentile

#: The macro gate: at p99, at most 5% of client-observed latency may be
#: unattributed by the server's phase vector.
GAP_FRACTION_LIMIT = 0.05


def collect_server_records(app: str,
                           timeout_s: float = 10.0) -> List[Dict]:
    """Fetch finished-request phase records from every live replica of
    ``app`` (ReplicaActor.observatory_records). Replicas that died
    during the run took their ring with them — their requests show up
    as unmatched cards, which the report surfaces rather than hides."""
    import ray_tpu as rt
    from ray_tpu.serve.controller import CONTROLLER_NAME

    ctrl = rt.get_actor(CONTROLLER_NAME)
    info = rt.get(ctrl.get_replicas.remote(app), timeout=timeout_s)
    refs = [r.observatory_records.remote() for r in info["replicas"]]
    ready, _ = rt.wait(refs, num_returns=len(refs), timeout=timeout_s)
    out: List[Dict] = []
    for ref in refs:
        if ref not in ready:
            continue
        try:
            out.extend(rt.get(ref, timeout=1.0))
        except Exception:  # rtlint: disable=RT007 — a replica dying
            # between wait and get is the chaos scenario itself; its
            # requests are reported as unmatched, not raised.
            pass
    return out


def reconcile(cards: Sequence, server_records: Sequence[Dict],
              gap_limit: float = GAP_FRACTION_LIMIT) -> Dict:
    """The reconciliation report.

    Per matched request: client_e2e, server_attributed (phase sum),
    gap seconds and gap fraction (clamped at >= 0 — a small negative
    gap just means the clocks disagree at sub-ms scale). Summary:
    p50/p99 of both, match/unmatch/error counts, and the pass/fail of
    the p99 gap-fraction gate.
    """
    by_rid = {r["rid"]: r for r in server_records if r.get("rid")}
    rows: List[Dict] = []
    unmatched = 0
    errors = 0
    for card in cards:
        if not card.ok:
            errors += 1
            continue
        rec = by_rid.get(card.rid) if card.rid else None
        if rec is None:
            unmatched += 1
            continue
        client_e2e = card.client_e2e_s
        attributed = sum(rec["phases"].values())
        gap = max(client_e2e - attributed, 0.0)
        rows.append({
            "rid": card.rid,
            "tenant": card.tenant,
            "client_e2e_s": client_e2e,
            "server_attributed_s": attributed,
            "server_e2e_s": rec["e2e_s"],
            "gap_s": gap,
            "gap_fraction": gap / client_e2e if client_e2e > 0 else 0.0,
            "ttfb_s": card.ttfb_s,
            "server_ttft_s": rec.get("ttft_s"),
        })
    gaps = sorted(r["gap_s"] for r in rows)
    fracs = sorted(r["gap_fraction"] for r in rows)
    e2es = sorted(r["client_e2e_s"] for r in rows)
    summary = {
        "matched": len(rows),
        "unmatched": unmatched,
        "errors": errors,
        "gap_s": {"p50": percentile(gaps, 0.50),
                  "p99": percentile(gaps, 0.99)},
        "gap_fraction": {"p50": percentile(fracs, 0.50),
                         "p99": percentile(fracs, 0.99)},
        "client_e2e_s": {"p50": percentile(e2es, 0.50),
                         "p99": percentile(e2es, 0.99)},
        "gap_limit": gap_limit,
        # No matches means nothing was witnessed — that must read as a
        # failure, not a vacuous pass.
        "gate_pass": bool(rows) and percentile(fracs, 0.99) <= gap_limit,
    }
    _emit_metrics(summary)
    return {"summary": summary, "requests": rows}


def _emit_metrics(summary: Dict) -> None:
    """Publish the reconciliation summary as loadgen_* gauges (Grafana's
    witness row). Best-effort: reconciliation must work without a
    metrics plane (offline trace analysis)."""
    try:
        from ray_tpu.util.metrics import Gauge, get_or_create

        get_or_create(
            Gauge, "loadgen_gap_fraction",
            "Unattributed fraction of client-observed latency "
            "(client_e2e - server phase sum) / client_e2e, per quantile",
            tag_keys=("q",),
        ).set(summary["gap_fraction"]["p99"], tags={"q": "p99"})
        get_or_create(
            Gauge, "loadgen_unattributed_gap_seconds",
            "Unattributed client<->server latency gap in seconds, "
            "per quantile", tag_keys=("q",),
        ).set(summary["gap_s"]["p99"], tags={"q": "p99"})
    except Exception:  # rtlint: disable=RT007 — metrics are garnish
        # here; the report dict is the product.
        pass


def render_report(report: Dict) -> str:
    """Human-readable reconciliation report (rt loadgen prints this)."""
    s = report["summary"]
    lines = [
        "client <-> server latency reconciliation",
        f"  matched {s['matched']}  unmatched {s['unmatched']}  "
        f"errors {s['errors']}",
        f"  client e2e    p50 {s['client_e2e_s']['p50'] * 1e3:8.1f} ms   "
        f"p99 {s['client_e2e_s']['p99'] * 1e3:8.1f} ms",
        f"  unattributed  p50 {s['gap_s']['p50'] * 1e3:8.1f} ms   "
        f"p99 {s['gap_s']['p99'] * 1e3:8.1f} ms",
        f"  gap fraction  p50 {s['gap_fraction']['p50']:8.4f}      "
        f"p99 {s['gap_fraction']['p99']:8.4f}",
        f"  gate: p99 gap_fraction <= {s['gap_limit']} -> "
        f"{'PASS' if s['gate_pass'] else 'FAIL'}",
    ]
    return "\n".join(lines)
