"""Arrival processes: when each request enters the system.

Two regimes with opposite failure semantics (the distinction every
serious load study hinges on):

  * OPEN LOOP — arrivals are exogenous: a precomputed schedule of
    offsets fires regardless of how the system responds, so queueing
    delay compounds under saturation exactly as it does for real user
    traffic (no coordinated omission). Poisson (memoryless) or Pareto
    (heavy-tailed, bursty) inter-arrivals, modulated by a RateCurve
    via thinning.
  * CLOSED LOOP — arrivals are completion-driven: a bounded fleet of
    virtual users each issues, waits, thinks, repeats. Throughput
    self-limits to what the system serves; concurrency never exceeds
    the bound. Useful for capacity probing, wrong for latency-under-
    overload.

Schedules are pure functions of (spec, seed) — same seed, same floats,
same bytes on disk.
"""

from __future__ import annotations

import random
from typing import List

from ray_tpu.loadgen.workload import RateCurve

#: Arrival process names accepted by open_loop_arrivals.
PROCESSES = ("poisson", "pareto")


def open_loop_arrivals(curve: RateCurve, duration_s: float, seed: int,
                      process: str = "poisson",
                      pareto_alpha: float = 1.5) -> List[float]:
    """Deterministic open-loop arrival offsets in [0, duration_s).

    Poisson: nonhomogeneous via Lewis thinning — candidates at the
    majorizing (peak) rate, kept with probability qps(t)/peak, so the
    realized intensity tracks the RateCurve exactly.

    Pareto: a renewal process whose inter-arrival gaps are Pareto with
    index ``pareto_alpha`` (heavier the closer to 1), scaled so the
    LOCAL mean gap is 1/qps(t) — bursty arrivals with the same average
    load, the regime that breaks queues sized for Poisson.
    """
    if process not in PROCESSES:
        raise ValueError(
            f"unknown arrival process {process!r} (want one of {PROCESSES})")
    if pareto_alpha <= 1.0:
        raise ValueError("pareto_alpha must be > 1 (finite mean)")
    rng = random.Random(seed)
    out: List[float] = []
    if process == "poisson":
        peak = curve.peak(duration_s)
        if peak <= 0:
            return out
        t = 0.0
        while True:
            t += rng.expovariate(peak)
            if t >= duration_s:
                return out
            if rng.random() * peak < curve.qps(t):
                out.append(t)
    # pareto renewal
    mean_pareto = pareto_alpha / (pareto_alpha - 1.0)
    t = 0.0
    while True:
        rate = curve.qps(t)
        if rate <= 0:
            # Dead zone in the curve: step past it without emitting.
            t += 0.1
            if t >= duration_s:
                return out
            continue
        t += rng.paretovariate(pareto_alpha) / (mean_pareto * rate)
        if t >= duration_s:
            return out
        out.append(t)


def closed_loop_think_times(num: int, seed: int,
                            mean_think_s: float = 0.0) -> List[float]:
    """Deterministic per-request think-time draws for a closed-loop run
    (exponential with the given mean; all zeros when mean is 0). Drawn
    up front so the trace can record them and a replay re-uses them."""
    if num < 0:
        raise ValueError("num must be >= 0")
    if mean_think_s <= 0:
        return [0.0] * num
    rng = random.Random(seed)
    return [rng.expovariate(1.0 / mean_think_s) for _ in range(num)]
