"""Trace record / byte-identical replay.

A trace is one JSONL file: a header line (schema, seed, loop kind,
curve/blend docs, optional chaos schedule) followed by one line per
request ({i, t, tenant, prompt_tokens, max_tokens} — ``t`` is the
arrival offset for open loop, the think-time draw for closed loop).

Determinism contract: ``generate(spec)`` is a pure function of the
spec (seed included), and serialization is canonical (sorted keys,
fixed separators, no whitespace variance) — so generating the same
spec twice, or replaying a recorded file through ``generate`` of its
own header, produces byte-identical files. bench_serve_macro gates on
exactly that.
"""

from __future__ import annotations

import json
import random
from typing import Dict, List, Optional, Sequence, Tuple

from ray_tpu.loadgen import arrival
from ray_tpu.loadgen.workload import RateCurve, TenantBlend, default_blend

SCHEMA_VERSION = 1


def _canon(obj: Dict) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


class TraceSpec:
    """Everything needed to regenerate a trace from scratch."""

    def __init__(self, seed: int, duration_s: float, curve: RateCurve,
                 blend: Optional[TenantBlend] = None, kind: str = "open",
                 process: str = "poisson", pareto_alpha: float = 1.5,
                 concurrency: int = 8, num_requests: int = 0,
                 mean_think_s: float = 0.0,
                 chaos: Sequence[Dict] = ()):
        if kind not in ("open", "closed"):
            raise ValueError("kind must be 'open' or 'closed'")
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.curve = curve
        self.blend = blend or default_blend()
        self.kind = kind
        self.process = process
        self.pareto_alpha = float(pareto_alpha)
        self.concurrency = int(concurrency)
        self.num_requests = int(num_requests)
        self.mean_think_s = float(mean_think_s)
        # Schedule-anchored chaos entries ({kind, t, kwargs}) recorded
        # alongside the traffic they were injected into.
        self.chaos = [dict(c) for c in chaos]

    def header(self) -> Dict:
        return {
            "schema": SCHEMA_VERSION,
            "seed": self.seed,
            "duration_s": self.duration_s,
            "kind": self.kind,
            "process": self.process,
            "pareto_alpha": self.pareto_alpha,
            "concurrency": self.concurrency,
            "num_requests": self.num_requests,
            "mean_think_s": self.mean_think_s,
            "curve": self.curve.to_doc(),
            "blend": self.blend.to_doc(),
            "chaos": [
                {"kind": c["kind"], "t": c["t"],
                 "kwargs": dict(c.get("kwargs", {}))}
                for c in self.chaos
            ],
        }

    @classmethod
    def from_header(cls, doc: Dict) -> "TraceSpec":
        if doc.get("schema") != SCHEMA_VERSION:
            raise ValueError(
                f"unsupported trace schema {doc.get('schema')!r} "
                f"(this build reads {SCHEMA_VERSION})")
        return cls(
            seed=doc["seed"], duration_s=doc["duration_s"],
            curve=RateCurve.from_doc(doc["curve"]),
            blend=TenantBlend.from_doc(doc["blend"]),
            kind=doc.get("kind", "open"),
            process=doc.get("process", "poisson"),
            pareto_alpha=doc.get("pareto_alpha", 1.5),
            concurrency=doc.get("concurrency", 8),
            num_requests=doc.get("num_requests", 0),
            mean_think_s=doc.get("mean_think_s", 0.0),
            chaos=doc.get("chaos", ()),
        )


def generate(spec: TraceSpec) -> Tuple[Dict, List[Dict]]:
    """(header, records) for the spec — the deterministic core.

    Open loop: one record per arrival offset. Closed loop: exactly
    ``num_requests`` records, ``t`` holding the pre-drawn think time
    (issue order is the record order; timing is completion-driven).
    Request shapes draw from an rng seeded independently of the
    arrival rng (seed ^ a fixed salt), so changing the arrival process
    does not reshuffle every prompt length.
    """
    shape_rng = random.Random(spec.seed ^ 0x5EED5A17)
    records: List[Dict] = []
    if spec.kind == "open":
        offsets = arrival.open_loop_arrivals(
            spec.curve, spec.duration_s, spec.seed,
            process=spec.process, pareto_alpha=spec.pareto_alpha)
        for i, t in enumerate(offsets):
            shape = spec.blend.draw(shape_rng)
            records.append({"i": i, "t": t, **shape})
    else:
        thinks = arrival.closed_loop_think_times(
            spec.num_requests, spec.seed, spec.mean_think_s)
        for i, t in enumerate(thinks):
            shape = spec.blend.draw(shape_rng)
            records.append({"i": i, "t": t, **shape})
    return spec.header(), records


def dumps(header: Dict, records: List[Dict]) -> str:
    """Canonical JSONL serialization (what byte-identity is defined
    over)."""
    lines = [_canon(header)]
    lines.extend(_canon(r) for r in records)
    return "\n".join(lines) + "\n"


def write(path: str, header: Dict, records: List[Dict]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write(dumps(header, records))


def read(path: str) -> Tuple[Dict, List[Dict]]:
    with open(path, "r", encoding="utf-8") as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"trace {path!r} is empty")
    header = json.loads(lines[0])
    if header.get("schema") != SCHEMA_VERSION:
        raise ValueError(
            f"unsupported trace schema {header.get('schema')!r} in {path!r}")
    return header, [json.loads(ln) for ln in lines[1:]]


def regenerate_bytes(path: str) -> bytes:
    """Re-derive the trace from its own header and return the canonical
    bytes — equal to the file's bytes iff generation is deterministic
    (the replay gate in bench_serve_macro and tests/test_loadgen)."""
    header, _ = read(path)
    spec = TraceSpec.from_header(header)
    new_header, records = generate(spec)
    return dumps(new_header, records).encode("utf-8")
