"""Load-generator fleet driver.

Executes a trace (header + records from loadgen.trace) against a
callable:

  * open loop — a dispatcher thread walks the arrival offsets from a
    perf-clock origin and hands records to a worker pool; arrivals
    fire on schedule whether or not earlier requests finished (no
    coordinated omission — the queue grows, as real traffic would).
  * closed loop — ``concurrency`` virtual users issue, wait, think
    (the record's pre-drawn think time), repeat; in-flight never
    exceeds the bound.

The driver also anchors any chaos schedule recorded in the trace
header at the run's t=0 (chaos.anchor_schedule), so a recorded fault
scenario replays in lockstep with the traffic.

``call_fn(request, card) -> card`` is the pluggable dispatch: the
serve-backed one (serve_call_fn) drives a deployment handle with
client stamp cards; tests substitute a stub and never need a cluster.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ray_tpu.loadgen.client import StampCard, call_streaming, call_unary
from ray_tpu.serve.observatory import percentile

_metrics_lock = threading.Lock()
_metrics: Optional[Dict] = None


def _lg_metrics() -> Dict:
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util import metrics as _mx

            _metrics = {
                "requests": _mx.get_or_create(
                    _mx.Counter, "loadgen_requests_total",
                    "Requests issued by the loadgen fleet, by tenant and "
                    "outcome (ok/error)",
                    tag_keys=("tenant", "outcome"),
                ),
                "e2e_s": _mx.get_or_create(
                    _mx.Histogram, "loadgen_client_e2e_seconds",
                    "Client-observed end-to-end latency (send to last "
                    "chunk), measured outside the serving stack",
                    boundaries=_mx.LATENCY_BOUNDARIES_WIDE,
                    tag_keys=("tenant",),
                ),
                "ttfb_s": _mx.get_or_create(
                    _mx.Histogram, "loadgen_client_ttfb_seconds",
                    "Client-observed time to first byte (the TTFT the "
                    "user sees, handle overhead and wire included)",
                    boundaries=_mx.LATENCY_BOUNDARIES_WIDE,
                    tag_keys=("tenant",),
                ),
                "offered_qps": _mx.get_or_create(
                    _mx.Gauge, "loadgen_offered_qps",
                    "Offered arrival rate of the active loadgen run",
                ),
            }
        return _metrics


class RunResult:
    """Outcome of one trace execution."""

    def __init__(self, cards: List[Optional[StampCard]], kind: str,
                 t0_epoch: float, duration_s: float):
        self.cards = [c for c in cards if c is not None]
        self.kind = kind
        self.t0_epoch = t0_epoch
        self.duration_s = duration_s

    @property
    def ok_cards(self) -> List[StampCard]:
        return [c for c in self.cards if c.ok]

    @property
    def errors(self) -> int:
        return sum(1 for c in self.cards if not c.ok)

    @property
    def achieved_qps(self) -> float:
        if self.duration_s <= 0:
            return 0.0
        return len(self.ok_cards) / self.duration_s

    def summary(self) -> Dict:
        ok = self.ok_cards
        e2es = sorted(c.client_e2e_s for c in ok)
        ttfbs = sorted(c.ttfb_s for c in ok if c.ttfb_s is not None)
        by_tenant: Dict[str, int] = {}
        for c in self.cards:
            by_tenant[c.tenant] = by_tenant.get(c.tenant, 0) + 1
        return {
            "kind": self.kind,
            "issued": len(self.cards),
            "ok": len(ok),
            "errors": self.errors,
            "shed": sum(
                1 for c in self.cards
                if c.error and "ServeOverloadedError" in c.error),
            "duration_s": self.duration_s,
            "achieved_qps": self.achieved_qps,
            "client_e2e_s": {"p50": percentile(e2es, 0.50),
                             "p99": percentile(e2es, 0.99)},
            "client_ttfb_s": {"p50": percentile(ttfbs, 0.50),
                              "p99": percentile(ttfbs, 0.99)},
            "by_tenant": by_tenant,
        }


def serve_call_fn(app: str, stream: bool = True,
                  deadline_s: float = 0.0,
                  max_retries: Optional[int] = None) -> Callable:
    """call_fn driving a serve deployment: one tenant-bound handle per
    tenant (shared router state underneath), streaming or unary."""
    from ray_tpu import serve

    base = serve.get_app_handle(app)
    handles: Dict[str, object] = {}
    hlock = threading.Lock()

    def call(request: Dict, card: StampCard) -> StampCard:
        tenant = request.get("tenant", "")
        with hlock:
            h = handles.get(tenant)
            if h is None:
                kwargs = {"stream": stream, "tenant": tenant,
                          "deadline_s": deadline_s}
                if max_retries is not None:
                    kwargs["max_retries"] = max_retries
                h = base.options(**kwargs)
                handles[tenant] = h
        if stream:
            return call_streaming(h, request, card)
        return call_unary(h, request, card)

    return call


def apply_chaos_schedule(header: Dict) -> int:
    """Register the trace header's chaos entries as schedule-anchored
    faults (chaos must already be enabled). Returns the count
    registered; the runner anchors t=0 when the run starts."""
    from ray_tpu._private import chaos

    entries = header.get("chaos") or []
    for e in entries:
        if e["kind"] == "kill_replica":
            chaos.kill_replica_at(e["t"], **e.get("kwargs", {}))
        elif e["kind"] == "drop_controller":
            chaos.drop_controller_at(e["t"], **e.get("kwargs", {}))
        else:
            raise ValueError(f"unknown chaos kind {e['kind']!r} in trace")
    return len(entries)


def run_trace(header: Dict, records: Sequence[Dict], call_fn: Callable,
              workers: int = 64, emit_metrics: bool = True) -> RunResult:
    """Execute a trace. Open loop uses a ``workers``-thread pool fed on
    the arrival schedule; closed loop runs ``header['concurrency']``
    virtual users. Chaos entries recorded in the header fire relative
    to this run's t=0 when chaos is enabled."""
    from ray_tpu._private import chaos

    kind = header.get("kind", "open")
    m = _lg_metrics() if emit_metrics else None
    if m is not None and header.get("duration_s"):
        m["offered_qps"].set(len(records) / header["duration_s"])
    cards: List[Optional[StampCard]] = [None] * len(records)

    def execute(rec: Dict) -> None:
        card = StampCard(rec["i"], rec.get("tenant", ""),
                         sched_t=rec.get("t", 0.0))
        try:
            call_fn(rec, card)
        except Exception as e:  # noqa: BLE001 — a call_fn that leaks an
            # exception must not kill the worker; the card records it.
            card.error = card.error or f"{type(e).__name__}: {e}"
        cards[rec["i"]] = card
        if m is not None:
            outcome = "ok" if card.ok else "error"
            m["requests"].inc(1, tags={"tenant": card.tenant,  # rtlint: disable=RT013 — tenant set is bounded by the trace file's tenant column, fixed per run
                                       "outcome": outcome})
            if card.ok:
                m["e2e_s"].observe(card.client_e2e_s,  # rtlint: disable=RT013 — bounded: tenants are fixed per trace
                                   tags={"tenant": card.tenant})
                if card.ttfb_s is not None:
                    m["ttfb_s"].observe(card.ttfb_s,  # rtlint: disable=RT013 — bounded: tenants are fixed per trace
                                        tags={"tenant": card.tenant})

    t0_epoch = time.time()
    if chaos.enabled() and (header.get("chaos") or []):
        chaos.anchor_schedule()
    t0 = time.perf_counter()
    if kind == "open":
        _drive_open(records, execute, workers)
    else:
        _drive_closed(records, execute,
                      int(header.get("concurrency", 8)))
    duration = time.perf_counter() - t0
    return RunResult(cards, kind, t0_epoch, duration)


def _drive_open(records: Sequence[Dict], execute: Callable,
                workers: int) -> None:
    q: queue_mod.Queue = queue_mod.Queue()
    threads = [
        threading.Thread(target=_pool_worker, args=(q, execute),
                         name=f"rt-loadgen-{i}", daemon=True)
        for i in range(max(1, workers))
    ]
    for t in threads:
        t.start()
    t0 = time.perf_counter()
    for rec in records:
        delay = rec["t"] - (time.perf_counter() - t0)
        if delay > 0:
            time.sleep(delay)
        # Behind schedule: fire immediately (open loop never skips —
        # lateness shows up as queueing, exactly like real overload).
        q.put(rec)
    for _ in threads:
        q.put(None)
    for t in threads:
        t.join()


def _pool_worker(q: "queue_mod.Queue", execute: Callable) -> None:
    while True:
        rec = q.get()
        if rec is None:
            return
        execute(rec)


def _drive_closed(records: Sequence[Dict], execute: Callable,
                  concurrency: int) -> None:
    it = iter(records)
    lock = threading.Lock()

    def user() -> None:
        while True:
            with lock:
                rec = next(it, None)
            if rec is None:
                return
            execute(rec)
            think = rec.get("t", 0.0)
            if think > 0:
                time.sleep(think)

    threads = [
        threading.Thread(target=user, name=f"rt-loadgen-user-{i}",
                         daemon=True)
        for i in range(max(1, concurrency))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
