"""`python -m ray_tpu` == the `rt` CLI."""

from ray_tpu.scripts.scripts import main

main()
