"""ray_tpu: a TPU-native distributed runtime and AI library stack.

A ground-up rebuild of the capabilities of the reference Ray monorepo
(ray-project/ray, see SURVEY.md) designed for TPU pods: the scheduler
treats TPU chips and pod slices as first-class resources, collectives run
over ICI/DCN via XLA, and the training/serving stacks are JAX-first.

Public core API mirrors the reference (python/ray/__init__.py):
init / shutdown / remote / get / put / wait / kill / get_actor / ...
"""

from __future__ import annotations

import atexit
import inspect
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu._version import version as __version__  # noqa: F401
from ray_tpu import exceptions  # noqa: F401
from ray_tpu._private import worker as _worker
from ray_tpu._private.ids import JobID
from ray_tpu._private.worker import (  # noqa: F401
    ActorHandle,
    ObjectRef,
    ObjectRefGenerator,
)
from ray_tpu.actor import ActorClass, method  # noqa: F401
from ray_tpu.remote_function import RemoteFunction

_node = None
_client = None


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    object_store_memory: Optional[int] = None,
    local_mode: bool = False,
    labels: Optional[Dict[str, str]] = None,
    ignore_reinit_error: bool = False,
    runtime_env: Optional[Dict] = None,
):
    """Start (or connect to) a ray_tpu cluster.

    Reference analog: ray.init (python/ray/_private/worker.py:1228). With no
    address this bootstraps a head node in-process (GCS + raylet services on
    a background event loop; worker processes are real subprocesses).
    With address="host:port" it connects to an existing GCS as a new node;
    with address="rt://host:port" it attaches as a REMOTE driver (the
    reference's Ray Client, ray://): no local node, no shared memory —
    puts/gets proxy through the head raylet over TCP.
    """
    global _node, _client
    if _worker.is_initialized():
        if ignore_reinit_error:
            return
        raise RuntimeError("ray_tpu.init() called twice")

    if address is not None and address.startswith("rt://"):
        bad = {
            "num_cpus": num_cpus, "num_tpus": num_tpus,
            "resources": resources,
            "object_store_memory": object_store_memory,
            "labels": labels, "local_mode": local_mode or None,
        }
        bad = [k for k, v in bad.items() if v]
        if bad:
            raise ValueError(
                f"rt:// remote drivers attach without a local node; "
                f"{bad} cannot apply (configure nodes cluster-side)"
            )
        _client = _remote_attach(address.removeprefix("rt://"))
        if runtime_env:
            _client.default_runtime_env = runtime_env
        _worker.set_client(_client, "driver", None)
        atexit.register(shutdown)
        return

    if local_mode:
        from ray_tpu._private.local_mode import LocalClient

        client = LocalClient(resources)
        _worker.set_client(client, "local")
        _client = client
        return

    from ray_tpu._private.node import Node

    if address is None:
        _node = Node(
            head=True,
            num_cpus=num_cpus,
            num_tpus=num_tpus,
            resources=resources,
            object_store_memory=object_store_memory,
            labels=labels,
        )
        _client = _node.make_client()
    else:
        # Join an existing cluster as a new node + driver.
        _node = Node(
            head=False,
            gcs_address=address,
            num_cpus=num_cpus if num_cpus is not None else 0,
            num_tpus=num_tpus,
            resources=resources,
            object_store_memory=object_store_memory,
            labels=labels,
        )
        _client = _node.make_client()
    if runtime_env:
        _client.default_runtime_env = runtime_env
    else:
        # A driver launched by the job supervisor inherits the job-level
        # runtime env (already resolved to URIs + hash by the submitter).
        import json as _json
        import os as _os

        job_env = _os.environ.get("RT_JOB_RUNTIME_ENV")
        if job_env:
            _client.default_runtime_env = _json.loads(job_env)
    _worker.set_client(_client, "driver", _node)
    atexit.register(shutdown)


def _remote_attach(address: str):
    """Attach as a remote (rt://) driver: connect to the GCS, find the head
    raylet, and build a storeless CoreClient proxying through it."""
    import asyncio as _asyncio

    from ray_tpu._private.ids import JobID as _JobID
    from ray_tpu._private.node import EventLoopThread
    from ray_tpu._private.protocol import connect as _connect
    from ray_tpu._private.worker import CoreClient

    host, port = address.rsplit(":", 1)
    io = EventLoopThread("rt-client")

    async def _find_head():
        gcs = await _connect(host, int(port))
        try:
            nodes = (await gcs.call("get_nodes", {}))["nodes"]
        finally:
            await gcs.close()
        heads = [n for n in nodes if n["state"] == "ALIVE" and n.get("is_head")]
        alive = heads or [n for n in nodes if n["state"] == "ALIVE"]
        if not alive:
            raise ConnectionError(f"no live nodes behind rt://{address}")
        return alive[0]

    try:
        head = io.run(_find_head())
        client = CoreClient(
            io.loop,
            (host, int(port)),
            (head["address"], head["port"]),
            None,  # no local store: remote mode
            head["node_id"],
            _JobID.from_random(),
            mode="driver",
        )
        client.connect()
    except BaseException:
        io.stop()  # failed attach must not leak the loop thread
        raise
    client._owns_io = io  # torn down in disconnect via shutdown()
    return client


def shutdown():
    """Tear down the cluster started by init() (reference: ray.shutdown)."""
    global _node, _client
    if _client is not None:
        try:
            _client.disconnect()
        except Exception:
            pass
        io = getattr(_client, "_owns_io", None)
        if io is not None:  # remote (rt://) driver owns its loop thread
            try:
                io.stop()
            except Exception:
                pass
        _client = None
    if _node is not None:
        try:
            _node.stop()
        except Exception:
            pass
        _node = None
    _worker.set_client(None, None)


def is_initialized() -> bool:
    return _worker.is_initialized()


def remote(*args, **options):
    """@remote decorator for functions and classes (reference:
    python/ray/remote_function.py:40, python/ray/actor.py)."""

    def decorate(obj):
        if inspect.isclass(obj):
            return ActorClass(obj, **options)
        return RemoteFunction(obj, **options)

    if len(args) == 1 and callable(args[0]) and not options:
        return decorate(args[0])
    if args:
        raise TypeError("@remote options must be keyword arguments")
    return decorate


def get(
    refs: Union[ObjectRef, Sequence[ObjectRef]],
    *,
    timeout: Optional[float] = None,
):
    """Fetch object values (reference: ray.get, _private/worker.py:2570).

    Accepts ObjectRefs and objects exposing one via `.ref` (e.g.
    serve.DeploymentResponse), matching ray.get's handling of responses.
    """
    client = _worker.get_client()
    if not isinstance(refs, ObjectRef) and hasattr(refs, "ref"):
        refs = refs.ref
    if isinstance(refs, ObjectRef):
        return client.get([refs], timeout)[0]
    return client.get(
        [r.ref if not isinstance(r, ObjectRef) and hasattr(r, "ref") else r
         for r in refs],
        timeout,
    )


def put(value: Any) -> ObjectRef:
    """Store a value in the object store (reference: ray.put,
    _private/worker.py:2688)."""
    return _worker.get_client().put(value)


def prefetch(refs: Union[ObjectRef, Sequence[ObjectRef]]) -> int:
    """Start pulling remote objects to this node without blocking.

    A later get() on the same refs joins the in-flight pull instead of
    starting its own probe, so transfer overlaps whatever the caller does
    in between. Purely advisory: failures are deferred to get(), which
    re-resolves with full reconstruction semantics. Returns the number of
    pulls started (already-local refs are skipped)."""
    if isinstance(refs, ObjectRef):
        refs = [refs]
    return _worker.get_client().prefetch(
        [r.ref if not isinstance(r, ObjectRef) and hasattr(r, "ref") else r
         for r in refs]
    )


def wait(
    refs: List[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    """Wait for refs to complete (reference: ray.wait)."""
    return _worker.get_client().wait(refs, num_returns, timeout, fetch_local)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    """Forcefully stop an actor (reference: ray.kill)."""
    actor._kill(no_restart)


def cancel(ref: ObjectRef, *, force: bool = False):
    """Best-effort task cancellation (reference: ray.cancel)."""
    # Round 1: cancellation only prevents un-dispatched local work.
    if ref._future is not None:
        ref._future.cancel()


def get_actor(name: str, namespace: str = "") -> ActorHandle:
    """Look up a named actor (reference: ray.get_actor)."""
    return _worker.get_client().get_actor_by_name(name, namespace)


def nodes() -> List[dict]:
    """Cluster node table (reference: ray.nodes)."""
    return _worker.get_client().nodes()


def cluster_resources() -> Dict[str, float]:
    return _worker.get_client().cluster_resources()


def available_resources() -> Dict[str, float]:
    return _worker.get_client().available_resources()


def get_runtime_context():
    from ray_tpu.runtime_context import get_runtime_context as _grc

    return _grc()


__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "prefetch",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "nodes",
    "cluster_resources",
    "available_resources",
    "get_runtime_context",
    "method",
    "ObjectRef",
    "ActorHandle",
    "exceptions",
    "__version__",
]
