"""Bridge so `ray_tpu.tune.report(...)` works inside trial functions."""

from __future__ import annotations

from typing import Optional

from ray_tpu.train.session import TrainSession

_active: Optional[TrainSession] = None


def set_active_session(session: TrainSession):
    global _active
    _active = session


def get_active_session() -> TrainSession:
    if _active is None:
        raise RuntimeError("no active tune session in this process")
    return _active
