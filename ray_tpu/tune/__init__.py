"""ray_tpu.tune: hyperparameter search.

Public surface mirrors the reference's ray.tune: Tuner/TuneConfig/
ResultGrid, sample domains (uniform/loguniform/choice/randint/grid_search),
schedulers (ASHA, median stopping), and tune.report inside trials.
"""

from typing import Dict, Optional

from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    BOHBSearcher,
    BasicVariantGenerator,
    Choice,
    ExternalSearcher,
    ConcurrencyLimiter,
    Domain,
    GridSearch,
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, Tuner


def with_parameters(trainable, **large_objects):
    """Attach large constant objects to a trainable WITHOUT serializing
    them into every trial's config (reference: tune.with_parameters):
    each object goes to the object store once; trials fetch by ref.

        tuner = Tuner(tune.with_parameters(train_fn, data=big_df), ...)
        def train_fn(config, data): ...
    """
    import ray_tpu as rt

    refs = {k: rt.put(v) for k, v in large_objects.items()}

    def wrapped(config):
        resolved = {k: rt.get(r) for k, r in refs.items()}
        return trainable(config, **resolved)

    wrapped.__name__ = getattr(trainable, "__name__", "trainable")
    if hasattr(trainable, "_tune_resources"):
        # Compose with with_resources in either order.
        wrapped._tune_resources = trainable._tune_resources
    return wrapped


def with_resources(trainable, resources: Dict[str, float]):
    """Attach per-trial resource requests to a trainable (reference:
    tune.with_resources): every trial actor of this trainable requests
    them, overriding TuneConfig.trial_resources.

        tuner = Tuner(tune.with_resources(train_fn, {"CPU": 2}), ...)
    """

    import copy
    import functools

    if hasattr(trainable, "as_trainable"):
        # Trainer objects keep their as_trainable dispatch: pin the
        # resources on a copy instead of wrapping.
        t = copy.copy(trainable)
        t._tune_resources = dict(resources)
        return t

    # functools.wraps sets __wrapped__, so the trial runner's signature
    # inspection sees the original arity — no dispatch duplication here.
    @functools.wraps(trainable)
    def wrapped(*args, **kwargs):
        return trainable(*args, **kwargs)

    wrapped._tune_resources = dict(resources)
    return wrapped


def report(metrics: Dict, checkpoint=None):
    """Report metrics from inside a trial (reference: tune.report /
    session.report)."""
    from ray_tpu.tune.session_bridge import get_active_session

    get_active_session().report(metrics, checkpoint)


def get_checkpoint():
    from ray_tpu.tune.session_bridge import get_active_session

    return get_active_session().get_checkpoint()


__all__ = [
    "Tuner",
    "TuneConfig",
    "ResultGrid",
    "report",
    "get_checkpoint",
    "with_parameters",
    "with_resources",
    "uniform",
    "loguniform",
    "choice",
    "randint",
    "grid_search",
    "BasicVariantGenerator",
    "ConcurrencyLimiter",
    "Searcher",
    "TPESearcher",
    "BOHBSearcher",
    "ExternalSearcher",
    "ASHAScheduler",
    "PB2",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "FIFOScheduler",
    "TrialScheduler",
    "Domain",
    "Choice",
    "GridSearch",
]
