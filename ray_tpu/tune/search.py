"""Search spaces and search algorithms.

Analog of the reference's tune.search surface: sample domains
(tune/search/sample.py), grid/random generation (basic_variant.py), and
ConcurrencyLimiter.
"""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclass
class RandInt(Domain):
    low: int
    high: int

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


@dataclass
class Choice(Domain):
    categories: List[Any]

    def sample(self, rng):
        return rng.choice(self.categories)


@dataclass
class GridSearch:
    values: List[Any]


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def choice(categories) -> Choice:
    return Choice(list(categories))


def grid_search(values) -> GridSearch:
    return GridSearch(list(values))


class Searcher:
    """Suggest configs one at a time (reference: tune/search/searcher.py)."""

    def suggest(self, trial_id: str) -> Optional[Dict]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[Dict] = None):
        pass


class BasicVariantGenerator(Searcher):
    """Grid x random sampling (reference: tune/search/basic_variant.py)."""

    def __init__(self, param_space: Dict, num_samples: int = 1, seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        self._variants = list(self._generate())
        self._idx = 0

    def _generate(self) -> Iterator[Dict]:
        grid_keys = [
            k for k, v in self.param_space.items() if isinstance(v, GridSearch)
        ]
        grid_values = [self.param_space[k].values for k in grid_keys]
        combos = list(itertools.product(*grid_values)) if grid_keys else [()]
        for _ in range(self.num_samples):
            for combo in combos:
                config = {}
                for k, v in self.param_space.items():
                    if isinstance(v, GridSearch):
                        config[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        config[k] = v.sample(self.rng)
                    elif callable(v) and not isinstance(v, type):
                        config[k] = v()
                    else:
                        config[k] = v
                yield config

    @property
    def total_trials(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if self._idx >= len(self._variants):
            return None
        config = self._variants[self._idx]
        self._idx += 1
        return config


class ConcurrencyLimiter(Searcher):
    """Cap in-flight suggestions (reference: tune/search/ConcurrencyLimiter)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self.live: set = set()

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if len(self.live) >= self.max_concurrent:
            return None
        config = self.searcher.suggest(trial_id)
        if config is not None:
            self.live.add(trial_id)
        return config

    def on_trial_complete(self, trial_id: str, result=None):
        self.live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result)
