"""Search spaces and search algorithms.

Analog of the reference's tune.search surface: sample domains
(tune/search/sample.py), grid/random generation (basic_variant.py), and
ConcurrencyLimiter.
"""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclass
class RandInt(Domain):
    low: int
    high: int

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


@dataclass
class Choice(Domain):
    categories: List[Any]

    def sample(self, rng):
        return rng.choice(self.categories)


@dataclass
class GridSearch:
    values: List[Any]


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def choice(categories) -> Choice:
    return Choice(list(categories))


def grid_search(values) -> GridSearch:
    return GridSearch(list(values))


# Sentinel a searcher returns when it has nothing to suggest *right now*
# but is not exhausted (reference: Searcher.FINISHED vs. deferred
# suggestions in tune/search/search_generator.py). None still means "no
# more trials ever".
PAUSED = "__tune_paused__"


class Searcher:
    """Suggest configs one at a time (reference: tune/search/searcher.py)."""

    def suggest(self, trial_id: str) -> Optional[Dict]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[Dict] = None):
        pass


class BasicVariantGenerator(Searcher):
    """Grid x random sampling (reference: tune/search/basic_variant.py)."""

    def __init__(self, param_space: Dict, num_samples: int = 1, seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        self._variants = list(self._generate())
        self._idx = 0

    def _generate(self) -> Iterator[Dict]:
        grid_keys = [
            k for k, v in self.param_space.items() if isinstance(v, GridSearch)
        ]
        grid_values = [self.param_space[k].values for k in grid_keys]
        combos = list(itertools.product(*grid_values)) if grid_keys else [()]
        for _ in range(self.num_samples):
            for combo in combos:
                config = {}
                for k, v in self.param_space.items():
                    if isinstance(v, GridSearch):
                        config[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        config[k] = v.sample(self.rng)
                    elif callable(v) and not isinstance(v, type):
                        config[k] = v()
                    else:
                        config[k] = v
                yield config

    @property
    def total_trials(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if self._idx >= len(self._variants):
            return None
        config = self._variants[self._idx]
        self._idx += 1
        return config


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator search (Bergstra et al. 2011).

    The native model-based searcher — the same algorithm the reference
    reaches through its Optuna integration (tune/search/optuna/, whose
    default sampler is TPE). Completed trials split into a good quantile
    l(x) and the rest g(x); each dimension is modeled with a kernel
    density over observed values, candidates are drawn from l and ranked
    by the acquisition ratio l(x)/g(x).

    Numeric domains (Uniform/LogUniform/RandInt) use Gaussian kernels
    (log-space for LogUniform); Choice uses smoothed categorical counts.
    Falls back to random sampling until `n_startup` results exist.
    """

    def __init__(self, param_space: Dict, metric: str, mode: str = "min",
                 num_samples: int = 32, gamma: float = 0.25,
                 n_startup: int = 8, n_candidates: int = 24,
                 seed: Optional[int] = None):
        assert mode in ("min", "max")
        self.param_space = param_space
        self.metric = metric
        self.mode = mode
        self.num_samples = num_samples
        self.gamma = gamma
        self.n_startup = n_startup
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._suggested = 0
        self._configs: Dict[str, Dict] = {}  # trial_id -> config
        self._observations: List[tuple] = []  # (config, score)

    def _random_config(self) -> Dict:
        config = {}
        for k, v in self.param_space.items():
            if isinstance(v, GridSearch):
                config[k] = self.rng.choice(v.values)
            elif isinstance(v, Domain):
                config[k] = v.sample(self.rng)
            elif callable(v) and not isinstance(v, type):
                config[k] = v()
            else:
                config[k] = v
        return config

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        if len(self._observations) < self.n_startup:
            config = self._random_config()
        else:
            config = self._tpe_config()
        self._configs[trial_id] = config
        return config

    def on_trial_complete(self, trial_id: str, result: Optional[Dict] = None):
        config = self._configs.pop(trial_id, None)
        if config is None or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "max":
            score = -score  # store as minimization
        self._observations.append((config, score))

    # -- TPE core --------------------------------------------------------
    def _split(self):
        ranked = sorted(self._observations, key=lambda cs: cs[1])
        n_good = max(1, int(len(ranked) * self.gamma))
        return [c for c, _ in ranked[:n_good]], [c for c, _ in ranked[n_good:]]

    def _tpe_config(self) -> Dict:
        good, bad = self._split()
        best, best_score = None, -float("inf")
        for _ in range(self.n_candidates):
            cand = {}
            ratio = 0.0
            for k, dom in self.param_space.items():
                if isinstance(dom, (Uniform, LogUniform, RandInt)):
                    val, r = self._numeric_dim(k, dom, good, bad)
                elif isinstance(dom, (Choice, GridSearch)):
                    cats = dom.categories if isinstance(dom, Choice) else dom.values
                    val, r = self._categorical_dim(k, cats, good, bad)
                elif isinstance(dom, Domain):
                    val, r = dom.sample(self.rng), 0.0
                else:
                    val, r = (dom() if callable(dom) and not isinstance(dom, type)
                              else dom), 0.0
                cand[k] = val
                ratio += r
            if ratio > best_score:
                best, best_score = cand, ratio
        return best

    def _numeric_dim(self, key, dom, good, bad):
        import math

        log = isinstance(dom, LogUniform)
        to_x = (lambda v: math.log(v)) if log else (lambda v: float(v))
        lo, hi = to_x(dom.low), to_x(dom.high if not isinstance(dom, RandInt)
                                     else dom.high - 1)
        goods = [to_x(c[key]) for c in good if key in c]
        bads = [to_x(c[key]) for c in bad if key in c]
        width = max(hi - lo, 1e-12)
        # Silverman-style bandwidth from the good points' spread: a
        # domain-width-based bandwidth degenerates with few goods (kernels
        # so wide the acquisition peaks at the domain boundary).
        n = max(len(goods), 1)
        if len(goods) >= 2:
            mean = sum(goods) / n
            spread = (sum((g - mean) ** 2 for g in goods) / n) ** 0.5
            spread = spread or width * 0.05
        else:
            spread = width * 0.25
        bw = max(min(1.06 * spread * n ** -0.2, width), width * 0.02)
        # Sample from l(x): pick a good point's kernel, draw, clamp.
        center = self.rng.choice(goods) if goods else self.rng.uniform(lo, hi)
        x = min(hi, max(lo, self.rng.gauss(center, bw)))

        def kde(pts, x):
            if not pts:
                return 1.0 / width  # uniform prior
            s = sum(
                math.exp(-0.5 * ((x - p) / bw) ** 2) / (bw * 2.5066282746)
                for p in pts
            )
            # Mix with the uniform prior so g(x) never hits zero.
            return 0.9 * s / len(pts) + 0.1 / width

        ratio = math.log(kde(goods, x)) - math.log(kde(bads, x))
        val = math.exp(x) if log else x
        if isinstance(dom, RandInt):
            val = min(dom.high - 1, max(dom.low, int(round(val))))
        return val, ratio

    def _categorical_dim(self, key, cats, good, bad):
        import math

        def probs(configs):
            counts = {repr(c): 1.0 for c in cats}  # +1 smoothing
            for cfg in configs:
                if key in cfg:
                    counts[repr(cfg[key])] = counts.get(repr(cfg[key]), 1.0) + 1
            total = sum(counts.values())
            return {k: v / total for k, v in counts.items()}

        pg, pb = probs(good), probs(bad)
        # Sample category from l, score by log ratio.
        cats_list = list(cats)
        weights = [pg[repr(c)] for c in cats_list]
        val = self.rng.choices(cats_list, weights=weights, k=1)[0]
        return val, math.log(pg[repr(val)]) - math.log(pb[repr(val)])


class ConcurrencyLimiter(Searcher):
    """Cap in-flight suggestions (reference: tune/search/ConcurrencyLimiter)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self.live: set = set()

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if len(self.live) >= self.max_concurrent:
            return PAUSED  # at cap now; ask again after a completion
        config = self.searcher.suggest(trial_id)
        if config is not None and config is not PAUSED:
            self.live.add(trial_id)
        return config

    def on_trial_complete(self, trial_id: str, result=None):
        self.live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result)


class ExternalSearcher(Searcher):
    """Adapter seam for third-party optimizers (the role of the
    reference's tune/search/ integrations — optuna/hyperopt/etc. each
    wrap an external ask/tell library behind Searcher).

    Wrap ANY object exposing `ask() -> (token, config)` and
    `tell(token, score)` (the near-universal external-optimizer
    protocol); metric extraction and min/max normalization happen here,
    so the external library always minimizes.
    """

    def __init__(self, external, metric: str, mode: str = "min",
                 num_samples: int = 32):
        assert mode in ("min", "max")
        if not callable(getattr(external, "ask", None)) or not callable(
            getattr(external, "tell", None)
        ):
            raise TypeError(
                "external optimizer must expose ask() -> (token, config) "
                "and tell(token, score)"
            )
        self.external = external
        self.metric = metric
        self.mode = mode
        self.num_samples = num_samples
        self._suggested = 0
        self._tokens: Dict[str, Any] = {}

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        token, config = self.external.ask()
        self._tokens[trial_id] = token
        return dict(config)

    def on_trial_complete(self, trial_id: str, result: Optional[Dict] = None):
        token = self._tokens.pop(trial_id, None)
        if token is None or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "max":
            score = -score
        try:
            self.external.tell(token, score)
        except Exception:  # noqa: BLE001 — a broken lib must not kill tuning
            pass


class BOHBSearcher(TPESearcher):
    """Bayesian-optimization HyperBand searcher (reference: TuneBOHB,
    tune/search/bohb/ — BOHB, Falkner et al. 2018). Pair with
    ASHAScheduler (the HyperBandForBOHB role): the scheduler provides the
    successive-halving rungs; this searcher fits its TPE model on results
    from the HIGHEST fidelity (training_iteration rung) that has enough
    observations, falling back rung-by-rung — BOHB's model-selection
    rule — instead of modeling only completed trials.
    """

    def __init__(self, param_space: Dict, metric: str, mode: str = "min",
                 time_attr: str = "training_iteration", **kwargs):
        super().__init__(param_space, metric, mode, **kwargs)
        self.time_attr = time_attr
        # rung (fidelity) -> list[(config, minimized_score)]
        self._rung_obs: Dict[int, List[tuple]] = {}

    def on_trial_result(self, trial_id: str, result: Dict):
        """Intermediate results land in their fidelity rung."""
        config = self._configs.get(trial_id)
        if config is None or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "max":
            score = -score
        rung = int(result.get(self.time_attr, 0))
        self._rung_obs.setdefault(rung, []).append((dict(config), score))
        self._refresh_model()

    def on_trial_complete(self, trial_id: str, result: Optional[Dict] = None):
        super().on_trial_complete(trial_id, result)
        self._refresh_model()

    def _refresh_model(self):
        """Model on the highest rung with >= n_startup points (BOHB's
        choose-the-best-budget rule); completed-trial observations from
        the base class stay as the fallback."""
        for rung in sorted(self._rung_obs, reverse=True):
            obs = self._rung_obs[rung]
            if len(obs) >= self.n_startup:
                self._observations = list(obs)
                return
