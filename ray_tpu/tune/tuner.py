"""Tuner: the experiment controller.

Analog of the reference's Tuner.fit (tune/tuner.py:346) → tune.run
(tune/tune.py:234) → TuneController (tune/execution/tune_controller.py:72,
event loop step() :709) managing Trials as remote actors. Collapsed here
into one controller loop: trials run as session-carrying actors
(reference: Trainable actors in placement groups), the searcher feeds
configs, the scheduler may stop trials early, and experiment state is
snapshotted to storage for restore.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu as rt
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import Result, RunConfig
from ray_tpu.tune.schedulers import (
    CONTINUE,
    EXPLOIT,
    STOP,
    FIFOScheduler,
    TrialScheduler,
)
from ray_tpu.tune.search import PAUSED, BasicVariantGenerator, Searcher


@dataclass
class TuneConfig:
    """Analog of tune.TuneConfig."""

    metric: Optional[str] = None
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    trial_resources: Optional[Dict[str, float]] = None
    seed: Optional[int] = None


@rt.remote
class _TrialActor:
    """Runs one trial's function with a reporting session (reference:
    Trainable actor, tune/trainable/trainable.py:61)."""

    def __init__(self, trial_id: str, trial_dir: str):
        from ray_tpu.train.session import init_session

        self.trial_id = trial_id
        self.trial_dir = trial_dir
        self._thread = None
        self._error = None
        self._done = False
        self.session = None

    def run(self, fn, config, checkpoint):
        import threading

        from ray_tpu.train.session import TrainSession

        self.session = TrainSession(
            world_rank=0,
            world_size=1,
            config=config,
            checkpoint=checkpoint,
            trial_dir=self.trial_dir,
        )
        import ray_tpu.tune.session_bridge as bridge

        bridge.set_active_session(self.session)

        def go():
            try:
                import inspect

                params = list(inspect.signature(fn).parameters)
                if len(params) >= 2:
                    fn(config, self.session)
                else:
                    fn(config)
            except BaseException as e:  # noqa: BLE001
                import traceback

                self._error = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
            finally:
                self._done = True

        self._thread = threading.Thread(target=go, daemon=True)
        self._thread.start()
        return True

    def poll(self):
        reports = self.session.drain() if self.session else []
        return {
            "reports": [
                {
                    "metrics": r["metrics"],
                    "checkpoint_path": r["checkpoint"].path if r["checkpoint"] else None,
                }
                for r in reports
            ],
            "done": self._done,
            "error": self._error,
        }


@dataclass
class Trial:
    trial_id: str
    config: Dict
    state: str = "PENDING"  # PENDING RUNNING TERMINATED STOPPED ERROR
    actor: Any = None
    last_metrics: Dict = field(default_factory=dict)
    metrics_history: List[Dict] = field(default_factory=list)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[str] = None
    iteration: int = 0
    trial_dir: str = ""
    failures: int = 0


class ResultGrid:
    """Analog of tune.ResultGrid."""

    def __init__(self, results: List[Result], trials: List[Trial],
                 metric: Optional[str], mode: str):
        self._results = results
        self._trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    @property
    def errors(self):
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (set TuneConfig.metric)")
        candidates = [r for r in self._results if metric in (r.metrics or {})]
        if not candidates:
            raise ValueError(f"no trial reported metric {metric!r}")
        return (max if mode == "max" else min)(
            candidates, key=lambda r: r.metrics[metric]
        )

    def get_dataframe(self):
        rows = []
        for t, r in zip(self._trials, self._results):
            row = {"trial_id": t.trial_id, **{f"config/{k}": v for k, v in t.config.items()}}
            row.update(r.metrics or {})
            rows.append(row)
        return rows


class Tuner:
    """Analog of tune.Tuner (tuner.py:346)."""

    def __init__(
        self,
        trainable: Callable,
        *,
        param_space: Optional[Dict] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        # Trainers adapt via as_trainable() (reference: base_trainer.py:839).
        if hasattr(trainable, "as_trainable"):
            trainable = trainable.as_trainable()
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._restore_state: Optional[List[Dict]] = None

    @classmethod
    def restore(cls, path: str, trainable: Callable,
                tune_config: Optional[TuneConfig] = None) -> "Tuner":
        """Resume an interrupted experiment from its directory
        (reference: Tuner.restore, tune/tuner.py): finished trials keep
        their recorded results; unfinished ones re-run from their newest
        checkpoint. `path` is the experiment dir (the run's
        resolved_storage_path)."""
        path = os.path.abspath(path)
        state_file = os.path.join(path, "experiment_state.json")
        with open(state_file) as f:
            state = json.load(f)
        run_config = RunConfig(
            name=os.path.basename(path),
            storage_path=os.path.dirname(path),
        )
        tuner = cls(trainable, tune_config=tune_config,
                    run_config=run_config)
        tuner._restore_state = state
        return tuner


    def fit(self) -> ResultGrid:
        tc = self.tune_config
        searcher = tc.search_alg or BasicVariantGenerator(
            self.param_space, num_samples=tc.num_samples, seed=tc.seed
        )
        scheduler = tc.scheduler or FIFOScheduler()
        exp_dir = self.run_config.resolved_storage_path()
        os.makedirs(exp_dir, exist_ok=True)

        max_concurrent = tc.max_concurrent_trials or 4
        # Per-trainable resources (tune.with_resources) win over the
        # TuneConfig default (matching the reference's precedence).
        resources = (
            getattr(self.trainable, "_tune_resources", None)
            or tc.trial_resources
            or {"CPU": 1.0}
        )

        trials: List[Trial] = []
        live: List[Trial] = []
        exhausted = False

        # Tuner.restore: completed trials keep their results; unfinished
        # ones re-queue with their recorded checkpoint. The searcher is
        # not consulted — the experiment's trial set is already decided.
        pending_restore: List[tuple] = []
        if self._restore_state is not None:
            exhausted = True
            for t in self._restore_state:
                # TERMINATED ran to completion; STOPPED was cut by the
                # scheduler on purpose — re-running it would re-spend the
                # compute early stopping deliberately saved. Both keep
                # their recorded results.
                if t["state"] in ("TERMINATED", "STOPPED"):
                    done = Trial(
                        trial_id=t["trial_id"], config=t["config"],
                        state=t["state"],
                        last_metrics=t.get("last_metrics") or {},
                        trial_dir=os.path.join(
                            exp_dir, f"trial_{t['trial_id']}"
                        ),
                    )
                    if t.get("checkpoint_path"):
                        done.checkpoint = Checkpoint.from_directory(
                            t["checkpoint_path"]
                        )
                    trials.append(done)
                else:
                    ckpt = (
                        Checkpoint.from_directory(t["checkpoint_path"])
                        if t.get("checkpoint_path") else None
                    )
                    pending_restore.append((t["trial_id"], t["config"], ckpt))

        # Controller event loop (reference: TuneController.step :709).
        while True:
            # Re-launch restored trials first, then consult the searcher.
            while pending_restore and len(live) < max_concurrent:
                trial_id, config, ckpt = pending_restore.pop(0)
                trial = Trial(trial_id=trial_id, config=config)
                trial_dir = os.path.join(exp_dir, f"trial_{trial_id}")
                os.makedirs(trial_dir, exist_ok=True)
                trial.trial_dir = trial_dir
                trial.checkpoint = ckpt
                self._launch_actor(trial, config, ckpt, resources)
                trial.state = "RUNNING"
                if hasattr(scheduler, "on_trial_add"):
                    scheduler.on_trial_add(trial_id, config)
                trials.append(trial)
                live.append(trial)
            # Launch new trials up to the concurrency cap.
            while not exhausted and len(live) < max_concurrent:
                trial_id = uuid.uuid4().hex[:8]
                config = searcher.suggest(trial_id)
                if config is PAUSED:
                    break  # nothing right now (e.g. ConcurrencyLimiter cap)
                if config is None:
                    exhausted = True
                    break
                trial = Trial(trial_id=trial_id, config=config)
                trial_dir = os.path.join(exp_dir, f"trial_{trial_id}")
                os.makedirs(trial_dir, exist_ok=True)
                trial.trial_dir = trial_dir
                self._launch_actor(trial, config, None, resources)
                trial.state = "RUNNING"
                if hasattr(scheduler, "on_trial_add"):
                    scheduler.on_trial_add(trial_id, config)
                trials.append(trial)
                live.append(trial)

            if not live and exhausted and not pending_restore:
                break

            # Poll live trials (per-trial isolation: one crashed actor
            # must not take down the controller loop).
            polls = []
            for t in live:
                try:
                    polls.append(rt.get(t.actor.poll.remote(), timeout=300))
                except Exception as e:  # noqa: BLE001 — actor/worker died
                    polls.append({"crashed": str(e)})
            still_live = []
            for trial, st in zip(live, polls):
                if "crashed" in st:
                    # Trial-level fault tolerance (FailureConfig.max_failures,
                    # reference air/config.py:377): restart the trial actor
                    # from its newest checkpoint. A FAILED restart keeps the
                    # trial live so the next poll retries it (counting
                    # against the same budget) — it must never abort fit().
                    trial.failures += 1
                    budget = self.run_config.failure_config.max_failures
                    if budget < 0 or trial.failures <= budget:
                        try:
                            self._restart_trial(trial, resources)
                        except Exception:  # noqa: BLE001 — retried next poll
                            pass
                        still_live.append(trial)
                    else:
                        trial.state = "ERROR"
                        trial.error = (
                            f"trial crashed {trial.failures}x "
                            f"(max_failures={budget}): {st['crashed']}"
                        )
                        try:
                            rt.kill(trial.actor)  # may be hung, not dead
                        except Exception:  # noqa: BLE001
                            pass
                        scheduler.on_complete(trial.trial_id, trial.last_metrics)
                        searcher.on_trial_complete(trial.trial_id, trial.last_metrics)
                    continue
                exploited = False
                for rep in st["reports"]:
                    trial.iteration += 1
                    metrics = dict(rep["metrics"])
                    metrics.setdefault("training_iteration", trial.iteration)
                    trial.last_metrics = metrics
                    trial.metrics_history.append(metrics)
                    if rep["checkpoint_path"]:
                        trial.checkpoint = Checkpoint.from_directory(
                            rep["checkpoint_path"]
                        )
                        if hasattr(scheduler, "record_checkpoint"):
                            scheduler.record_checkpoint(
                                trial.trial_id, rep["checkpoint_path"]
                            )
                    if hasattr(searcher, "on_trial_result"):
                        # Multi-fidelity searchers (BOHB) model
                        # intermediate rung results, not just finals.
                        searcher.on_trial_result(trial.trial_id, metrics)
                    decision = scheduler.on_result(trial.trial_id, metrics)
                    if decision == STOP and not st["done"]:
                        trial.state = "STOPPED"
                    elif decision == EXPLOIT and not st["done"]:
                        exploited = self._exploit(trial, scheduler, resources)
                        if exploited:
                            break  # fresh actor: stale reports are moot
                if exploited:
                    still_live.append(trial)
                    continue
                if st["error"]:
                    trial.state = "ERROR"
                    trial.error = st["error"]
                elif st["done"] and trial.state == "RUNNING":
                    trial.state = "TERMINATED"
                if trial.state in ("RUNNING",):
                    still_live.append(trial)
                else:
                    scheduler.on_complete(trial.trial_id, trial.last_metrics)
                    searcher.on_trial_complete(trial.trial_id, trial.last_metrics)
                    try:
                        rt.kill(trial.actor)
                    except Exception:
                        pass
            live = still_live
            self._snapshot(exp_dir, trials)
            if live or not exhausted:
                time.sleep(0.05)

        results = [
            Result(
                metrics=t.last_metrics,
                checkpoint=t.checkpoint,
                error=RuntimeError(t.error) if t.error else None,
                path=os.path.join(exp_dir, f"trial_{t.trial_id}"),
                metrics_history=t.metrics_history,
            )
            for t in trials
        ]
        return ResultGrid(results, trials, tc.metric, tc.mode)

    def _launch_actor(self, trial: Trial, config, checkpoint, resources):
        """The single trial-actor launch path (initial, exploit, restart)."""
        trial.actor = _TrialActor.options(
            num_cpus=resources.get("CPU", 1.0),
            resources={k: v for k, v in resources.items() if k != "CPU"},
        ).remote(trial.trial_id, trial.trial_dir)
        rt.get(
            trial.actor.run.remote(self.trainable, config, checkpoint),
            timeout=300,
        )

    def _restart_trial(self, trial: Trial, resources):
        """Replace a crashed trial actor, resuming from its checkpoint."""
        try:
            rt.kill(trial.actor)
        except Exception:  # noqa: BLE001
            pass
        self._launch_actor(trial, trial.config, trial.checkpoint, resources)

    def _exploit(self, trial: Trial, scheduler, resources) -> bool:
        """PBT exploit/explore: restart the trial from a donor's checkpoint
        with a mutated config (reference: pbt.py _exploit)."""
        ckpt_path, new_config = scheduler.make_exploit(trial.trial_id)
        if ckpt_path is None:
            return False
        try:
            rt.kill(trial.actor)
        except Exception:
            pass
        trial.config = new_config
        self._launch_actor(
            trial, new_config, Checkpoint.from_directory(ckpt_path),
            resources,
        )
        return True

    def _snapshot(self, exp_dir: str, trials: List[Trial]):
        """Experiment state snapshot (reference:
        tune/execution/experiment_state.py)."""
        state = [
            {
                "trial_id": t.trial_id,
                "config": _json_safe(t.config),
                "state": t.state,
                "last_metrics": _json_safe(t.last_metrics),
                "error": t.error,
                # Restoration point for Tuner.restore (user checkpoints
                # live wherever tune.report was given them).
                "checkpoint_path": t.checkpoint.path if t.checkpoint else None,
            }
            for t in trials
        ]
        # Write-then-rename: a driver killed mid-snapshot (the exact
        # scenario Tuner.restore exists for) must never truncate the
        # state file into unrestorability.
        path = os.path.join(exp_dir, "experiment_state.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f, indent=2)
        os.replace(tmp, path)


def _json_safe(d):
    try:
        json.dumps(d)
        return d
    except TypeError:
        return {k: str(v) for k, v in d.items()}
