"""Trial schedulers.

Analog of the reference's tune/schedulers: FIFO and ASHA
(async_hyperband.py) plus median stopping (median_stopping_rule.py).
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE

    def on_complete(self, trial_id: str, result: Optional[Dict] = None):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving (reference:
    tune/schedulers/async_hyperband.py).

    Rungs at time_attr values grace_period * reduction_factor^k; a trial
    reaching a rung stops unless its metric is in the top 1/reduction_factor
    of results recorded at that rung so far.
    """

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        reduction_factor: int = 4,
        max_t: int = 100,
    ):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.reduction_factor = reduction_factor
        self.max_t = max_t
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        # rung value -> recorded metrics
        self.recorded: Dict[int, List[float]] = defaultdict(list)
        self._passed: Dict[str, set] = defaultdict(set)

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        for rung in self.rungs:
            if t >= rung and rung not in self._passed[trial_id]:
                self._passed[trial_id].add(rung)
                recorded = self.recorded[rung]
                recorded.append(value)
                if len(recorded) >= self.reduction_factor:
                    ordered = sorted(recorded, reverse=(self.mode == "max"))
                    cutoff_idx = max(
                        0, math.ceil(len(ordered) / self.reduction_factor) - 1
                    )
                    cutoff = ordered[cutoff_idx]
                    good = value >= cutoff if self.mode == "max" else value <= cutoff
                    if not good:
                        return STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running average falls below the median of other
    trials at the same step (reference: tune/schedulers/median_stopping_rule.py).
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self.histories: Dict[str, List[float]] = defaultdict(list)

    def on_result(self, trial_id: str, result: Dict) -> str:
        value = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if value is None:
            return CONTINUE
        self.histories[trial_id].append(value)
        if t < self.grace_period or len(self.histories) < self.min_samples:
            return CONTINUE
        import statistics

        avgs = [
            sum(h) / len(h) for tid, h in self.histories.items() if tid != trial_id and h
        ]
        if len(avgs) < self.min_samples - 1:
            return CONTINUE
        median = statistics.median(avgs)
        mine = sum(self.histories[trial_id]) / len(self.histories[trial_id])
        worse = mine > median if self.mode == "min" else mine < median
        return STOP if worse else CONTINUE
