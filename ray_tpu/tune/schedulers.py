"""Trial schedulers.

Analog of the reference's tune/schedulers: FIFO and ASHA
(async_hyperband.py), median stopping (median_stopping_rule.py), and
population based training (pbt.py).
"""

from __future__ import annotations

import math
import random
from collections import defaultdict
from typing import Callable, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
EXPLOIT = "EXPLOIT"  # PBT: clone a better trial's state + mutate config


class TrialScheduler:
    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE

    def on_complete(self, trial_id: str, result: Optional[Dict] = None):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving (reference:
    tune/schedulers/async_hyperband.py).

    Rungs at time_attr values grace_period * reduction_factor^k; a trial
    reaching a rung stops unless its metric is in the top 1/reduction_factor
    of results recorded at that rung so far.

    Multiple brackets (the full async-HyperBand shape, reference
    async_hyperband.py `brackets` arg) assign trials round-robin to
    brackets whose grace periods grow by reduction_factor — bracket k
    starts halving at grace_period * rf^k, trading early-stopping
    aggressiveness against robustness to slow starters.
    """

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        reduction_factor: int = 4,
        max_t: int = 100,
        brackets: int = 1,
    ):
        assert mode in ("min", "max")
        assert brackets >= 1
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.reduction_factor = reduction_factor
        self.max_t = max_t
        # Per-bracket rung ladders: bracket k's first rung is
        # grace_period * rf^k.
        self.bracket_rungs: List[List[int]] = []
        for k in range(brackets):
            rungs = []
            t = grace_period * reduction_factor ** k
            while t < max_t:
                rungs.append(t)
                t *= reduction_factor
            self.bracket_rungs.append(rungs)
        # (bracket, rung value) -> recorded metrics
        self.recorded: Dict[tuple, List[float]] = defaultdict(list)
        self._passed: Dict[str, set] = defaultdict(set)
        self._bracket_of: Dict[str, int] = {}
        self._next_bracket = 0

    def _bracket(self, trial_id: str) -> int:
        if trial_id not in self._bracket_of:
            self._bracket_of[trial_id] = self._next_bracket
            self._next_bracket = (self._next_bracket + 1) % len(
                self.bracket_rungs
            )
        return self._bracket_of[trial_id]

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        bracket = self._bracket(trial_id)
        for rung in self.bracket_rungs[bracket]:
            if t >= rung and rung not in self._passed[trial_id]:
                self._passed[trial_id].add(rung)
                recorded = self.recorded[(bracket, rung)]
                recorded.append(value)
                if len(recorded) >= self.reduction_factor:
                    ordered = sorted(recorded, reverse=(self.mode == "max"))
                    cutoff_idx = max(
                        0, math.ceil(len(ordered) / self.reduction_factor) - 1
                    )
                    cutoff = ordered[cutoff_idx]
                    good = value >= cutoff if self.mode == "max" else value <= cutoff
                    if not good:
                        return STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running average falls below the median of other
    trials at the same step (reference: tune/schedulers/median_stopping_rule.py).
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self.histories: Dict[str, List[float]] = defaultdict(list)

    def on_result(self, trial_id: str, result: Dict) -> str:
        value = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if value is None:
            return CONTINUE
        self.histories[trial_id].append(value)
        if t < self.grace_period or len(self.histories) < self.min_samples:
            return CONTINUE
        import statistics

        avgs = [
            sum(h) / len(h) for tid, h in self.histories.items() if tid != trial_id and h
        ]
        if len(avgs) < self.min_samples - 1:
            return CONTINUE
        median = statistics.median(avgs)
        mine = sum(self.histories[trial_id]) / len(self.histories[trial_id])
        worse = mine > median if self.mode == "min" else mine < median
        return STOP if worse else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """Population based training (reference: tune/schedulers/pbt.py).

    Every `perturbation_interval` reported iterations a trial in the bottom
    quantile EXPLOITS a top-quantile trial — the tuner restarts it from the
    donor's checkpoint — and EXPLORES by mutating hyperparameters: with
    `resample_probability` a fresh sample from `hyperparam_mutations`,
    otherwise the value scaled by 1.2/0.8 (or a neighboring choice for
    categorical lists).
    """

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        perturbation_interval: int = 4,
        hyperparam_mutations: Optional[Dict] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: Optional[int] = None,
    ):
        if not hyperparam_mutations:
            raise ValueError("PBT requires hyperparam_mutations")
        if not 0 < quantile_fraction <= 0.5:
            raise ValueError("quantile_fraction must be in (0, 0.5]")
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self._rng = random.Random(seed)
        self._scores: Dict[str, float] = {}
        self._iters: Dict[str, int] = defaultdict(int)
        self._last_perturb: Dict[str, int] = defaultdict(int)
        self._configs: Dict[str, Dict] = {}
        self._checkpoints: Dict[str, str] = {}
        self.num_exploits = 0  # observability for tests/dashboards

    # -- tuner integration hooks ----------------------------------------
    def on_trial_add(self, trial_id: str, config: Dict):
        self._configs[trial_id] = dict(config)

    def record_checkpoint(self, trial_id: str, path: str):
        self._checkpoints[trial_id] = path

    def on_complete(self, trial_id: str, result: Optional[Dict] = None):
        self._scores.pop(trial_id, None)
        self._checkpoints.pop(trial_id, None)

    # -- decisions -------------------------------------------------------
    def _quantiles(self):
        ranked = sorted(
            self._scores.items(), key=lambda kv: kv[1],
            reverse=(self.mode == "max"),
        )
        k = max(1, int(len(ranked) * self.quantile))
        if len(ranked) < 2 * k:
            return [], []
        top = [tid for tid, _ in ranked[:k]]
        bottom = [tid for tid, _ in ranked[-k:]]
        return top, bottom

    def on_result(self, trial_id: str, result: Dict) -> str:
        if self.metric not in result:
            return CONTINUE
        self._scores[trial_id] = float(result[self.metric])
        self._iters[trial_id] += 1
        if self._iters[trial_id] - self._last_perturb[trial_id] < self.interval:
            return CONTINUE
        top, bottom = self._quantiles()
        if trial_id not in bottom:
            return CONTINUE
        donors = [t for t in top if t in self._checkpoints]
        if not donors:
            return CONTINUE
        # _last_perturb is recorded in make_exploit: a failed exploit (the
        # donor finished in between) must not cost a whole interval.
        return EXPLOIT

    def make_exploit(self, trial_id: str):
        """Pick a donor; return (donor_checkpoint_path, mutated_config)."""
        top, _ = self._quantiles()
        donors = [t for t in top if t in self._checkpoints]
        if not donors:
            return None, None
        donor = self._rng.choice(donors)
        new_config = self._explore(dict(self._configs.get(donor, {})))
        self._configs[trial_id] = new_config
        self._last_perturb[trial_id] = self._iters[trial_id]
        self.num_exploits += 1
        return self._checkpoints[donor], new_config

    def _explore(self, config: Dict) -> Dict:
        for key, spec in self.mutations.items():
            if self._rng.random() < self.resample_p or key not in config:
                config[key] = self._sample(spec)
            elif isinstance(spec, (list, tuple)):
                # Move to a neighboring choice.
                try:
                    idx = list(spec).index(config[key])
                except ValueError:
                    idx = self._rng.randrange(len(spec))
                step = self._rng.choice((-1, 1))
                config[key] = list(spec)[max(0, min(len(spec) - 1, idx + step))]
            elif isinstance(config[key], (int, float)):
                factor = self._rng.choice((0.8, 1.2))
                val = config[key] * factor
                config[key] = type(config[key])(val) if isinstance(
                    config[key], int) else val
            else:
                config[key] = self._sample(spec)
        return config

    def _sample(self, spec):
        if callable(spec):
            return spec()
        if isinstance(spec, (list, tuple)):
            return self._rng.choice(list(spec))
        raise TypeError(
            f"hyperparam_mutations values must be callables or lists, "
            f"got {type(spec).__name__}"
        )


class PB2(PopulationBasedTraining):
    """Population Based Bandits (reference: tune/schedulers/pb2.py —
    Parker-Holder et al. 2020). PBT's exploit step stays; EXPLORE is
    model-guided instead of random: a Gaussian-process surrogate is fit
    over (hyperparams -> observed metric change) and the new config
    maximizes a UCB acquisition over candidate perturbations — directed
    search through the mutation space rather than 0.8x/1.2x coin flips.

    Numeric hyperparams declared as (low, high) tuples in
    `hyperparam_bounds` ride the GP; anything in `hyperparam_mutations`
    keeps PBT's random perturbation.
    """

    def __init__(self, *args, hyperparam_bounds: Optional[Dict] = None,
                 ucb_beta: float = 1.5, n_candidates: int = 64, **kwargs):
        bounds = dict(hyperparam_bounds or {})
        if not kwargs.get("hyperparam_mutations") and bounds:
            # PB2 with bounds only: the base class requires mutations, so
            # synthesize uniform resample specs over each bound (only used
            # while the GP is cold).
            import random as _random

            kwargs["hyperparam_mutations"] = {
                k: (lambda lo=lo, hi=hi: _random.uniform(lo, hi))
                for k, (lo, hi) in bounds.items()
            }
        super().__init__(*args, **kwargs)
        self.bounds = bounds
        self.ucb_beta = ucb_beta
        self.n_candidates = n_candidates
        # Fitness history: (hyperparam vector, metric delta) per window.
        self._gp_data: list = []
        self._prev_score: Dict[str, float] = {}

    def on_result(self, trial_id: str, result: Dict) -> str:
        if self.metric in result and self.bounds:
            score = float(result[self.metric])
            if self.mode == "min":
                score = -score  # GP always maximizes improvement
            prev = self._prev_score.get(trial_id)
            cfg = self._configs.get(trial_id, {})
            if prev is not None and all(k in cfg for k in self.bounds):
                x = [self._norm(k, cfg[k]) for k in sorted(self.bounds)]
                self._gp_data.append((x, score - prev))
                del self._gp_data[:-128]  # sliding window
            self._prev_score[trial_id] = score
        return super().on_result(trial_id, result)

    def _norm(self, key, v):
        lo, hi = self.bounds[key]
        return (float(v) - lo) / max(hi - lo, 1e-12)

    def _denorm(self, key, x):
        lo, hi = self.bounds[key]
        return lo + x * (hi - lo)

    def _explore(self, config: Dict) -> Dict:
        config = super()._explore(config)
        if not self.bounds:
            return config
        if len(self._gp_data) < 4:
            # Cold model: uniform resample inside bounds.
            for k in self.bounds:
                config[k] = self._denorm(k, self._rng.random())
            return config
        import numpy as np

        keys = sorted(self.bounds)
        X = np.asarray([x for x, _ in self._gp_data])
        y = np.asarray([d for _, d in self._gp_data])
        y = (y - y.mean()) / (y.std() + 1e-9)
        # RBF-kernel GP posterior (noise-regularized).
        ls = 0.2
        def k(a, b):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-d2 / (2 * ls * ls))
        K = k(X, X) + 0.1 * np.eye(len(X))
        Kinv_y = np.linalg.solve(K, y)
        cand = np.asarray([
            [self._rng.random() for _ in keys]
            for _ in range(self.n_candidates)
        ])
        Ks = k(cand, X)
        mu = Ks @ Kinv_y
        var = 1.0 - np.einsum(
            "ij,ji->i", Ks, np.linalg.solve(K, Ks.T)
        ).clip(max=1.0)
        ucb = mu + self.ucb_beta * np.sqrt(var.clip(min=0.0))
        best = cand[int(np.argmax(ucb))]
        for i, key in enumerate(keys):
            config[key] = self._denorm(key, float(best[i]))
        return config
