from ray_tpu.util import debug
from ray_tpu.util.check_serialize import inspect_serializability
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.placement_group import (
    PlacementGroup,
    PlacementGroupConfig,
    placement_group,
    remove_placement_group,
)

__all__ = [
    "inspect_serializability",
    "ActorPool",
    "debug",
    "PlacementGroup",
    "PlacementGroupConfig",
    "placement_group",
    "remove_placement_group",
]
