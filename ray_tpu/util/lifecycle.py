"""Sampled control-plane lifecycle profiler: name where the µs/task go.

Analog of the reference's task-event lifecycle stream
(src/ray/protobuf/export_task_event.proto state transitions feeding
`ray timeline`), narrowed to the question ROADMAP item 2 asks: which
control-plane phase bends the cost curve at a million tasks?

Head sampling: the submitting client decides once per task
(`RT_TASK_TRACE_SAMPLE` rate, flippable cluster-wide at runtime via
`rt profile --on`) and stamps a ``sampled`` bit into the task spec /
actor-call request. Every hop that sees the bit stamps monotonic phase
marks and emits ONE ``LIFECYCLE_SPAN`` task event carrying its phases;
the stitcher joins them per task id into a breakdown whose leaf phases
sum to ≈ the submit→complete wall.

Phase marks ride as ``extra["phases"] = {name: [epoch_start_s, dur_s]}``
— durations from ``time.monotonic()`` deltas (immune to clock steps),
start stamps from ``time.time()`` so `rt timeline --lifecycle` can place
the rows on the shared chrome-trace axis.

The unsampled fast path must stay ~free: the only per-task cost with
sampling off is the module-attribute ``enabled`` check on the submit
side and ``spec.get("sampled")`` dict misses on the hops (benched in
bench_scale.py, gated < 2 µs/task).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional

#: Canonical phase order for display (client submit → worker → result).
PHASE_ORDER = (
    "serialize",      # client: args → wire payload
    "submit_buffer",  # client: submit-burst buffer wait (batching delay)
    "lease",          # client: direct-path worker-lease RPC (per group)
    "queue_wait",     # raylet: enqueue → dispatch pop
    "dispatch",       # raylet: resource grant + push to worker
    "fn_fetch",       # worker: function-manager fetch
    "arg_fetch",      # worker: store pulls for by-reference args
    "deserialize",    # worker: arg payload decode (minus arg_fetch)
    "exec",           # worker: user function body
    "result_store",   # worker: package / store returns
    "transport",      # client: submit-RPC wire + event-loop residual
    "get_wait",       # driver: rt.get block (overlaps remote phases)
)

#: Leaf phases whose sum is compared against the submit→complete wall.
#: get_wait overlaps remote execution (a caller blocked in get is waiting
#: on queue/exec time already counted), so it stays out of the sum.
SUM_PHASES = frozenset(PHASE_ORDER) - {"get_wait"}

#: Canonical phase order for SERVE requests (serve.observatory emits
#: these on sampled requests; display order for rt trace / aggregate).
#: `exec` replaces the four engine phases on non-engine deployments.
SERVE_PHASE_ORDER = (
    "handle_queue",           # caller: .remote() → router dispatch
    "dispatch",               # wire + replica pre-engine work
    "engine_admission_wait",  # engine queue → decode-slot grant
    "prefill",                # slot grant → first token
    "decode",                 # first token → terminal token
    "stream",                 # terminal token → reply handed back
    "exec",                   # non-engine deployments: user callable body
)

#: Fast-path guard: hops check this module attribute before doing ANY
#: sampling work. Only set_sample_rate flips it.
enabled = False
_rate = 0.0
_lock = threading.Lock()
_tls = threading.local()


def set_sample_rate(rate: float) -> None:
    """Set the head-sampling probability (0 disables, 1 traces all)."""
    global enabled, _rate
    rate = min(1.0, max(0.0, float(rate)))
    with _lock:
        _rate = rate
        enabled = rate > 0.0


def get_sample_rate() -> float:
    return _rate


def sample() -> bool:
    """One head-sampling decision. Callers must gate on ``enabled``."""
    r = _rate
    return r >= 1.0 or random.random() < r


def event(
    task_id: bytes,
    name: str,
    job_id: bytes,
    node_id: bytes,
    hop: str,
    phases: Dict[str, List[float]],
    e2e_s: Optional[float] = None,
    worker_id: Optional[bytes] = None,
) -> dict:
    """Build one LIFECYCLE_SPAN task event for this hop's phase marks.

    phases: {phase: [epoch_start_s, dur_s]}. The caller appends the
    event to whatever task-event buffer its process already flushes
    (client: profiling._buffer, raylet/worker: self._task_events).
    """
    extra: Dict = {"hop": hop, "phases": phases}
    if e2e_s is not None:
        extra["e2e_s"] = e2e_s
    ev = {
        "task_id": task_id,
        "name": name,
        "job_id": job_id,
        "node_id": node_id,
        "type": "LIFECYCLE_SPAN",
        "state": "PHASES",
        "ts": time.time(),
        "extra": extra,
    }
    if worker_id is not None:
        ev["worker_id"] = worker_id
    from ray_tpu.util import journal

    journal.emit("lifecycle.span", task=name, hop=hop,
                 **({"e2e_s": round(e2e_s, 6)} if e2e_s is not None else {}))
    return ev


# -- executing-worker arg-fetch capture ---------------------------------
# deserialize_args resolves by-reference args with store gets; splitting
# that wait out of "deserialize" needs a thread-local accumulator the
# resolver adds into. Off path: one getattr miss per STORE arg (which
# already paid an RPC), nothing on inline args.

def begin_arg_capture() -> None:
    _tls.arg_fetch = 0.0


def add_arg_fetch(dur_s: float) -> None:
    if getattr(_tls, "arg_fetch", None) is not None:
        _tls.arg_fetch += dur_s


def end_arg_capture() -> float:
    dur = getattr(_tls, "arg_fetch", 0.0) or 0.0
    _tls.arg_fetch = None
    return dur


# -- stitching / aggregation --------------------------------------------

def stitch(events: List[dict]) -> Dict[str, dict]:
    """Join LIFECYCLE_SPAN events per task id.

    Returns {task_id_hex: {"name", "ts", "hops": [..], "phases":
    {phase: dur_s}, "e2e_s": float|None}}. Durations for a phase seen on
    several hops (never expected, but a retry can re-stamp) accumulate.
    """
    tasks: Dict[str, dict] = {}
    for ev in events:
        if ev.get("type") != "LIFECYCLE_SPAN":
            continue
        extra = ev.get("extra") or {}
        tid = ev.get("task_id")
        key = tid.hex() if isinstance(tid, (bytes, bytearray)) else str(tid)
        rec = tasks.setdefault(
            key,
            {"name": ev.get("name", ""), "ts": ev.get("ts", 0.0),
             "hops": [], "phases": {}, "phase_marks": {}, "e2e_s": None},
        )
        if ev.get("name"):
            rec["name"] = ev["name"]
        hop = extra.get("hop", "")
        if hop and hop not in rec["hops"]:
            rec["hops"].append(hop)
        for phase, mark in (extra.get("phases") or {}).items():
            try:
                start, dur = float(mark[0]), float(mark[1])
            except (TypeError, ValueError, IndexError):
                continue
            rec["phases"][phase] = rec["phases"].get(phase, 0.0) + dur
            rec["phase_marks"].setdefault(phase, [start, dur])
            if hop != "client" and phase in SUM_PHASES:
                rec["_remote_s"] = rec.get("_remote_s", 0.0) + dur
        if extra.get("e2e_s") is not None:
            rec["e2e_s"] = float(extra["e2e_s"])
    # Derive "transport": the client stamps rpc_wait (the submit RPC's
    # full round-trip on single-spec frames); everything the raylet /
    # worker attributed happened inside that window, so the residual is
    # wire + event-loop time — the phase that dominates tiny tasks.
    # rpc_wait itself would double-count the remote phases, so it is
    # replaced, not kept.
    for rec in tasks.values():
        remote = rec.pop("_remote_s", 0.0)
        rpc = rec["phases"].pop("rpc_wait", None)
        mark = rec["phase_marks"].pop("rpc_wait", None)
        if rpc is None:
            continue
        rec["rpc_wait_s"] = rpc
        resid = rpc - remote
        if resid > 0.0:
            rec["phases"]["transport"] = (
                rec["phases"].get("transport", 0.0) + resid
            )
            if mark is not None:
                rec["phase_marks"].setdefault("transport", [mark[0], resid])
    return tasks


def coverage(record: dict) -> Optional[float]:
    """Fraction of the task's e2e wall its leaf phases explain
    (None when the client hop — which owns e2e — wasn't seen)."""
    e2e = record.get("e2e_s")
    if not e2e:
        return None
    leaf = sum(
        d for p, d in record["phases"].items() if p in SUM_PHASES
    )
    return leaf / e2e


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


def aggregate(records: Dict[str, dict]) -> Dict[str, dict]:
    """Per-phase aggregate over stitched records:
    {phase: {"count", "mean_us", "p50_us", "p99_us"}} plus pseudo-rows
    ``e2e`` (client submit→complete wall) and ``coverage`` (leaf-phase
    sum / e2e, unitless fractions in the *_us fields)."""
    by_phase: Dict[str, List[float]] = {}
    e2es: List[float] = []
    covs: List[float] = []
    for rec in records.values():
        for phase, dur in rec["phases"].items():
            by_phase.setdefault(phase, []).append(dur * 1e6)
        if rec.get("e2e_s"):
            e2es.append(rec["e2e_s"] * 1e6)
            c = coverage(rec)
            if c is not None:
                covs.append(c)
    out: Dict[str, dict] = {}

    def _row(vals: List[float]) -> dict:
        vals = sorted(vals)
        return {
            "count": len(vals),
            "mean_us": sum(vals) / len(vals) if vals else 0.0,
            "p50_us": _percentile(vals, 0.5),
            "p99_us": _percentile(vals, 0.99),
        }

    for phase in PHASE_ORDER + SERVE_PHASE_ORDER:
        if phase in by_phase:
            out[phase] = _row(by_phase.pop(phase))
    for phase, vals in sorted(by_phase.items()):  # unknown extras last
        out[phase] = _row(vals)
    if e2es:
        out["e2e"] = _row(e2es)
    if covs:
        out["coverage"] = _row(covs)
    return out


def _init_from_config() -> None:
    try:
        from ray_tpu._private.config import get_config

        set_sample_rate(get_config().task_trace_sample)
    except Exception:  # noqa: BLE001 — profiling must never break import
        pass


_init_from_config()
