"""User-level profiling spans in the cluster timeline.

Analog of the reference's ray.profiling.profile() (_private/profiling.py:84):
a context manager that records a named span from ANY driver or worker into
the GCS task-event stream, so `rt timeline` shows user phases ("preprocess",
"forward", "checkpoint") interleaved with task execution spans in
chrome://tracing.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

_lock = threading.Lock()
_buffer: List[dict] = []
_last_flush = 0.0
_flush_timer: threading.Timer | None = None

# Spans recorded just before exit must still reach the timeline.
import atexit

atexit.register(lambda: _flush(force=True))


def request_flush(delay_s: float | None = None) -> None:
    """Schedule a forced flush within a bounded delay.

    The batching path for high-rate span producers (tracing._record used
    to force one GCS RPC per span): the first request arms a one-shot
    timer, subsequent requests while it is armed are free, and every
    span buffered in the window rides one add_task_events RPC. Eager
    flushing remains only at atexit/driver exit.
    """
    global _flush_timer
    if delay_s is None:
        from ray_tpu._private.config import get_config

        delay_s = get_config().trace_flush_delay_s
    with _lock:
        if _flush_timer is not None:
            return
        t = threading.Timer(delay_s, _timer_fire)
        t.daemon = True
        _flush_timer = t
    t.start()


def buffer_events(events: List[dict], flush_delay_s: float | None = None) -> None:
    """Append pre-built task events (e.g. serve LIFECYCLE_SPANs) to the
    batched flush buffer. Rides the same armed-timer add_task_events
    batching as spans — no per-event GCS RPC."""
    if not events:
        return
    with _lock:
        _buffer.extend(events)
    request_flush(flush_delay_s)


def _timer_fire() -> None:
    global _flush_timer
    with _lock:
        _flush_timer = None
    _flush(force=True)


def _flush(force: bool = False):
    global _last_flush
    from ray_tpu._private import worker as worker_mod

    with _lock:
        now = time.monotonic()
        if not _buffer or (
            not force and len(_buffer) < 16 and now - _last_flush < 1.0
        ):
            return
        events, _buffer[:] = list(_buffer), []
        _last_flush = now
    try:
        client = worker_mod.get_client()
        client._run(
            client._gcs_call("add_task_events", {"events": events}),
            timeout=10,
        )
    except Exception:  # noqa: BLE001 — profiling must never break user code
        pass


@contextmanager
def profile(name: str, extra: Optional[Dict] = None):
    """Record a named span:

        with rt.util.profiling.profile("tokenize"):
            ...

    Spans appear in `rt timeline` under the emitting worker's row.
    """
    from ray_tpu._private import worker as worker_mod

    try:
        client = worker_mod.get_client()
        node_id = client.node_id
        worker_id = client.client_id
    except Exception:  # noqa: BLE001 — not connected: no-op span
        yield
        return
    span_id = os.urandom(16)
    start = time.time()
    try:
        yield
    finally:
        end = time.time()
        base = {
            "task_id": span_id,
            "name": name,
            "job_id": b"",
            "node_id": node_id,
            "worker_id": worker_id,
            "type": "USER_SPAN",
        }
        if extra:
            base["extra"] = dict(extra)
        with _lock:
            _buffer.append({**base, "state": "RUNNING", "ts": start})
            _buffer.append({**base, "state": "FINISHED", "ts": end})
        _flush()


def flush():
    """Force-flush buffered spans (call before process exit in tests)."""
    _flush(force=True)
