"""Drop-in multiprocessing.Pool over the cluster.

Analog of the reference's ``ray.util.multiprocessing`` (util/
multiprocessing/pool.py): a Pool of actor processes; ``map``/``starmap``/
``apply``/``imap`` fan work out as actor calls so a single-machine Pool
program scales onto the cluster unchanged.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu as rt


@rt.remote
class _PoolWorker:
    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run(self, fn, args, kwargs):
        return fn(*args, **(kwargs or {}))

    def run_batch(self, fn, chunk):
        return [fn(*args) for args in chunk]


class AsyncResult:
    def __init__(self, refs, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        results = rt.get(self._refs, timeout=timeout)
        return results[0] if self._single else results

    def wait(self, timeout: Optional[float] = None):
        rt.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        done, _ = rt.wait(self._refs, num_returns=len(self._refs), timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0)
            return True
        except Exception:
            return False


class Pool:
    def __init__(
        self,
        processes: Optional[int] = None,
        initializer: Optional[Callable] = None,
        initargs: tuple = (),
    ):
        if not rt.is_initialized():
            rt.init()
        self._size = processes or 4
        self._workers = [
            _PoolWorker.remote(initializer, initargs) for _ in range(self._size)
        ]
        self._rr = itertools.cycle(range(self._size))
        self._closed = False

    # -- scheduling helpers ----------------------------------------------
    def _next(self):
        if self._closed:
            raise ValueError("Pool not running")
        return self._workers[next(self._rr)]

    @staticmethod
    def _chunks(items: List, n: int):
        for i in range(0, len(items), n):
            yield items[i : i + n]

    # -- API ---------------------------------------------------------------
    def apply(self, fn, args=(), kwds=None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn, args=(), kwds=None) -> AsyncResult:
        ref = self._next().run.remote(fn, tuple(args), kwds)
        return AsyncResult([ref], single=True)

    def map(self, fn, iterable: Iterable, chunksize: Optional[int] = None):
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        items = [(x,) for x in iterable]
        return self._starmap_async(fn, items, chunksize)

    def starmap(self, fn, iterable, chunksize=None):
        return self.starmap_async(fn, iterable, chunksize).get()

    def starmap_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        return self._starmap_async(fn, [tuple(x) for x in iterable], chunksize)

    def _starmap_async(self, fn, items, chunksize) -> AsyncResult:
        if chunksize is None:
            chunksize = max(1, len(items) // (self._size * 4) or 1)
        refs = [
            self._next().run_batch.remote(fn, chunk)
            for chunk in self._chunks(items, chunksize)
        ]
        return _FlattenResult(refs)

    def imap(self, fn, iterable, chunksize: int = 1):
        refs = [self._next().run.remote(fn, (x,), None) for x in iterable]
        for ref in refs:
            yield rt.get(ref)

    def imap_unordered(self, fn, iterable, chunksize: int = 1):
        refs = [self._next().run.remote(fn, (x,), None) for x in iterable]
        pending = list(refs)
        while pending:
            done, pending = rt.wait(pending, num_returns=1)
            yield rt.get(done[0])

    def close(self):
        self._closed = True

    def terminate(self):
        self.close()
        for w in self._workers:
            rt.kill(w)
        self._workers = []

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
        return False


class _FlattenResult(AsyncResult):
    def __init__(self, refs):
        super().__init__(refs, single=False)

    def get(self, timeout: Optional[float] = None):
        out: List[Any] = []
        for batch in rt.get(self._refs, timeout=timeout):
            out.extend(batch)
        return out
