"""Parallel iterators over actor shards.

Analog of the reference's ``ray.util.iter`` (util/iter.py): partition a
sequence across actor shards, apply lazy transforms shard-side, and gather
(sync or batched) on the driver.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu as rt


@rt.remote
class _ShardActor:
    def __init__(self, items: List[Any]):
        self.items = list(items)
        self.ops: List[tuple] = []

    def add_op(self, kind: str, fn):
        self.ops.append((kind, fn))

    def materialize(self) -> List[Any]:
        out: Iterable[Any] = self.items
        for kind, fn in self.ops:
            if kind == "map":
                out = [fn(x) for x in out]
            elif kind == "filter":
                out = [x for x in out if fn(x)]
            elif kind == "flat_map":
                out = [y for x in out for y in fn(x)]
            elif kind == "batch":
                out = list(out)
                out = [out[i : i + fn] for i in range(0, len(out), fn)]
        return list(out)


class ParallelIterator:
    def __init__(self, shards: List):
        self._shards = shards

    # -- transforms (lazy, shard-side) ------------------------------------
    def for_each(self, fn: Callable) -> "ParallelIterator":
        rt.get([s.add_op.remote("map", fn) for s in self._shards])
        return self

    def filter(self, fn: Callable) -> "ParallelIterator":
        rt.get([s.add_op.remote("filter", fn) for s in self._shards])
        return self

    def flat_map(self, fn: Callable) -> "ParallelIterator":
        rt.get([s.add_op.remote("flat_map", fn) for s in self._shards])
        return self

    def batch(self, n: int) -> "ParallelIterator":
        rt.get([s.add_op.remote("batch", n) for s in self._shards])
        return self

    # -- consumption -------------------------------------------------------
    def gather_sync(self) -> List[Any]:
        out: List[Any] = []
        for chunk in rt.get([s.materialize.remote() for s in self._shards]):
            out.extend(chunk)
        return out

    def num_shards(self) -> int:
        return len(self._shards)


def from_items(items: List[Any], num_shards: int = 2) -> ParallelIterator:
    shards = []
    per = max(1, (len(items) + num_shards - 1) // num_shards)
    for i in range(0, max(len(items), 1), per):
        shards.append(_ShardActor.remote(items[i : i + per]))
    return ParallelIterator(shards)


def from_range(n: int, num_shards: int = 2) -> ParallelIterator:
    return from_items(list(range(n)), num_shards)
