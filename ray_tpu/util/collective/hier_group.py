"""Hierarchical collectives: XLA over local devices, DCN ring across
processes.

The multi-slice TPU topology has two bandwidth tiers: ICI within a slice
(fast, reached through XLA programs over local devices) and DCN between
slices/hosts (orders of magnitude slower). A flat cross-host ring would
push every device's data over DCN; the hierarchical schedule reduces
locally first, and — since PR 9 — SHARDS the cross-tier exchange:

    allreduce = ICI-local reduce-scatter      # n_local shards of local sum
              -> DCN exchange, ONE shard per lane  (1/n_local the bytes
                 a flat all-devices DCN ring would push per process)
              -> ICI allgather of the reduced shards (free: replication)

The legacy schedule (local psum -> full-array DCN ring -> broadcast)
remains available as the "ring" algorithm; "rd" runs the full local sum
through the latency-optimal recursive-doubling exchange instead (small
messages). The choice comes from the alpha-beta cost model in
topology.py per (collective, topology, nbytes), overridable with
RT_COLLECTIVE_ALGO, and is recorded in `last_op_info`.

Quantization (quant="int8"/"fp8") applies to the DCN tier only — the
ICI tier stays full-precision, exactly the EQuARX placement: compress
where the wire is slow, never where it is free.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ray_tpu.util.collective.dcn_group import DcnGroup
from ray_tpu.util.collective.topology import (
    ALGO_HIER,
    ALGO_RD,
    ALGO_RING,
    Topology,
)
from ray_tpu.util.collective.types import ReduceOp
from ray_tpu.util.collective.xla_group import XlaLocalGroup


class HierarchicalGroup:
    """Two-tier collective group.

    `world_size`/`rank` count PROCESSES (slices/hosts); each process
    contributes one tensor per local device, like XlaLocalGroup.
    """

    def __init__(self, client, world_size: int, rank: int, group_name: str,
                 num_local_devices=None, epoch: int = 0,
                 op_timeout_s=None):
        self.local = XlaLocalGroup(num_local_devices)
        self.dcn = DcnGroup(client, world_size, rank, group_name + "::dcn",
                            epoch=epoch, op_timeout=op_timeout_s)
        self.world_size = world_size
        self.rank = rank
        # Two-tier topology: DCN width = processes, local width = the
        # devices this process actually drives.
        self.topo = Topology.detect(world_size, n_local=self.local.world_size)
        self.last_op_info: dict = {}

    @property
    def total_ranks(self) -> int:
        return self.world_size * self.local.world_size

    def _record_op(self, op_name: str, algo: str, dcn_bytes0: int,
                   dtype, quant: Optional[str] = None) -> None:
        self.last_op_info = {
            "op": op_name,
            "algo": algo,
            "tier": "ici+dcn",
            "bytes": self.dcn.bytes_sent - dcn_bytes0,  # slow-tier bytes
            "dtype": str(dtype),
            "quant": quant,
        }

    def allreduce(self, tensors: List, op: ReduceOp = ReduceOp.SUM,
                  quant: Optional[str] = None,
                  error_feedback: bool = False,
                  algo: Optional[str] = None) -> List:
        """tensors: one per local device. Returns the GLOBAL reduction
        (across every device of every process), one copy per local
        device. quant/error_feedback apply to the DCN tier only."""
        import jax.numpy as jnp

        arr0 = np.asarray(tensors[0])
        dcn_bytes0 = self.dcn.bytes_sent
        if algo is None:
            algo = self.topo.select("allreduce", arr0.nbytes)
        if algo == ALGO_HIER and self.local.world_size == 1:
            algo = ALGO_RING  # nothing to shard over
        if self.world_size == 1:
            out = self.local.allreduce(tensors, op)
            self._record_op("allreduce", algo, dcn_bytes0, arr0.dtype, quant)
            return out

        if algo == ALGO_HIER:
            # ICI tier: local reduce-scatter — device d ends with shard
            # d of the local sum (flat, 1/n_local of the elements).
            shards = self.local.reducescatter(tensors, op)
            # DCN tier: each shard crosses as its own lane (per-chip
            # NICs in hardware; sequential over one socket here), so a
            # lane's wire cost is 1/n_local of the full-array exchange.
            reduced = [
                self.dcn.allreduce(
                    np.asarray(shard), op, quant=quant,
                    error_feedback=error_feedback, algo=ALGO_RING,
                    ef_key=("hier_lane", lane, np.asarray(shard).size),
                )
                for lane, shard in enumerate(shards)
            ]
            # ICI tier: allgather — replication of the host copy is
            # free on the local tier.
            full = np.concatenate([np.asarray(s).reshape(-1)
                                   for s in reduced])
            full = full.reshape(arr0.shape).astype(arr0.dtype, copy=False)
            out_val = jnp.asarray(full)
            self._record_op("allreduce", ALGO_HIER, dcn_bytes0,
                            arr0.dtype, quant)
            return [out_val for _ in range(self.local.world_size)]

        # Legacy two-tier schedules: full local reduction, then the
        # whole array crosses DCN once per process (ring or recursive
        # doubling), then local broadcast by replication.
        local = self.local.allreduce(tensors, op)  # ICI tier
        dcn_algo = ALGO_RD if algo == ALGO_RD else ALGO_RING
        global_val = self.dcn.allreduce(
            np.asarray(local[0]), op, quant=quant,
            error_feedback=error_feedback, algo=dcn_algo,
        )
        out = jnp.asarray(global_val)
        self._record_op("allreduce", dcn_algo, dcn_bytes0, arr0.dtype, quant)
        return [out for _ in range(self.local.world_size)]

    def broadcast(self, tensors: List, root_process: int = 0,
                  root_local: int = 0) -> List:
        dcn_bytes0 = self.dcn.bytes_sent
        local = self.local.broadcast(tensors, root_local)
        if self.world_size == 1:
            self._record_op("broadcast", ALGO_RING, dcn_bytes0,
                            np.asarray(tensors[root_local]).dtype)
            return local
        global_val = self.dcn.broadcast(np.asarray(local[0]), root_process)
        import jax.numpy as jnp

        out = jnp.asarray(global_val)
        self._record_op("broadcast", ALGO_RING, dcn_bytes0, global_val.dtype)
        return [out for _ in range(self.local.world_size)]

    def allgather(self, tensors: List) -> List[List]:
        """Returns, per local device, the list of every device's tensor
        across all processes (process-major, local-device-minor order)."""
        dcn_bytes0 = self.dcn.bytes_sent
        local_lists = self.local.allgather(tensors)  # all local tensors
        if self.world_size == 1:
            self._record_op("allgather", ALGO_RING, dcn_bytes0,
                            np.asarray(tensors[0]).dtype)
            return local_lists
        stacked = np.stack([np.asarray(t) for t in local_lists[0]])
        gathered = self.dcn.allgather(stacked)  # [world][n_local, ...]
        flat = [g[i] for g in gathered for i in range(len(local_lists[0]))]
        self._record_op("allgather", ALGO_RING, dcn_bytes0, stacked.dtype)
        return [list(flat) for _ in range(self.local.world_size)]

    def reducescatter(self, tensors: List, op: ReduceOp = ReduceOp.SUM) -> List:
        """Global reduce, then each local device takes its slice of the
        process's shard (total_ranks-way split)."""
        dcn_bytes0 = self.dcn.bytes_sent
        reduced = self.allreduce(tensors, op)
        algo = self.last_op_info.get("algo", ALGO_RING)
        outs = []
        n_local = self.local.world_size
        for i in range(n_local):
            chunks = np.array_split(
                np.asarray(reduced[i]).reshape(-1), self.total_ranks
            )
            outs.append(chunks[self.rank * n_local + i])
        self._record_op("reducescatter", algo, dcn_bytes0,
                        np.asarray(tensors[0]).dtype)
        return outs

    def barrier(self):
        dcn_bytes0 = self.dcn.bytes_sent
        self.local.barrier()
        if self.world_size > 1:
            self.dcn.barrier()
        self._record_op("barrier", self.dcn.last_op_info.get("algo", ALGO_RING)
                        if self.world_size > 1 else ALGO_RING,
                        dcn_bytes0, np.dtype(np.int32))

    def destroy(self):
        self.local.destroy()
        if self.world_size > 1:
            self.dcn.destroy()
