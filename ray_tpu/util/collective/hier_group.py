"""Hierarchical collectives: XLA over local devices, DCN ring across
processes.

The multi-slice TPU topology has two bandwidth tiers: ICI within a slice
(fast, reached through XLA programs over local devices) and DCN between
slices/hosts (orders of magnitude slower). A flat cross-host ring would
push every device's data over DCN; the hierarchical schedule reduces
locally first so only ONE copy per process crosses the slow tier:

    allreduce = local XLA psum (ICI)          # n_local arrays -> 1 value
              -> DCN ring allreduce of that value across processes
              -> local broadcast of the global result (free: replication)

This is the standard two-level algorithm for multi-slice training (the
scaling-book cross-slice recipe; reference analog: NCCL's intra-node
NVLink + inter-node IB hierarchy, which NCCL performs internally — here
the two tiers are explicit because they are different transports).
"""

from __future__ import annotations

from typing import List

import numpy as np

from ray_tpu.util.collective.dcn_group import DcnGroup
from ray_tpu.util.collective.types import ReduceOp
from ray_tpu.util.collective.xla_group import XlaLocalGroup


class HierarchicalGroup:
    """Two-tier collective group.

    `world_size`/`rank` count PROCESSES (slices/hosts); each process
    contributes one tensor per local device, like XlaLocalGroup.
    """

    def __init__(self, client, world_size: int, rank: int, group_name: str,
                 num_local_devices=None, epoch: int = 0,
                 op_timeout_s=None):
        self.local = XlaLocalGroup(num_local_devices)
        self.dcn = DcnGroup(client, world_size, rank, group_name + "::dcn",
                            epoch=epoch, op_timeout=op_timeout_s)
        self.world_size = world_size
        self.rank = rank

    @property
    def total_ranks(self) -> int:
        return self.world_size * self.local.world_size

    def allreduce(self, tensors: List, op: ReduceOp = ReduceOp.SUM) -> List:
        """tensors: one per local device. Returns the GLOBAL reduction
        (across every device of every process), one copy per local
        device."""
        local = self.local.allreduce(tensors, op)  # ICI tier
        if self.world_size == 1:
            return local
        global_val = self.dcn.allreduce(np.asarray(local[0]), op)  # DCN tier
        import jax.numpy as jnp

        out = jnp.asarray(global_val)
        return [out for _ in range(self.local.world_size)]

    def broadcast(self, tensors: List, root_process: int = 0,
                  root_local: int = 0) -> List:
        local = self.local.broadcast(tensors, root_local)
        if self.world_size == 1:
            return local
        global_val = self.dcn.broadcast(np.asarray(local[0]), root_process)
        import jax.numpy as jnp

        out = jnp.asarray(global_val)
        return [out for _ in range(self.local.world_size)]

    def allgather(self, tensors: List) -> List[List]:
        """Returns, per local device, the list of every device's tensor
        across all processes (process-major, local-device-minor order)."""
        local_lists = self.local.allgather(tensors)  # all local tensors
        if self.world_size == 1:
            return local_lists
        stacked = np.stack([np.asarray(t) for t in local_lists[0]])
        gathered = self.dcn.allgather(stacked)  # [world][n_local, ...]
        flat = [g[i] for g in gathered for i in range(len(local_lists[0]))]
        return [list(flat) for _ in range(self.local.world_size)]

    def reducescatter(self, tensors: List, op: ReduceOp = ReduceOp.SUM) -> List:
        """Global reduce, then each local device takes its slice of the
        process's shard (total_ranks-way split)."""
        reduced = self.allreduce(tensors, op)
        outs = []
        n_local = self.local.world_size
        for i in range(n_local):
            chunks = np.array_split(
                np.asarray(reduced[i]).reshape(-1), self.total_ranks
            )
            outs.append(chunks[self.rank * n_local + i])
        return outs

    def barrier(self):
        self.local.barrier()
        if self.world_size > 1:
            self.dcn.barrier()

    def destroy(self):
        self.local.destroy()
        if self.world_size > 1:
            self.dcn.destroy()
