"""Collective communication API.

Mirrors the reference's python/ray/util/collective/collective.py surface
(init_collective_group :120, create_collective_group :151, allreduce :258,
barrier :298, reduce :311, broadcast :373, allgather :423, reducescatter
:472, send/recv :531/:594, GroupManager :40) with TPU-native backends:

  * Backend.DCN  — cross-process eager collectives over TCP rings with
    GCS-KV rendezvous (role of the reference's gloo backend).
  * Backend.XLA  — jit-compiled collectives over this process's local
    devices (role of the reference's nccl multi-GPU entry points).

The high-bandwidth training path does NOT use this module: gradients reduce
inside pjit-compiled programs over ICI (see ray_tpu/parallel/). This module
serves control-plane sync, weight broadcast outside jit, and CPU testing.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ray_tpu._private import worker as worker_mod
from ray_tpu.util import journal
from ray_tpu.util.collective.dcn_group import DcnGroup
from ray_tpu.util.collective.types import Backend, ReduceOp
from ray_tpu.util.collective.hier_group import HierarchicalGroup
from ray_tpu.util.collective.xla_group import XlaLocalGroup


class GroupManager:
    """Per-process registry of collective groups (reference: GroupManager
    collective.py:40)."""

    def __init__(self):
        self._groups: Dict[str, object] = {}
        self._meta: Dict[str, dict] = {}
        self._lock = threading.Lock()

    def create(self, backend: Backend, world_size: int, rank: int,
               group_name: str, epoch: int = 0,
               op_timeout_s: Optional[float] = None):
        with self._lock:
            if group_name in self._groups:
                raise ValueError(f"collective group {group_name!r} already exists")
        if backend == Backend.DCN:
            client = worker_mod.get_client()
            group = DcnGroup(client, world_size, rank, group_name,
                             epoch=epoch, op_timeout=op_timeout_s)
        elif backend == Backend.XLA:
            group = XlaLocalGroup(world_size if world_size > 0 else None)
        elif backend == Backend.HIER:
            client = worker_mod.get_client()
            group = HierarchicalGroup(client, world_size, rank, group_name,
                                      epoch=epoch, op_timeout_s=op_timeout_s)
        else:
            raise ValueError(backend)
        with self._lock:
            self._groups[group_name] = group
            self._meta[group_name] = {
                "backend": backend,
                "world_size": world_size,
                "rank": rank,
                "epoch": epoch,
            }
        return group

    def get(self, group_name: str):
        # create/destroy mutate these maps under the lock from other
        # threads (epoch bumps during fault recovery), so reads take it
        # too — a torn create must not hand out a half-registered group.
        with self._lock:
            g = self._groups.get(group_name)
        if g is None:
            raise ValueError(
                f"collective group {group_name!r} is not initialized in this "
                f"process; call init_collective_group first"
            )
        return g

    def meta(self, group_name: str) -> dict:
        with self._lock:
            return self._meta[group_name]

    def destroy(self, group_name: str):
        with self._lock:
            g = self._groups.pop(group_name, None)
            self._meta.pop(group_name, None)
        if g is not None:
            g.destroy()


_manager = GroupManager()


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "dcn",
    group_name: str = "default",
    epoch: int = 0,
    op_timeout_s: Optional[float] = None,
):
    """Join this process to a collective group (reference :120).

    epoch: gang attempt number — rendezvous is epoch-stamped so members
    of a torn-down prior attempt cannot join the rebuilt ring.
    op_timeout_s: per-op socket deadline (DCN); None uses the
    RT_COLLECTIVE_OP_TIMEOUT_S config default.
    """
    b = Backend.validate(backend)
    return _manager.create(b, world_size, rank, group_name, epoch=epoch,
                           op_timeout_s=op_timeout_s)


def create_collective_group(
    actors: List,
    world_size: int,
    ranks: List[int],
    backend: str = "dcn",
    group_name: str = "default",
    epoch: int = 0,
    timeout_s: Optional[float] = None,
):
    """Declaratively set up a group across actors (reference :151).

    Each actor must expose the reference convention of running
    `init_collective_group` inside itself; here we call a well-known
    method name via an internal task.

    epoch is forwarded to each actor's init so rendezvous keys are
    gang-epoch-stamped; epoch=0 keeps the legacy 4-arg call so actors
    written before the epoch parameter existed keep working (a gang
    that actually restarts must expose an epoch-accepting init).  The
    gather of init acks is bounded by `timeout_s` (default: the
    RT_COLLECTIVE_RENDEZVOUS_TIMEOUT_S config) — a member that never
    reaches rendezvous must surface as GetTimeoutError, not hang the
    caller forever.
    """
    import ray_tpu as rt
    from ray_tpu._private.config import get_config

    if timeout_s is None:
        timeout_s = get_config().collective_rendezvous_timeout_s

    refs = []
    for actor, rank in zip(actors, ranks):
        method = (actor._do_init_collective
                  if hasattr(actor, "_do_init_collective")
                  else actor.init_collective)
        args = (world_size, rank, backend, group_name)
        if epoch:
            refs.append(method.remote(*args, epoch=epoch))
        else:
            refs.append(method.remote(*args))
    rt.get(refs, timeout=timeout_s)


def destroy_collective_group(group_name: str = "default"):
    _manager.destroy(group_name)


def get_rank(group_name: str = "default") -> int:
    return _manager.meta(group_name)["rank"]


def get_collective_group_size(group_name: str = "default") -> int:
    return _manager.meta(group_name)["world_size"]


def _as_numpy(tensor) -> np.ndarray:
    return np.asarray(tensor)


# Collective-op observers: callables (op_name, seconds, info) invoked
# after each eager collective completes. The flight recorder registers one
# so the step profiler can attribute collective wall time per training
# step without this module importing anything from train/. `info` is the
# group's last_op_info dict ({tier, algo, bytes, dtype, quant}) for
# backends that record one, else None; legacy two-arg observers keep
# working (called without info). The timed path only runs when an
# observer is registered or the group records op info — a plain XLA-local
# op with no observers pays two checks and nothing else.
_op_observers: List = []

_metrics = None  # lazy: {bytes: Counter, seconds: Histogram}


def add_op_observer(cb) -> None:
    """Register `cb(op_name: str, seconds: float, info: Optional[dict])`
    to run after every eager collective op in this process (idempotent
    per callable). Two-arg callables are still supported."""
    if cb not in _op_observers:
        _op_observers.append(cb)


def remove_op_observer(cb) -> None:
    try:
        _op_observers.remove(cb)
    except ValueError:
        pass


def _collective_metrics():
    """collective_bytes_total / collective_op_seconds, created on the
    first instrumented op so importing this module registers nothing."""
    global _metrics
    if _metrics is None:
        from ray_tpu.util import metrics as metrics_mod

        _metrics = {
            "bytes": metrics_mod.get_or_create(
                metrics_mod.Counter,
                "collective_bytes_total",
                "Bytes eager collectives pushed on the wire, by link tier, "
                "algorithm, and element dtype.",
                tag_keys=("tier", "algo", "dtype"),
            ),
            "seconds": metrics_mod.get_or_create(
                metrics_mod.Histogram,
                "collective_op_seconds",
                "Wall time of eager collective ops.",
                boundaries=metrics_mod.LATENCY_BOUNDARIES,
                tag_keys=("op", "tier", "algo"),
            ),
        }
    return _metrics


def _emit_metrics(op_name: str, dt: float, info: Optional[dict]) -> None:
    if not info:
        return
    try:
        m = _collective_metrics()
        tier = str(info.get("tier", ""))
        algo = str(info.get("algo", ""))
        nbytes = info.get("bytes", 0)
        if nbytes:
            m["bytes"].inc(
                float(nbytes),
                tags={"tier": tier, "algo": algo,
                      "dtype": str(info.get("dtype", ""))},
            )
        m["seconds"].observe(
            dt, tags={"op": op_name, "tier": tier, "algo": algo}
        )
    except Exception:  # rtlint: disable=RT007 — metrics must never break the op
        pass


def _observed(op_name: str, fn, group=None):
    """Run fn(), reporting wall time + the group's recorded op info
    (tier/algo/bytes) to observers and the collective metrics."""
    records_info = group is not None and hasattr(group, "last_op_info")
    if not _op_observers and not records_info:
        return fn()
    t0 = time.perf_counter()
    try:
        return fn()
    finally:
        dt = time.perf_counter() - t0
        info = group.last_op_info if records_info else None
        info = dict(info) if info else None  # snapshot; {} -> None
        _emit_metrics(op_name, dt, info)
        journal.emit("collective.op", op=op_name, seconds=round(dt, 6),
                     **({k: info[k] for k in ("tier", "algo", "bytes")
                         if k in info} if info else {}))
        for cb in list(_op_observers):
            try:
                try:
                    cb(op_name, dt, info)
                except TypeError:
                    cb(op_name, dt)  # pre-info two-arg observer
            except Exception:  # rtlint: disable=RT007 — observers must never break the op
                pass


def last_op_info(group_name: str = "default") -> dict:
    """The {op, tier, algo, bytes, dtype, quant} record of the group's
    most recent eager op ({} for backends that do not record one)."""
    return dict(getattr(_manager.get(group_name), "last_op_info", None) or {})


def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM,
              quant: Optional[str] = None, error_feedback: bool = False,
              algo: Optional[str] = None):
    """In-place-style allreduce (reference :258). Returns the reduced value
    (numpy for DCN; device arrays for XLA).

    quant ("int8"/"fp8") and error_feedback quantize the DCN tier of the
    exchange (see util/collective/quant.py); algo ("ring"/"rd"/"hier")
    overrides the topology cost model's per-op choice. All three are
    DCN/hierarchical-only — the XLA-local backend reduces over ICI where
    the wire is effectively free, so asking to quantize it is an error.
    """
    g = _manager.get(group_name)
    if isinstance(g, XlaLocalGroup):
        if quant is not None or error_feedback or algo is not None:
            raise ValueError(
                "quant/error_feedback/algo apply to the DCN tier; the XLA "
                "backend is ICI-local"
            )
        return _observed("allreduce", lambda: g.allreduce(tensor, op), g)
    if isinstance(g, HierarchicalGroup):
        return _observed(
            "allreduce",
            lambda: g.allreduce(tensor, op, quant=quant,
                                error_feedback=error_feedback, algo=algo),
            g,
        )
    return _observed(
        "allreduce",
        lambda: g.allreduce(_as_numpy(tensor), op, quant=quant,
                            error_feedback=error_feedback, algo=algo),
        g,
    )


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: ReduceOp = ReduceOp.SUM):
    g = _manager.get(group_name)
    return _observed("reduce",
                     lambda: g.reduce(_as_numpy(tensor), dst_rank, op), g)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = _manager.get(group_name)
    if isinstance(g, (XlaLocalGroup, HierarchicalGroup)):
        return _observed("broadcast", lambda: g.broadcast(tensor, src_rank), g)
    return _observed("broadcast",
                     lambda: g.broadcast(_as_numpy(tensor), src_rank), g)


def allgather(tensor, group_name: str = "default"):
    g = _manager.get(group_name)
    if isinstance(g, (XlaLocalGroup, HierarchicalGroup)):
        return _observed("allgather", lambda: g.allgather(tensor), g)
    return _observed("allgather", lambda: g.allgather(_as_numpy(tensor)), g)


def reducescatter(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    g = _manager.get(group_name)
    if isinstance(g, (XlaLocalGroup, HierarchicalGroup)):
        return _observed("reducescatter",
                         lambda: g.reducescatter(tensor, op), g)
    return _observed("reducescatter",
                     lambda: g.reducescatter(_as_numpy(tensor), op), g)


def barrier(group_name: str = "default"):
    g = _manager.get(group_name)
    _observed("barrier", g.barrier, g)


def send(tensor, dst_rank: int, group_name: str = "default"):
    g = _manager.get(group_name)
    _observed("send", lambda: g.send(_as_numpy(tensor), dst_rank), g)


def recv(tensor_shape, src_rank: int, group_name: str = "default"):
    g = _manager.get(group_name)
    return _observed("recv", lambda: g.recv(src_rank), g)
