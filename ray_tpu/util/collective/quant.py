"""Quantized wire codec for the DCN collective tier.

Gradients that cross DCN pay the slow tier's bandwidth in fp32 today.
EQuARX (arXiv:2506.17615) shows block-scaled int8 AllReduce inside XLA
costs a bounded, SGD-tolerable error for a ~4x wire-byte cut; this
module is the eager-DCN analog: a numpy codec the TCP ring applies
per message, plus the error-feedback residual bookkeeping that makes
the quantization noise average out over steps (EF-SGD).

Schemes (the `quant=` argument of `collective.allreduce`):

  * "int8" — per-block absmax scaling to int8 codes (block=256 floats
    per fp32 scale: 1.56% scale overhead, ~3.9x wire reduction).
  * "fp8"  — fp8 (e4m3) codes carried on the int8 wire: same byte
    count, relative-precision rounding instead of uniform — better for
    heavy-tailed blocks. Needs ml_dtypes (ships with jax); selecting it
    without ml_dtypes raises rather than silently degrading.

The codec is reduction-safe by construction: codes are NEVER reduced —
every hop decodes to fp32, reduces in fp32, and re-encodes the partial
it forwards (the "quantize-scatter / reduce in fp32 / quantize-gather"
two-pass in dcn_group.py) — so SUM/MIN/MAX/PRODUCT all behave, and the
error per element is bounded by the per-hop rounding radius times the
hop count, independent of the values' magnitude spread across blocks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

#: Floats covered by one fp32 scale. 256 keeps the scale overhead at
#: 4/256 = 1.56% of the code bytes while isolating magnitude outliers
#: to their own block (EQuARX uses comparable block sizes).
DEFAULT_BLOCK = 256

SCHEMES = ("int8", "fp8")

_FP8_MAX = 448.0  # e4m3 finite max


def _fp8_dtype():
    try:
        import ml_dtypes

        return np.dtype(ml_dtypes.float8_e4m3fn)
    except Exception:  # rtlint: disable=RT007 — optional dep probe
        return None


def validate_scheme(scheme: str) -> str:
    if scheme not in SCHEMES:
        raise ValueError(
            f"unknown quant scheme {scheme!r}; valid: {SCHEMES}"
        )
    if scheme == "fp8" and _fp8_dtype() is None:
        raise ValueError(
            "quant='fp8' needs ml_dtypes (jax dependency) for the e4m3 "
            "code table; install it or use quant='int8'"
        )
    return scheme


@dataclass
class QuantPayload:
    """One quantized array on the wire: int8 codes + per-block fp32
    scales + enough metadata to decode on any peer."""

    scheme: str
    codes: np.ndarray        # int8, flat
    scales: np.ndarray       # float32, one per block
    shape: tuple
    dtype: str               # original dtype str, restored on decode
    block: int = DEFAULT_BLOCK

    @property
    def wire_bytes(self) -> int:
        return self.codes.nbytes + self.scales.nbytes


def encode(arr: np.ndarray, scheme: str = "int8",
           block: int = DEFAULT_BLOCK) -> QuantPayload:
    """Quantize `arr` to block-scaled codes (lossy, bounded)."""
    validate_scheme(scheme)
    a = np.ascontiguousarray(arr)
    flat = a.reshape(-1).astype(np.float32, copy=False)
    n = flat.size
    nblocks = max(1, -(-n // block))
    padded = np.zeros(nblocks * block, dtype=np.float32)
    padded[:n] = flat
    blocks = padded.reshape(nblocks, block)
    absmax = np.abs(blocks).max(axis=1)
    if scheme == "int8":
        scales = (absmax / 127.0).astype(np.float32)
        safe = np.where(scales > 0, scales, 1.0)
        codes = np.rint(blocks / safe[:, None]).clip(-127, 127).astype(np.int8)
    else:  # fp8 codes on the int8 wire
        f8 = _fp8_dtype()
        scales = (absmax / _FP8_MAX).astype(np.float32)
        safe = np.where(scales > 0, scales, 1.0)
        codes = (blocks / safe[:, None]).astype(f8).view(np.int8)
    # The pad exists only to block the scales math: truncate it off the
    # wire (at DCN chunk sizes the tail pad would eat ~10% of the win).
    return QuantPayload(
        scheme=scheme, codes=codes.reshape(-1)[:n], scales=scales,
        shape=tuple(a.shape), dtype=a.dtype.str, block=block,
    )


def decode(p: QuantPayload) -> np.ndarray:
    """Dequantize to the original shape/dtype (values in fp32 grid)."""
    if p.scheme == "int8":
        vals = p.codes.astype(np.float32)
    else:
        f8 = _fp8_dtype()
        if f8 is None:
            raise ValueError("cannot decode fp8 payload without ml_dtypes")
        vals = p.codes.view(f8).astype(np.float32)
    nblocks = p.scales.size
    if vals.size < nblocks * p.block:  # re-pad the truncated tail block
        vals = np.concatenate(
            [vals, np.zeros(nblocks * p.block - vals.size, dtype=np.float32)]
        )
    blocks = vals.reshape(nblocks, p.block) * p.scales[:, None]
    n = int(np.prod(p.shape)) if p.shape else 1
    out = blocks.reshape(-1)[:n].reshape(p.shape)
    return out.astype(np.dtype(p.dtype), copy=False)


def roundtrip_error(arr: np.ndarray, scheme: str = "int8",
                    block: int = DEFAULT_BLOCK) -> float:
    """Max abs error of one encode/decode, normalized by the array's
    absmax — the per-hop noise radius the two-pass transport multiplies
    by its hop count."""
    a = np.asarray(arr, dtype=np.float32)
    peak = float(np.abs(a).max()) if a.size else 0.0
    if peak == 0.0:
        return 0.0
    err = float(np.abs(decode(encode(a, scheme, block)) - a).max())
    return err / peak


class ErrorFeedback:
    """Per-group residual memory for EF-quantized allreduce (EF-SGD).

    Every quantization a rank performs on the wire injects a rounding
    error into the global sum. The transport reports each injection
    here (`add`); the NEXT allreduce on the same key folds the
    accumulated residual back into the input (`apply`), so the noise
    telescopes: the time-average of the quantized results converges to
    the time-average of the exact ones instead of random-walking away.
    Keyed per tensor (caller-supplied `ef_key`, e.g. the gradient
    bucket name or the hier lane index) because residuals are
    positional.

    SUM-only: folding an additive residual into MIN/MAX/PRODUCT inputs
    would corrupt them, so the transport refuses EF for other ops.
    """

    def __init__(self):
        self._residual: Dict[object, np.ndarray] = {}

    def apply(self, key: object, flat: np.ndarray) -> np.ndarray:
        """Return flat + residual[key] (fp32), claiming the residual."""
        r = self._residual.pop(key, None)
        if r is None or r.size != flat.size:
            return flat.astype(np.float32, copy=True)
        return flat.astype(np.float32) + r

    def add(self, key: object, start: int, err: np.ndarray,
            size: int) -> None:
        """Record `err` (exact - quantized) at flat offset `start` of
        the tensor known as `key` (total flat length `size`)."""
        r = self._residual.get(key)
        if r is None or r.size != size:
            r = self._residual[key] = np.zeros(size, dtype=np.float32)
        r[start:start + err.size] += err

    def residual_norm(self, key: object) -> float:
        r = self._residual.get(key)
        return float(np.abs(r).max()) if r is not None else 0.0

    def clear(self) -> None:
        self._residual.clear()
