"""Collective types.

Analog of python/ray/util/collective/types.py (Backend enum at :29-34,
ReduceOp). The reference ships NCCL and GLOO; the TPU-native backends are:

  * "xla": collectives executed by XLA over the devices attached to this
    process (ICI on a TPU host; the virtual CPU mesh in tests). Eager calls
    JIT tiny collective programs against a persistent mesh context.
  * "dcn": eager cross-process collectives over TCP rings between hosts
    (the role gloo plays for the reference's CPU path; on TPU pods this is
    the DCN control path). Rendezvous goes through the GCS KV, as the
    reference's gloo backend does (gloo_util.py:271 RayInternalKvStore).
  * "hier": two-tier composition — XLA over local devices (ICI), then a
    DCN ring across processes with ONE copy per process on the slow tier
    (the multi-slice allreduce schedule; see hier_group.py).
"""

from __future__ import annotations

from enum import Enum


class Backend(str, Enum):
    XLA = "xla"
    DCN = "dcn"
    HIER = "hier"

    @classmethod
    def validate(cls, value: str) -> "Backend":
        try:
            return cls(value)
        except ValueError:
            raise ValueError(
                f"unknown collective backend {value!r}; valid: "
                f"{[b.value for b in cls]}"
            ) from None


class ReduceOp(str, Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
