from ray_tpu.util.collective.collective import (
    allgather,
    allreduce,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    recv,
    reduce,
    reducescatter,
    send,
)
from ray_tpu.util.collective.types import Backend, ReduceOp

__all__ = [
    "init_collective_group",
    "create_collective_group",
    "destroy_collective_group",
    "allreduce",
    "allgather",
    "reducescatter",
    "broadcast",
    "reduce",
    "barrier",
    "send",
    "recv",
    "get_rank",
    "get_collective_group_size",
    "Backend",
    "ReduceOp",
]
